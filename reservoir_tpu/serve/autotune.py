"""Service-knob autotuner: offline sweep cache + online SLO closed loop.

Kernel geometry closed its tuning loop in :mod:`reservoir_tpu.ops.autotune`
— measure on live hardware, persist the winner, consume it at construction.
This module does the same for the *serving plane's* knobs
(``coalesce_bytes``, ``max_inflight_bytes``, ``checkpoint_every``,
``sweep_interval_s``, ``gate_push_chunk``), whose winners depend on the
workload, not just the device: arrival rate sets how fast the coalesce
buffer fills, key skew sets the session-churn and snapshot mix, and the
SLO verdicts are the ground truth for "too far".  Two coupled halves:

- **Offline sweep** (``tools/serve_knob_sweep.py`` drives it): candidates
  are scored lexicographically — no SLO page > no warn > max effective
  elem/s > min ingest p99 — against live loadgen traffic, and the winner
  is persisted under a *workload fingerprint* key
  (``serve|device|R|k|mode|gated|rate-band|zipf-band``) in the SAME
  atomic JSON store the kernel sweeps use (:func:`ops.autotune.record_raw`
  is the extension surface; schema 3).  :class:`ReservoirService` consumes
  the cached winner at construction exactly the way the engine consumes
  kernel geometry — explicit kwargs always win, absent cache = builtin
  defaults, byte-identical behavior either way.

- **Online controller** (:class:`ServiceTuner`): subscribes to the
  :class:`~reservoir_tpu.obs.slo.SLOPlane` burn verdicts and nudges the
  live knobs inside declared safe bounds with AIMD-style hysteresis —
  multiplicative backoff toward each knob's safe end on warn-level burn,
  additive re-probe toward the cached optimum after a healthy dwell.
  Every decision is journaled as a structured event, traced as a
  ``tune.decide`` span, and surfaced through ``tune.*`` instruments
  (``reservoir_top`` renders them); all of it is zero-overhead when
  telemetry is disabled and fully absent when no tuner is attached (the
  trip-wire discipline of :mod:`reservoir_tpu.obs`).

The controller never touches durability: knob nudges change *when* bytes
ship and state checkpoints, never what is sampled — the same
advisory-only guarantee the kernel-geometry cache gives (a stale entry
can cost speed, never correctness).
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, NamedTuple, Optional, Tuple

from ..obs import registry as _obs
from ..obs import trace as _trace
from ..ops import autotune as _store

__all__ = [
    "ServiceKnobs",
    "DEFAULT_KNOBS",
    "KnobBounds",
    "DEFAULT_BOUNDS",
    "SAFE_END",
    "device_kind_of",
    "rate_band",
    "zipf_band",
    "make_serve_key",
    "lookup_knobs",
    "record_knobs",
    "TuneDecision",
    "ServiceTuner",
]


class ServiceKnobs(NamedTuple):
    """One complete serving-knob assignment.

    ``sweep_interval_s=0.0`` means manual-only sweeps (the service's
    ``None``); ``gate_push_chunk=0`` defers to the bridge's own resolution
    (gate-geometry cache, 1 Mi fallback).  Both zeros survive the JSON
    round-trip, which is why the sentinel is numeric here rather than
    ``None``."""

    coalesce_bytes: int
    max_inflight_bytes: int
    checkpoint_every: int
    sweep_interval_s: float
    gate_push_chunk: int


#: The service's hardcoded constructor defaults, as a knob vector — the
#: A side of every ``bench.py tune`` A/B and the sweep's always-included
#: baseline candidate (the cached winner can therefore never lose to it).
DEFAULT_KNOBS = ServiceKnobs(
    coalesce_bytes=1 << 16,
    max_inflight_bytes=1 << 24,
    checkpoint_every=64,
    sweep_interval_s=0.0,
    gate_push_chunk=0,
)

#: Which end of a knob's range is the SAFE end under latency burn:
#: smaller coalesce/admission/push-chunk = shed earlier + smaller device
#: dispatches; larger checkpoint/sweep cadence = less background work on
#: the ingest path.
SAFE_END = {
    "coalesce_bytes": "lo",
    "max_inflight_bytes": "lo",
    "checkpoint_every": "hi",
    "sweep_interval_s": "hi",
    "gate_push_chunk": "lo",
}


@dataclass(frozen=True)
class KnobBounds:
    """Declared safe range per knob — the controller clamps every nudge
    into these, so a pathological burn signal can degrade throughput but
    never push a knob somewhere the service was not designed to run."""

    coalesce_bytes: Tuple[int, int] = (1 << 12, 1 << 22)
    max_inflight_bytes: Tuple[int, int] = (1 << 16, 1 << 28)
    checkpoint_every: Tuple[int, int] = (8, 1024)
    sweep_interval_s: Tuple[float, float] = (0.05, 30.0)
    gate_push_chunk: Tuple[int, int] = (1 << 12, 1 << 22)

    def clamp(self, name: str, value):
        lo, hi = getattr(self, name)
        return min(hi, max(lo, value))


DEFAULT_BOUNDS = KnobBounds()


# --------------------------------------------------------------- fingerprint


def device_kind_of(device: Optional[Any] = None) -> str:
    """The ``device_kind`` string the cache keys on — the pinned device's
    when given, the default backend's otherwise, ``"cpu"`` when no backend
    is reachable (construction must never fail on a lookup)."""
    try:
        if device is not None:
            return str(device.device_kind)
        import jax

        return str(jax.devices()[0].device_kind)
    except Exception:
        return "cpu"


def rate_band(rate: Optional[float]) -> str:
    """Decade band of the offered arrival rate (``1e3`` = [1000, 10000)),
    ``any`` when unknown — knob winners are stable within an order of
    magnitude of load, not at one exact rate."""
    if rate is None or rate <= 0:
        return "any"
    return f"1e{int(math.floor(math.log10(rate)))}"


def zipf_band(zipf_s: Optional[float]) -> str:
    """Key-skew band: the Zipf exponent rounded to the nearest 0.5
    (``1.0`` covers s in [0.75, 1.25)), ``any`` when unknown."""
    if zipf_s is None or zipf_s < 0:
        return "any"
    return f"{round(zipf_s * 2) / 2:.1f}"


def make_serve_key(
    device_kind: str,
    R: int,
    k: int,
    mode: str,
    gated: bool,
    rate: Optional[float] = None,
    zipf_s: Optional[float] = None,
) -> str:
    """Workload-fingerprint cache key for a serve-knob entry.  ``mode`` is
    ``plain`` / ``weighted`` / ``distinct`` (what the sessions sample);
    rate/skew land in coarse bands so one sweep covers a neighborhood."""
    if mode not in ("plain", "weighted", "distinct"):
        raise ValueError(f"unknown service mode {mode!r}")
    return (
        f"serve|{device_kind}|R={R}|k={k}|mode={mode}"
        f"|gated={int(bool(gated))}"
        f"|rate={rate_band(rate)}|zipf={zipf_band(zipf_s)}"
    )


def lookup_knobs(
    device_kind: str,
    R: int,
    k: int,
    mode: str,
    gated: bool,
    rate: Optional[float] = None,
    zipf_s: Optional[float] = None,
    path: Optional[str] = None,
) -> Optional[ServiceKnobs]:
    """The tuned knob vector for this workload fingerprint, or ``None``
    (keep the builtin defaults).  Falls back from the exact rate/skew
    bands to the ``any`` entry, so a service constructed without a
    traffic forecast still gets the sweep's overall winner."""
    data = _store.load(path)
    for key in (
        make_serve_key(device_kind, R, k, mode, gated, rate, zipf_s),
        make_serve_key(device_kind, R, k, mode, gated, None, None),
    ):
        entry = data.get(key)
        if isinstance(entry, dict):
            try:
                return ServiceKnobs(
                    coalesce_bytes=int(entry["coalesce_bytes"]),
                    max_inflight_bytes=int(entry["max_inflight_bytes"]),
                    checkpoint_every=int(entry["checkpoint_every"]),
                    sweep_interval_s=float(
                        entry.get("sweep_interval_s", 0.0)
                    ),
                    gate_push_chunk=int(entry.get("gate_push_chunk", 0)),
                )
            except (KeyError, TypeError, ValueError):
                return None
    return None


def record_knobs(
    device_kind: str,
    R: int,
    k: int,
    mode: str,
    gated: bool,
    knobs: ServiceKnobs,
    rate: Optional[float] = None,
    zipf_s: Optional[float] = None,
    elem_per_sec: Optional[float] = None,
    ingest_p99_s: Optional[float] = None,
    source: Optional[str] = None,
    path: Optional[str] = None,
) -> str:
    """Persist one swept winner under its workload fingerprint (atomic
    merge into the shared store; kernel-geometry entries untouched).
    Returns the key written.  Provenance rides along like the kernel
    entries' ``elem_per_sec``/``source``."""
    knobs = ServiceKnobs(*knobs)
    entry = {
        "coalesce_bytes": int(knobs.coalesce_bytes),
        "max_inflight_bytes": int(knobs.max_inflight_bytes),
        "checkpoint_every": int(knobs.checkpoint_every),
        "sweep_interval_s": float(knobs.sweep_interval_s),
        "gate_push_chunk": int(knobs.gate_push_chunk),
    }
    if elem_per_sec is not None:
        entry["elem_per_sec"] = float(elem_per_sec)
    if ingest_p99_s is not None:
        entry["ingest_p99_s"] = float(ingest_p99_s)
    if source is not None:
        entry["source"] = source
    key = make_serve_key(device_kind, R, k, mode, gated, rate, zipf_s)
    _store.record_raw(key, entry, path)
    return key


def service_fingerprint(service: Any) -> Tuple[str, int, int, str, bool]:
    """The (device_kind, R, k, mode, gated) slice of a live service's
    workload fingerprint — what construction-time lookup and the sweep
    tool both key on."""
    config = service.config
    mode = (
        "weighted"
        if config.weighted
        else "distinct" if config.distinct else "plain"
    )
    return (
        device_kind_of(service.device),
        int(config.num_reservoirs),
        int(config.max_sample_size),
        mode,
        bool(getattr(service.bridge, "gate_active", False)),
    )


# ------------------------------------------------------------ online control


@dataclass
class TuneDecision:
    """One controller step, journaled: what the plane said, what the
    controller did, and the knob vector it left behind."""

    at: float
    verdict: str
    action: str  # "backoff" | "probe" | "hold"
    knobs: ServiceKnobs
    healthy_streak: int


class ServiceTuner:
    """SLO-closed-loop knob controller (AIMD with hysteresis).

    Attach one per service: ``ServiceTuner(service, plane)`` registers
    itself via :meth:`ReservoirService.attach_tuner`, after which the
    ingest hot path calls :meth:`maybe_observe` — one ``None`` test plus
    a clock read per accepted ingest, a full evaluation at most every
    ``interval_s``.  The control law:

    - **warn/page burn** → multiplicative backoff: every active knob
      moves toward its :data:`SAFE_END` by ``backoff_factor`` (halving /
      doubling at the default 0.5), clamped into ``bounds``.  The healthy
      streak resets — one bad window is enough to retreat.
    - **ok** for ``healthy_dwell`` consecutive evaluations →
      additive re-probe: every knob steps a ``probe_step`` fraction of
      its remaining distance back toward ``optimum`` (the cached sweep
      winner, or the knobs at attach time).  Hysteresis: backoff is
      immediate and large, recovery is dwelled and small, so an
      oscillating signal parks the knobs near the safe end instead of
      thrashing.

    Knobs that are inert for this service (sweep cadence without a TTL,
    gate push chunk on an ungated bridge) are never touched.  Decisions
    land in :attr:`decisions` (bounded), the ``tune.decide`` event/span,
    and ``tune.*`` gauges — all zero-overhead while telemetry is off.
    """

    def __init__(
        self,
        service: Any,
        plane: Any,
        *,
        optimum: Optional[ServiceKnobs] = None,
        bounds: Optional[KnobBounds] = None,
        backoff_factor: float = 0.5,
        probe_step: float = 0.25,
        healthy_dwell: int = 2,
        interval_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        max_decisions: int = 256,
        attach: bool = True,
    ) -> None:
        if not 0.0 < backoff_factor < 1.0:
            raise ValueError("backoff_factor must be in (0, 1)")
        if not 0.0 < probe_step <= 1.0:
            raise ValueError("probe_step must be in (0, 1]")
        if healthy_dwell < 1:
            raise ValueError("healthy_dwell must be >= 1")
        self._service = service
        self._plane = plane
        self._bounds = bounds if bounds is not None else DEFAULT_BOUNDS
        self._backoff = float(backoff_factor)
        self._probe = float(probe_step)
        self._dwell = int(healthy_dwell)
        self._interval_s = float(interval_s)
        self._clock = clock
        live = ServiceKnobs(*service.live_knobs())
        self._optimum = (
            ServiceKnobs(*optimum) if optimum is not None else live
        )
        # inert knobs stay untouched: no TTL = no sweep cadence to tune,
        # ungated bridge = the push chunk never slices anything
        active = ["coalesce_bytes", "max_inflight_bytes", "checkpoint_every"]
        if service.table.ttl_s is not None and (
            live.sweep_interval_s > 0 or self._optimum.sweep_interval_s > 0
        ):
            active.append("sweep_interval_s")
        if getattr(service.bridge, "gate_active", False):
            active.append("gate_push_chunk")
        self._active = tuple(active)
        self._healthy_streak = 0
        self._last_eval = -math.inf
        self.decisions: Deque[TuneDecision] = deque(maxlen=max_decisions)
        self.backoffs = 0
        self.probes = 0
        if attach:
            service.attach_tuner(self)

    # ------------------------------------------------------------- observe

    @property
    def optimum(self) -> ServiceKnobs:
        return self._optimum

    def maybe_observe(
        self, now: Optional[float] = None
    ) -> Optional[TuneDecision]:
        """Rate-limited hot-path hook: a full :meth:`observe` at most
        every ``interval_s``, else nothing (one clock read)."""
        now = self._clock() if now is None else now
        if now - self._last_eval < self._interval_s:
            return None
        return self.observe(now)

    def observe(self, now: Optional[float] = None) -> TuneDecision:
        """Evaluate the SLO plane and take one control step; returns the
        journaled decision."""
        now = self._clock() if now is None else now
        self._last_eval = now
        tr = _trace.get()
        if tr is not None:
            with tr.span("tune.decide"):
                return self._decide(now)
        return self._decide(now)

    def _decide(self, now: float) -> TuneDecision:
        self._plane.evaluate(now)
        verdict = self._plane.worst()
        live = ServiceKnobs(*self._service.live_knobs())
        if verdict in ("warn", "page"):
            self._healthy_streak = 0
            target = self._backoff_from(live)
            action = "backoff" if target != live else "hold"
        else:
            self._healthy_streak += 1
            if self._healthy_streak >= self._dwell:
                target = self._probe_from(live)
                action = "probe" if target != live else "hold"
            else:
                target, action = live, "hold"
        if action != "hold":
            self._service.apply_knobs(target)
            if action == "backoff":
                self.backoffs += 1
            else:
                self.probes += 1
        decision = TuneDecision(
            at=now,
            verdict=verdict,
            action=action,
            knobs=target,
            healthy_streak=self._healthy_streak,
        )
        self.decisions.append(decision)
        self._instrument(decision)
        return decision

    # ------------------------------------------------------------ control law

    def _backoff_from(self, live: ServiceKnobs) -> ServiceKnobs:
        """Multiplicative retreat: every active knob toward its safe end
        by ``backoff_factor``, clamped into bounds."""
        out = live._asdict()
        for name in self._active:
            cur = out[name]
            if name == "gate_push_chunk" and cur == 0:
                continue  # bridge-resolved: nothing concrete to halve yet
            if SAFE_END[name] == "lo":
                nxt = cur * self._backoff
            else:
                nxt = cur / self._backoff
            nxt = self._bounds.clamp(name, nxt)
            out[name] = type(cur)(nxt) if isinstance(cur, int) else float(nxt)
        knobs = ServiceKnobs(**out)
        # the pair constraint survives every nudge
        if knobs.coalesce_bytes > knobs.max_inflight_bytes:
            out["coalesce_bytes"] = out["max_inflight_bytes"]
            knobs = ServiceKnobs(**out)
        return knobs

    def _probe_from(self, live: ServiceKnobs) -> ServiceKnobs:
        """Additive recovery: every active knob a ``probe_step`` fraction
        of its remaining distance toward the optimum (at least one unit,
        never overshooting)."""
        out = live._asdict()
        opt = self._optimum._asdict()
        for name in self._active:
            cur, goal = out[name], opt[name]
            if cur == goal:
                continue
            if isinstance(cur, int):
                step = max(1, int(round(abs(goal - cur) * self._probe)))
                nxt = cur + step if goal > cur else cur - step
                nxt = min(nxt, goal) if goal > cur else max(nxt, goal)
            else:
                nxt = cur + (goal - cur) * self._probe
                if abs(goal - nxt) < 1e-9:
                    nxt = goal
            out[name] = self._bounds.clamp(name, nxt) if nxt != goal else goal
        knobs = ServiceKnobs(**out)
        if knobs.coalesce_bytes > knobs.max_inflight_bytes:
            out["coalesce_bytes"] = out["max_inflight_bytes"]
            knobs = ServiceKnobs(**out)
        return knobs

    # ------------------------------------------------------------- telemetry

    def _instrument(self, decision: TuneDecision) -> None:
        """Structured journal + gauges for one decision — one global load
        and a ``None`` test when telemetry is disabled (trip-wire)."""
        reg = _obs.get()
        if reg is not None:
            for name, value in decision.knobs._asdict().items():
                reg.gauge(f"tune.{name}").set(float(value))
            reg.gauge("tune.healthy_streak").set(
                float(decision.healthy_streak)
            )
            if decision.action == "backoff":
                reg.counter("tune.backoffs").inc()
            elif decision.action == "probe":
                reg.counter("tune.probes").inc()
        _obs.emit(
            "tune.decide",
            site="serve.tune",
            verdict=decision.verdict,
            action=decision.action,
            **{
                f"knob_{k}": v
                for k, v in decision.knobs._asdict().items()
            },
        )
