"""Multi-tenant serving plane over the engine/bridge stack (SURVEY §7.3's
"millions of users" row made executable).

Everything below this package is single-owner: a
:class:`~reservoir_tpu.stream.bridge.DeviceStreamBridge` binds a fixed row
layout at construction and ``result()``/``complete()`` are destructive
one-shot reads.  The serve layer adds the missing multiplexing plane:

- :mod:`.sessions` — a :class:`~reservoir_tpu.serve.sessions.SessionTable`
  leasing reservoir rows of the batched engine to opaque session keys
  (open/route/close, TTL + LRU eviction, generation counters so a recycled
  row can never serve a stale read, counter-keyed Threefry sub-seeds so
  recycled rows are statistically fresh without reseeding the engine);
- :mod:`.service` — a :class:`~reservoir_tpu.serve.service.ReservoirService`
  front-end: per-session ingest coalesced across sessions into the bridge's
  interleaved tile path, admission control (bounded in-flight bytes,
  reject-with-retry-after), live non-destructive snapshot queries served
  from a ``flushed_seq``-keyed device->host cache, and crash recovery that
  rebuilds the session table from a journaled session map.
"""

from .service import ReservoirService
from .sessions import Session, SessionTable

__all__ = ["ReservoirService", "Session", "SessionTable"]
