"""Multi-tenant serving plane over the engine/bridge stack (SURVEY §7.3's
"millions of users" row made executable).

Everything below this package is single-owner: a
:class:`~reservoir_tpu.stream.bridge.DeviceStreamBridge` binds a fixed row
layout at construction and ``result()``/``complete()`` are destructive
one-shot reads.  The serve layer adds the missing multiplexing plane:

- :mod:`.sessions` — a :class:`~reservoir_tpu.serve.sessions.SessionTable`
  leasing reservoir rows of the batched engine to opaque session keys
  (open/route/close, TTL + LRU eviction, generation counters so a recycled
  row can never serve a stale read, counter-keyed Threefry sub-seeds so
  recycled rows are statistically fresh without reseeding the engine);
- :mod:`.service` — a :class:`~reservoir_tpu.serve.service.ReservoirService`
  front-end: per-session ingest coalesced across sessions into the bridge's
  interleaved tile path, admission control (bounded in-flight bytes,
  reject-with-retry-after), live non-destructive snapshot queries served
  from a ``flushed_seq``-keyed device->host cache, and crash recovery that
  rebuilds the session table from a journaled session map;
- :mod:`.autotune` — the SLO-closed-loop knob plane (ISSUE 14): a
  workload-fingerprinted persistent cache of swept service-knob winners
  (same atomic JSON store as the kernel-geometry autotuner; consumed at
  construction, explicit kwargs winning) plus a
  :class:`~reservoir_tpu.serve.autotune.ServiceTuner` that nudges the
  live knobs inside declared safe bounds with AIMD hysteresis, driven by
  the :class:`~reservoir_tpu.obs.slo.SLOPlane` burn verdicts;
- :mod:`.replica` / :mod:`.ha` — the high-availability plane (ISSUE 5): a
  :class:`~reservoir_tpu.serve.replica.StandbyReplica` tails the primary's
  flush journal into a warm, bit-identical replica
  (:class:`~reservoir_tpu.serve.replica.JournalFollower` is the resumable
  CRC-checked byte-cursor tail), and a
  :class:`~reservoir_tpu.serve.ha.FailoverController` watches the
  primary's heartbeat/health signals
  (:class:`~reservoir_tpu.serve.ha.HeartbeatWriter`) and performs
  **epoch-fenced** promotion — the fenced old primary fails its next
  durable write with :class:`~reservoir_tpu.errors.FencedError` instead
  of double-serving;
- :mod:`.shard` / :mod:`.cluster` — the sharded serving plane (ISSUE 9):
  a :class:`~reservoir_tpu.serve.cluster.ShardedReservoirService` fronts
  N fully independent :class:`~reservoir_tpu.serve.shard.ShardUnit`
  failure domains (engine + bridge + journal dir + epoch fence + hot
  standby each) behind deterministic hash routing with a pinned,
  journaled routing epoch — one fenced/killed/saturated shard rejects
  only its own sessions (:class:`~reservoir_tpu.errors.ShardUnavailable`
  with ``retry_after_s``) while the rest keep serving, and cross-shard
  merged snapshots ride the exact mergeable-reservoir math
  (:func:`~reservoir_tpu.parallel.merge.merge_samples_host`).
"""

from .autotune import (
    DEFAULT_KNOBS,
    KnobBounds,
    ServiceKnobs,
    ServiceTuner,
    TuneDecision,
    lookup_knobs,
    record_knobs,
)
from .cluster import ShardedReservoirService, shard_of
from .ha import FailoverController, HealthReport, HeartbeatWriter, read_heartbeat
from .replica import JournalFollower, StandbyReplica
from .service import ReservoirService
from .sessions import Session, SessionTable
from .shard import ShardUnit

__all__ = [
    "ReservoirService",
    "ServiceKnobs",
    "ServiceTuner",
    "TuneDecision",
    "KnobBounds",
    "DEFAULT_KNOBS",
    "lookup_knobs",
    "record_knobs",
    "Session",
    "SessionTable",
    "ShardUnit",
    "ShardedReservoirService",
    "shard_of",
    "StandbyReplica",
    "JournalFollower",
    "FailoverController",
    "HeartbeatWriter",
    "HealthReport",
    "read_heartbeat",
]
