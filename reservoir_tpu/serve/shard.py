"""One shard = one fully independent failure domain of the serving plane.

ROADMAP item 1 partitions the serving plane across shards; what makes the
partition a *robustness* win (ISSUE 9) is that each shard is its own
complete availability stack, with nothing shared: its own engine + bridge,
its own checkpoint/journal directory, its own epoch fence, its own
:class:`~reservoir_tpu.serve.ha.HeartbeatWriter` beacon, and (optionally)
its own hot :class:`~reservoir_tpu.serve.replica.StandbyReplica` under a
shard-scoped :class:`~reservoir_tpu.serve.ha.FailoverController`.  A
Pallas demotion, wedged flush pipeline, or fence loss on shard 3 is shard
3's outage — the PR-5 HA machinery runs per shard instead of
whole-world.

:class:`ShardUnit` is that bundle, factored out of
:class:`~reservoir_tpu.serve.cluster.ShardedReservoirService` so a
single-shard deployment and an N-shard cluster are the same code: the
cluster is N units plus routing.  The unit owns the lifecycle levers the
chaos soak (and an operator) pulls:

- :meth:`kill` — simulate a primary crash (no shutdown, no flush; the
  zombie is kept for fence probes);
- :meth:`promote` — epoch-fenced standby promotion (fires the
  ``shard.promote`` fault site; an injected failure leaves the standby
  un-promoted and re-promotable), then re-arms a fresh standby +
  controller tailing the new primary;
- :meth:`recover` — stop-the-world :meth:`ReservoirService.recover` from
  the shard's own directory (the no-standby path), with the ISSUE-9
  pre-flight: a lineage fenced by a promotion fails typed, before replay;
- :meth:`beat` / :meth:`health` / :meth:`maybe_promote` — the per-shard
  heartbeat/health loop, verdicts carrying the ISSUE-9 trigger tags.

Telemetry is shard-scoped end to end: the unit's service records its
``serve.*`` instruments under ``@shard<i>`` labels
(:func:`~reservoir_tpu.obs.registry.scoped`) and :meth:`slo_verdicts`
judges them with a per-shard :class:`~reservoir_tpu.obs.slo.SLOPlane`
(``attach=False`` — N planes must not fight over the registry's one
export slot), so one saturated shard pages alone.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Dict, Optional

from ..config import SamplerConfig
from ..errors import RetryPolicy
from ..obs import flight as _flight
from ..obs import registry as _obs
from ..obs import trace as _ctrace
from ..utils import faults as _faults
from ..utils.checkpoint import advance_epoch, read_epoch
from .ha import FailoverController, HealthReport, HeartbeatWriter
from .replica import StandbyReplica
from .service import ReservoirService

__all__ = ["ShardUnit"]


class ShardUnit:
    """One shard's primary + beacon + (optional) hot standby, as a unit.

    Args:
      config: the shard's engine config (``num_reservoirs`` = this
        shard's session capacity; the cluster's total capacity is
        ``n_shards * num_reservoirs``).
      shard_id: this shard's index (names its obs scope and directory).
      checkpoint_dir: this shard's OWN durability directory — never
        shared with another shard; the whole failure-domain story rests
        on that.
      key: engine PRNG seed for this shard (the cluster derives one per
        shard; kept on :attr:`engine_seed` for oracle replays).
      standby: keep a hot :class:`StandbyReplica` tailing the journal
        (with a :class:`FailoverController` over the heartbeat).
        ``False`` = recover-in-place only.
      heartbeat_timeout_s / max_watchdog_trips / max_demotions /
        max_rejections: forwarded to the shard's controller.
      clock: controller/heartbeat time source (injectable for tests).
      obs_scope: instrument label (default ``shard<i>``).
      slo_kwargs: overrides for this shard's
        :func:`~reservoir_tpu.obs.slo.default_slos` objectives (e.g.
        ``{"staleness_s": 30.0}``) — thresholds are deployment knobs, the
        scoping is not.
      faults: fault plane for this unit's sites (``shard.promote`` fires
        here; the cluster fires ``shard.route``).
      device: pin this shard's engine state to one ``jax.Device``
        (threaded into the service, and re-applied on :meth:`recover`).
        ``None`` keeps the backend default placement.
      **service_kwargs: forwarded to :class:`ReservoirService`
        (``ttl_s``, ``coalesce_bytes``, ``gated``, ``durability``, ...).
    """

    def __init__(
        self,
        config: SamplerConfig,
        shard_id: int,
        checkpoint_dir: str,
        *,
        key: Any = None,
        standby: bool = True,
        heartbeat_timeout_s: float = 5.0,
        max_watchdog_trips: int = 0,
        max_demotions: Optional[int] = None,
        max_rejections: Optional[int] = None,
        clock=time.time,
        obs_scope: Optional[str] = None,
        slo_kwargs: Optional[dict] = None,
        faults: Optional[Any] = None,
        retry_policy: Optional[RetryPolicy] = None,
        device: Optional[Any] = None,
        _service: Optional[ReservoirService] = None,
        **service_kwargs: Any,
    ) -> None:
        self.shard_id = int(shard_id)
        self.checkpoint_dir = checkpoint_dir
        self.engine_seed = key
        self.device = device
        self._config = config
        self._standby_enabled = bool(standby)
        self._clock = clock
        self._faults = faults
        self._obs_scope = (
            obs_scope if obs_scope is not None else f"shard{self.shard_id}"
        )
        self._slo_kwargs = dict(slo_kwargs or {})
        self._ctl_kwargs = dict(
            heartbeat_timeout_s=heartbeat_timeout_s,
            max_watchdog_trips=max_watchdog_trips,
            max_demotions=max_demotions,
            max_rejections=max_rejections,
        )
        self._service_kwargs = dict(service_kwargs)
        self._service_kwargs.setdefault("retry_policy", retry_policy)
        if device is not None:
            self._service_kwargs["device"] = device
        if _service is not None:
            # adoption path (cluster recover): the service was rebuilt by
            # ReservoirService.recover and already owns the directory
            self._service: Optional[ReservoirService] = _service
            _service._obs_scope = self._obs_scope
        else:
            os.makedirs(checkpoint_dir, exist_ok=True)
            self._service = ReservoirService(
                config,
                key=key,
                checkpoint_dir=checkpoint_dir,
                obs_scope=self._obs_scope,
                faults=faults,
                **self._service_kwargs,
            )
        self.last_zombie: Optional[ReservoirService] = None
        self._unavailable_reason: Optional[str] = None
        self._slo_plane = None
        self._hb: Optional[HeartbeatWriter] = None
        self._standby: Optional[StandbyReplica] = None
        self._controller: Optional[FailoverController] = None
        self._arm()

    # ------------------------------------------------------------ properties

    @property
    def alive(self) -> bool:
        """Whether this shard has a live primary (killed/fenced = False)."""
        return self._service is not None

    @property
    def unavailable_reason(self) -> Optional[str]:
        """Why the shard is down (``killed`` / ``fenced``), None while up."""
        return self._unavailable_reason

    @property
    def service(self) -> ReservoirService:
        if self._service is None:
            raise RuntimeError(
                f"shard {self.shard_id} has no live primary "
                f"({self._unavailable_reason}); promote() or recover() first"
            )
        return self._service

    @property
    def table(self):
        return self.service.table

    @property
    def standby(self) -> Optional[StandbyReplica]:
        return self._standby

    @property
    def controller(self) -> Optional[FailoverController]:
        return self._controller

    @property
    def obs_scope(self) -> str:
        return self._obs_scope

    @property
    def epoch(self) -> int:
        """The persisted fence epoch of this shard's directory."""
        return read_epoch(self.checkpoint_dir)

    # --------------------------------------------------------------- arming

    def _arm(self) -> None:
        """(Re-)attach the beacon and, when enabled, a fresh standby +
        controller tailing the CURRENT primary.  Called at construction
        and after every promote/recover — the old standby's service
        identity is stale either way."""
        if self._service is None:
            return
        self._hb = HeartbeatWriter(
            self.checkpoint_dir,
            service=self._service,
            clock=self._clock,
            faults=self._faults,
        )
        if self._standby_enabled:
            self._standby = StandbyReplica(
                self.checkpoint_dir, faults=self._faults
            )
            self._controller = FailoverController(
                self._standby,
                clock=self._clock,
                faults=self._faults,
                **self._ctl_kwargs,
            )

    # -------------------------------------------------------------- levers

    def kill(self) -> ReservoirService:
        """Simulate a primary crash: drop the service with NO shutdown or
        flush (pending coalesced elements die with it, exactly the crash
        contract).  The zombie is kept on :attr:`last_zombie` so chaos
        tests can probe the fence; the standby (if any) keeps tailing the
        journal and is ready to promote."""
        zombie = self.service
        self.last_zombie = zombie
        self._service = None
        self._hb = None
        self._unavailable_reason = "killed"
        _obs.emit(
            "shard.killed", site="shard.promote", shard=self.shard_id
        )
        tr = _ctrace.get()
        if tr is not None:
            tr.point(
                "shard.killed",
                shard=self.shard_id,
                flush_seq=zombie.flushed_seq,
            )
        return zombie

    def fence(self) -> int:
        """Advance the shard's persisted epoch WITHOUT promoting — the
        split-brain chaos lever: the current primary's next durable write
        fails with :class:`~reservoir_tpu.errors.FencedError`."""
        return advance_epoch(self.checkpoint_dir)

    def mark_fenced(self) -> None:
        """Record that the primary hit its fence (the cluster calls this
        when a delegated call raises ``FencedError``): the shard rejects
        with ``retry_after`` until promoted/recovered."""
        if self._service is not None:
            self.last_zombie = self._service
        self._service = None
        self._hb = None
        self._unavailable_reason = "fenced"

    def promote(
        self, reason: str = "manual", triggers: Optional[list] = None
    ) -> ReservoirService:
        """Epoch-fenced failover onto this shard's hot standby; the
        ``shard.promote`` fault site fires first, so an injected failure
        leaves the standby un-promoted (and this method re-callable).
        Re-arms a fresh beacon + standby + controller on success."""
        if self._standby is None:
            raise RuntimeError(
                f"shard {self.shard_id} has no standby to promote"
            )
        _faults.fire("shard.promote", self._faults)
        if self._service is not None:
            # promoting over a live primary: it becomes the fenced zombie
            self.last_zombie = self._service
        assert self._controller is not None
        tr = _ctrace.get()
        if tr is None:
            promoted = self._controller.promote(
                reason=reason, triggers=triggers
            )
        else:
            with tr.span(
                "shard.promote",
                force=True,
                shard=self.shard_id,
                reason=reason,
            ) as span:
                promoted = self._controller.promote(
                    reason=reason, triggers=triggers
                )
                if span is not None:
                    span.fields["flush_seq"] = promoted.flushed_seq
                    span.fields["epoch"] = self.epoch
        promoted._obs_scope = self._obs_scope
        self._service = promoted
        self._unavailable_reason = None
        self._arm()
        return promoted

    def recover(self, **kwargs: Any) -> ReservoirService:
        """Stop-the-world rebuild from this shard's own directory
        (:meth:`ReservoirService.recover`), with the ISSUE-9 pre-flight:
        a lineage fenced by a promotion raises
        :class:`~reservoir_tpu.errors.CheckpointMismatch` before replay.
        Re-arms the beacon/standby on success."""
        fwd = {
            k: self._service_kwargs[k]
            for k in (
                "ttl_s", "coalesce_bytes", "max_inflight_bytes",
                "retry_after_s", "sweep_interval_s", "auditor",
                "retry_policy", "flush_timeout_s", "checkpoint_every",
                "durability", "pipelined", "device",
            )
            if k in self._service_kwargs
        }
        fwd.update(kwargs)
        tr = _ctrace.get()
        cm = (
            tr.span("shard.recover", force=True, shard=self.shard_id)
            if tr is not None
            else contextlib.nullcontext()
        )
        with cm as span:
            service = ReservoirService.recover(
                self.checkpoint_dir,
                obs_scope=self._obs_scope,
                faults=self._faults,
                **fwd,
            )
            if span is not None:
                span.fields["flush_seq"] = service.flushed_seq
                span.fields["epoch"] = self.epoch
        fl = _flight.get()
        if fl is not None:
            fl.note(
                "shard.recovered",
                shard=self.shard_id,
                flush_seq=service.flushed_seq,
                epoch=self.epoch,
            )
        self._service = service
        self._unavailable_reason = None
        self._arm()
        return service

    # ------------------------------------------------------- health plane

    def beat(self) -> Optional[dict]:
        """One heartbeat of the live primary (None while the shard is
        down — a dead shard must look dead, not quietly skipped)."""
        if self._hb is None:
            return None
        return self._hb.beat()

    def poll(self) -> int:
        """One standby replication step (0 when no standby)."""
        if self._standby is None:
            return 0
        return self._standby.poll()

    def health(self) -> Optional[HealthReport]:
        """The shard controller's verdict (None when no standby)."""
        if self._controller is None:
            return None
        return self._controller.health()

    def maybe_promote(self) -> Optional[ReservoirService]:
        """Controller-driven failover: promote iff the shard-scoped health
        verdict says so; returns the promoted service or None."""
        report = self.health()
        if report is None or not report.should_promote:
            return None
        return self.promote(
            reason="; ".join(report.reasons) or "unhealthy",
            triggers=report.triggers,
        )

    def slo_verdicts(self) -> Dict[str, str]:
        """This shard's burn-rate verdicts over its scoped instruments
        (empty while telemetry is disabled).  The plane is created lazily
        on the first call with a live registry, detached
        (``attach=False``)."""
        if _obs.get() is None:
            return {}
        if self._slo_plane is None:
            from ..obs.slo import SLOPlane, default_slos

            self._slo_plane = SLOPlane(
                default_slos(scope=self._obs_scope, **self._slo_kwargs),
                attach=False,
            )
        return {
            name: v.verdict
            for name, v in self._slo_plane.evaluate().items()
        }

    def status(self) -> dict:
        """One JSON-able row for the cluster heartbeat / status panel."""
        row: dict = {
            "alive": self.alive,
            "epoch": self.epoch,
            "reason": self._unavailable_reason,
        }
        if self._service is not None:
            row.update(
                seq=self._service.flushed_seq,
                sessions_open=len(self._service.table),
                watchdog_trips=self._service.bridge.metrics.watchdog_trips,
                demotions=self._service.bridge.metrics.demotions,
                rejections=self._service.metrics.rejections,
            )
        if self._standby is not None:
            row["standby_applied_seq"] = self._standby.applied_seq
            row["standby_lag_seq"] = self._standby.metrics.lag_seq
        verdicts = self.slo_verdicts()
        if verdicts:
            row["slo_worst"] = max(
                verdicts.values(),
                key=lambda v: {"ok": 0, "warn": 1, "page": 2}[v],
            )
            row["slo"] = verdicts
        return row

    def shutdown(self) -> None:
        if self._service is not None:
            self._service.shutdown()
