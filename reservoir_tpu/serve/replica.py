"""Hot-standby replication: tail a primary's flush journal into a warm replica.

The bridge's crash-recovery plane (PR 3) already writes everything a
replica needs: ``engine.npz`` (atomic checkpoints carrying the flush
watermark), ``journal.bin`` (CRC-framed tiles keyed by ``flushed_seq``),
and — for the serving plane (PR 4) — ``sessions.jsonl`` (the session map
with each op's ``at_seq`` position between flushes).  *Parallel Streaming
Random Sampling* (arXiv:1906.04120) observes that reservoir state is
cheaply transferable because it is tiny relative to the stream; this
module turns that observation into availability: instead of a
stop-the-world ``recover()`` after a crash (downtime = checkpoint load +
full journal replay), a :class:`StandbyReplica` keeps a *warm* copy
continuously caught up, so failover is an epoch bump plus the last few
journal records.

Components:

- :class:`JournalFollower` — a resumable byte-cursor tail of
  ``journal.bin``: CRC-checked, torn-tail tolerant (a partial frame is a
  primary mid-append, retried next poll), rotation-aware (the file
  shrinking below the cursor means the primary checkpointed and truncated;
  the scan restarts at byte 0 and skips already-applied sequence numbers),
  and gap-detecting (records lost to a rotation the standby slept through
  force a checkpoint-shipping re-bootstrap).
- :class:`StandbyReplica` — checkpoint-shipping bootstrap + incremental
  apply.  It holds a warm :class:`~reservoir_tpu.serve.service.ReservoirService`
  (never journaling, never checkpointing — one primary owns the durable
  state) and applies shipped tiles through the exact replay path
  ``recover()`` uses, with session-map ops (row resets between flushes)
  re-applied at their journaled ``at_seq`` positions — **bit-exact by
  construction**, because it replays the same journaled bytes in the same
  order.  :meth:`StandbyReplica.lag` reports (seq delta, staleness
  seconds); :meth:`StandbyReplica.promote` performs the epoch-fenced
  failover (see :mod:`reservoir_tpu.serve.ha` for the fencing story).

Fault plane: ``replica.ship`` fires on the follower's read path and
``replica.apply`` before each tile lands on the standby engine — an
injected failure at either site makes the poll return early (counted in
:class:`~reservoir_tpu.utils.metrics.HAMetrics`), the cursor does not
advance past unapplied records, and the next poll retries: lag grows,
state never corrupts (pinned by ``tests/test_faults.py`` /
``tests/test_ha.py``).

Single-writer like everything below it: one thread owns a replica's
``poll``/``promote``; snapshot reads share that thread.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from collections import deque
from typing import Any, Deque, List, Optional, Tuple

import numpy as np

from ..obs import registry as _obs
from ..obs import trace as _ctrace
from ..stream.bridge import (
    DeviceStreamBridge,
    _FlushJournal,
    _unpack_adopt_payload,
)
from ..utils import faults as _faults
from ..utils.checkpoint import (
    advance_epoch,
    load_engine,
    read_engine_metadata,
)
from ..utils.metrics import HAMetrics
from ..utils.tracing import trace_span
from .service import _JOURNAL_NAME, ReservoirService
from .sessions import SessionTable

__all__ = ["JournalFollower", "StandbyReplica"]


class JournalFollower:
    """Resumable byte-cursor tail of a bridge tile journal.

    The cursor is ``(seq, offset)``: :meth:`poll` returns every intact
    record past it (bounded by ``max_records``), stopping cleanly at a
    torn tail.  The caller advances the cursor explicitly
    (:meth:`advance`) after *applying* each record, so a failed apply is
    re-read on the next poll — the follower can never skip a record it
    only read.
    """

    def __init__(
        self,
        path: str,
        num_streams: int,
        tile_width: int,
        dtype,
        weighted: bool,
        *,
        start_seq: int = 0,
        max_records: int = 256,
        faults: Optional[Any] = None,
    ) -> None:
        self._path = path
        self._S = int(num_streams)
        self._B = int(tile_width)
        self._dtype = np.dtype(dtype)
        self._weighted = weighted
        self._seq = int(start_seq)
        self._offset = 0
        self._offset_seq = 0
        # byte offset where the record ending at the cursor STARTS —
        # tracked explicitly because gated frames (ISSUE 8) make journal
        # records variable-size, so the predecessor probe can no longer
        # assume a fixed stride
        self._offset_start: Optional[int] = None
        self._starts: dict = {}
        self._max = int(max_records)
        self._faults = faults
        n_payload = self._S * 4 + self._S * self._B * (
            self._dtype.itemsize + (4 if weighted else 0)
        )
        # a PLAIN frame's size: the largest frame a non-gated primary
        # writes; used as the conservative misalignment bound below
        self._record_nbytes = _FlushJournal._HEADER.size + n_payload + 4

    @property
    def seq(self) -> int:
        """Sequence number of the last record the caller acknowledged."""
        return self._seq

    @property
    def offset(self) -> int:
        return self._offset

    def advance(self, seq: int, offset: int) -> None:
        """Acknowledge a record as applied: the cursor moves past it."""
        self._seq = int(seq)
        self._offset = int(offset)
        self._offset_seq = int(seq)
        start = self._starts.get(int(offset))
        if start is not None:
            self._offset_start = start

    def rewind(self, seq: int) -> None:
        """Reset after a re-bootstrap: scan from byte 0, skipping records
        the fresh checkpoint covers (``seq`` is its watermark)."""
        self._seq = int(seq)
        self._offset = 0
        self._offset_start = None

    def _cursor_valid(self) -> bool:
        """Whether the record ending at the cursor is still the one we
        read there.  Rotation truncates the journal and new records land
        at reusable byte offsets, so a size check alone cannot detect it —
        re-read the header of the record ending at the cursor (its start
        offset is tracked per ack: gated frames make records
        variable-size) and compare its sequence number."""
        start = (
            self._offset_start
            if self._offset_start is not None
            else self._offset - self._record_nbytes
        )
        if start < 0:
            return False
        try:
            with open(self._path, "rb") as fh:
                fh.seek(start)
                head = fh.read(_FlushJournal._HEADER.size)
        except FileNotFoundError:
            return False
        if len(head) < _FlushJournal._HEADER.size:
            return False
        magic, seq, _ = _FlushJournal._HEADER.unpack(head)
        return magic in (
            _FlushJournal._MAGIC,
            _FlushJournal._MAGIC_GATED,
            _FlushJournal._MAGIC_ADOPT,
        ) and seq == self._offset_seq

    def poll(
        self,
    ) -> Tuple[
        List[
            Tuple[
                int, int, np.ndarray, np.ndarray, Optional[np.ndarray],
                Optional[np.ndarray],
            ]
        ],
        bool,
        bool,
    ]:
        """Read intact records past the cursor.

        Returns ``(records, rotated, gap)``: ``records`` is a list of
        ``(end_offset, seq, tile, valid, wtile, advance)`` in sequence
        order (``advance`` non-None marks a gated frame, ISSUE 8);
        ``rotated`` flags a detected journal rotation (file shrank below
        the cursor); ``gap`` means an intact record was found whose seq
        skips past the cursor — records were lost to a rotation and the
        caller must re-bootstrap from the checkpoint.  The ``replica.ship``
        fault site fires before any file I/O.
        """
        _faults.fire("replica.ship", self._faults)
        rotated = False
        try:
            size = os.path.getsize(self._path)
        except FileNotFoundError:
            return [], False, False
        if self._offset and (size < self._offset or not self._cursor_valid()):
            rotated = True
            self._offset = 0
            self._offset_start = None
        records: List = []
        gap = False
        prev_end = self._offset
        starts: dict = {}
        for end, seq, tile, valid, wtile, adv in _FlushJournal.read_records(
            self._path,
            self._S,
            self._B,
            self._dtype,
            self._weighted,
            offset=self._offset,
        ):
            start, prev_end = prev_end, end
            if seq <= self._seq:
                # already applied (post-rotation rescan): skip permanently
                self._offset = end
                self._offset_seq = seq
                self._offset_start = start
                continue
            if seq != self._seq + len(records) + 1:
                gap = True
                break
            records.append((end, seq, tile, valid, wtile, adv))
            starts[end] = start
            if len(records) >= self._max:
                break
        self._starts = starts
        if not records and not gap and self._offset:
            # Misalignment detector: a rotation can go unnoticed when the
            # new journal grows past the old cursor (size never dipped
            # below it) — the cursor then points mid-record and parses
            # nothing, forever.  The primary appends record-at-a-time
            # (each fully flushed before the next starts), so a full
            # record's worth of bytes beyond the cursor that does NOT
            # parse cannot be a torn tail: declare a gap and let the
            # caller re-bootstrap, which realigns the scan at byte 0.
            try:
                size = os.path.getsize(self._path)
            except FileNotFoundError:
                size = 0
            if size >= self._offset + self._record_nbytes:
                gap = True
        return records, rotated, gap


class StandbyReplica:
    """A warm replica of a checkpointing bridge/service, continuously
    caught up by tailing its journal — the hot-standby half of the HA
    plane (ISSUE 5).

    Construction performs the checkpoint-shipping bootstrap: load
    ``engine.npz``, rebuild the session table from ``sessions.jsonl``
    (row resets the checkpoint already covers are skipped — they are
    baked into its state), and point a :class:`JournalFollower` at the
    post-checkpoint tail.  :meth:`poll` then applies newly journaled
    tiles and session ops in their original interleaving; because every
    draw is counter-keyed on absolute stream indices, the standby's
    reservoirs are **bit-identical** to the primary's at every applied
    watermark.

    The standby never writes to ``checkpoint_dir``: one primary owns the
    durable state until :meth:`promote` fences it (epoch bump), drains
    the remaining tail, and flips this replica into a live, journaling
    primary.  Until then, :meth:`snapshot` serves read-only (bounded-
    staleness) session queries — a read replica for free.

    Args:
      checkpoint_dir: the primary's checkpoint directory (shared or
        shipped filesystem).
      map_fn / hash_fn: code is not data — re-supply them when the
        primary's engine was built with them.
      max_records: tile-apply batch bound per :meth:`poll`.
      clock: monotonic time source for staleness accounting (injectable).
      faults: fault plane for the ``replica.*`` sites.
      metrics: shared :class:`HAMetrics` (one is created when omitted).
      status_path: when set, every :meth:`poll` / :meth:`promote` writes an
        atomic JSON status file there (applied watermark, lag, promotion
        state, plus the telemetry JSON export when the registry is
        enabled) — what ``tools/reservoir_top.py`` tails for the standby
        half of an HA pair.  Never inside ``checkpoint_dir``: the standby
        does not write to the primary's durable state.
    """

    def __init__(
        self,
        checkpoint_dir: str,
        *,
        map_fn: Optional[Any] = None,
        hash_fn: Optional[Any] = None,
        max_records: int = 256,
        clock=time.monotonic,
        faults: Optional[Any] = None,
        metrics: Optional[HAMetrics] = None,
        status_path: Optional[str] = None,
    ) -> None:
        self._dir = checkpoint_dir
        self._status_path = status_path
        self._map_fn = map_fn
        self._hash_fn = hash_fn
        self._max_records = int(max_records)
        self._clock = clock
        self._faults = faults
        self._metrics = metrics if metrics is not None else HAMetrics()
        self._promoted = False
        self._last_error: Optional[BaseException] = None
        self._started_at = clock()
        self._caught_up_at: Optional[float] = None
        self._target_seq = 0
        self._covered_cache: Tuple[Optional[Tuple[int, int]], int] = (None, 0)
        self._bootstrap()

    # ------------------------------------------------------------ properties

    @property
    def checkpoint_dir(self) -> str:
        return self._dir

    @property
    def metrics(self) -> HAMetrics:
        return self._metrics

    @property
    def applied_seq(self) -> int:
        """The flush watermark this replica has applied (its reservoirs
        are bit-identical to the primary's as of this sequence)."""
        return self._applied_seq

    @property
    def is_promoted(self) -> bool:
        return self._promoted

    @property
    def service(self) -> ReservoirService:
        """The warm service.  NOTE: its identity changes when a journal
        rotation forces a re-bootstrap — hold the replica, not this."""
        return self._service

    @property
    def table(self) -> SessionTable:
        return self._service.table

    @property
    def last_error(self) -> Optional[BaseException]:
        """The most recent ship/apply failure (retried on the next poll)."""
        return self._last_error

    # ------------------------------------------------------------- bootstrap

    def _bootstrap(self) -> None:
        """Checkpoint-shipping bootstrap: engine from ``engine.npz``,
        session table from the full ``sessions.jsonl``, follower cursor at
        the checkpoint's watermark."""
        engine_path = os.path.join(self._dir, "engine.npz")
        engine, metadata = load_engine(
            engine_path,
            map_fn=self._map_fn,
            hash_fn=self._hash_fn,
            with_metadata=True,
        )
        info = (metadata or {}).get("bridge")
        if info is None:
            raise ValueError(
                f"{engine_path!r} was not written by an auto-checkpointing "
                "bridge (no bridge metadata); a standby can only follow one"
            )
        engine._faults = self._faults
        covered = int(info["seq"])
        self._bridge_info = dict(info)
        config = engine.config
        # the standby's bridge is an engine holder + snapshot-cache keyer:
        # unpipelined (tiles apply on the poll thread) and NOT journaling
        # (one primary owns the durable state until promote())
        bridge = DeviceStreamBridge(
            config,
            map_fn=self._map_fn,
            hash_fn=self._hash_fn,
            reusable=True,
            pipelined=False,
            faults=self._faults,
            _engine=engine,
        )
        bridge._flush_seq = covered
        self._engine = engine
        self._bridge = bridge
        self._covered = covered
        self._applied_seq = covered
        self._target_seq = max(self._target_seq, covered)
        self._pending_ops: Deque[dict] = deque()
        self._sess_offset = 0
        header = self._read_session_header()
        table = SessionTable(
            config.num_reservoirs,
            ttl_s=(header or {}).get("ttl_s"),
            seed=int((header or {}).get("seed", 0)),
        )
        self._service = ReservoirService(
            config,
            ttl_s=table.ttl_s,
            faults=self._faults,
            _bridge=bridge,
            _table=table,
        )
        self._table = table
        self._follower = JournalFollower(
            os.path.join(self._dir, "journal.bin"),
            config.num_reservoirs,
            config.tile_size,
            np.dtype(config.element_dtype),
            config.weighted,
            start_seq=covered,
            max_records=self._max_records,
            faults=self._faults,
        )
        # ops journaled before the checkpoint watermark apply immediately
        # (their table effect; resets with at_seq < covered are baked into
        # the checkpointed state and skipped — the recover() cursor rule)
        self._pending_ops.extend(self._tail_session_ops())
        self._drain_ready_ops()
        self._metrics.bootstraps += 1
        _obs.emit(
            "replica.bootstrap", site="replica.ship", flush_seq=covered
        )

    def _read_session_header(self) -> Optional[dict]:
        """Parse and consume the ``base`` header record, when a session
        journal exists (bridge-only primaries have none — the replica then
        follows tiles alone over a fresh table)."""
        ops = self._tail_session_ops()
        if not ops:
            return None
        header = ops[0]
        if header.get("op") != "base":
            raise ValueError(
                f"{os.path.join(self._dir, _JOURNAL_NAME)!r}: session "
                "journal has no base header record"
            )
        self._pending_ops.extend(ops[1:])
        return header

    # ------------------------------------------------------------- tailing

    def _tail_session_ops(self) -> List[dict]:
        """Incremental session-journal tail: parse newline-terminated
        lines past the byte cursor (a torn final line is a primary
        mid-append — left unconsumed for the next poll)."""
        path = os.path.join(self._dir, _JOURNAL_NAME)
        try:
            with open(path, "rb") as fh:
                fh.seek(self._sess_offset)
                data = fh.read()
        except FileNotFoundError:
            return []
        ops: List[dict] = []
        consumed = 0
        for line in data.split(b"\n")[:-1]:
            consumed += len(line) + 1
            if line.strip():
                ops.append(json.loads(line))
        self._sess_offset += consumed
        return ops

    def _apply_op(self, op: dict) -> None:
        """One session-map op at its journaled position.  Engine resets go
        FIRST (from the record's own row/gen, so a failure retries
        cleanly with the table untouched), then the table op with the same
        divergence check ``recover()`` applies."""
        kind = op.get("op")
        if kind == "open":
            row, gen = int(op["row"]), int(op["gen"])
            if gen > 0 and int(op["at_seq"]) >= self._covered:
                self._engine.reset_rows(
                    [row], self._table.sub_key(row, gen)
                )
                self._service._reset_epoch += 1
            sess, evicted = self._table.open(op["key"])
            if evicted or sess.row != row or sess.generation != gen:
                raise ValueError(
                    f"session journal replay diverged at {op!r}: rebuilt "
                    f"lease (row={sess.row}, gen={sess.generation}) does "
                    "not match the record"
                )
        elif kind in ("close", "evict"):
            self._table.close(op["key"])
        else:
            raise ValueError(f"session journal: unknown op {kind!r}")
        self._metrics.applied_ops += 1

    def _drain_ready_ops(self) -> None:
        """Apply queued ops whose journaled position has been reached.
        An op at ``at_seq`` happened after flush ``at_seq`` on the
        primary, so it applies once ``applied_seq`` reaches it — both its
        table effect and its engine reset, together, so a standby
        snapshot can never route a new lease to a not-yet-reset row."""
        while self._pending_ops and (
            int(self._pending_ops[0]["at_seq"]) <= self._applied_seq
        ):
            self._apply_op(self._pending_ops[0])
            self._pending_ops.popleft()

    def _checkpoint_covered(self) -> int:
        """The current checkpoint's flush watermark, stat-cached so the
        per-poll staleness probe costs one stat until the primary actually
        checkpoints again (manifest-only read on change)."""
        path = os.path.join(self._dir, "engine.npz")
        try:
            st = os.stat(path)
        except FileNotFoundError:
            return 0
        key = (st.st_mtime_ns, st.st_size)
        if self._covered_cache[0] != key:
            meta = read_engine_metadata(path)
            self._covered_cache = (
                key,
                int((meta.get("bridge") or {}).get("seq", 0)),
            )
        return self._covered_cache[1]

    # --------------------------------------------------------------- polling

    def poll(self) -> int:
        """One replication step: tail new session ops and journal records,
        apply them in their original interleaving.  Returns the number of
        flush sequences advanced (0 = caught up, or a ship/apply failure
        — inspect :attr:`last_error` / :attr:`metrics`; the failed work is
        retried on the next poll, never skipped)."""
        if self._promoted:
            raise RuntimeError(
                "this replica was promoted; poll the new primary's standby"
            )
        applied = 0
        try:
            self._pending_ops.extend(self._tail_session_ops())
            self._drain_ready_ops()
            records, rotated, gap = self._follower.poll()
            if not records and not gap:
                # Nothing readable: records may have been lost to a
                # rotation this follower could not witness (journal
                # truncated before it ever read them — e.g. a fresh
                # re-follow whose cursor is still at byte 0, so neither
                # the size dip nor the content probe can fire).  The
                # checkpoint watermark is the authority: anything it
                # covers beyond our applied seq means re-bootstrap.
                if self._checkpoint_covered() > self._applied_seq:
                    gap = True
            if gap:
                # records were lost to a rotation we slept through (or
                # the cursor is misaligned past one): the newer checkpoint
                # covers everything before its watermark — re-bootstrap
                # from it, then tail the realigned journal in this poll
                old = self._applied_seq
                self._bootstrap()
                applied += max(0, self._applied_seq - old)
                records, _, _ = self._follower.poll()
                if records:
                    self._target_seq = max(
                        self._target_seq, records[-1][1]
                    )
        except Exception as e:
            self._metrics.ship_errors += 1
            self._last_error = e
            self._update_lag()
            return applied
        if records:
            self._target_seq = max(self._target_seq, records[-1][1])
        for end, seq, tile, valid, wtile, advance in records:
            try:
                _faults.fire("replica.apply", self._faults)
                # the exact replay path recover() uses — bit-exact by
                # construction (counter-keyed draws); gated frames apply
                # through the same gated engine path (ISSUE 8)
                reg = _obs.get()
                tr = _ctrace.get()
                t0 = time.perf_counter() if reg is not None else 0.0
                # causal root keyed by the flush seq: the same stable hash
                # the bridge used, so a sampled flush is sampled here too
                # and the two sides of a journal frame join on flush_seq
                acm = (
                    tr.span("replica.apply", key=seq, flush_seq=seq)
                    if tr is not None
                    else contextlib.nullcontext()
                )
                with acm, trace_span("reservoir_replica_apply"):
                    if advance is _FlushJournal.ADOPT:
                        # adopt frame (ISSUE 12): a live migration landed
                        # rows on the primary — re-apply them here at the
                        # same position between flushes
                        rows, sub = _unpack_adopt_payload(tile)
                        self._engine.adopt_rows(rows, sub)
                        self._service._reset_epoch += 1
                    elif advance is not None:
                        self._engine.sample_gated(tile, valid, advance)
                    else:
                        self._engine.sample(tile, valid=valid, weights=wtile)
                if reg is not None:
                    reg.histogram("replica.apply_s").observe(
                        time.perf_counter() - t0
                    )
                self._applied_seq = seq
                self._bridge._flush_seq = seq  # keys the snapshot cache
                self._follower.advance(seq, end)
                self._metrics.applied_tiles += 1
                applied += 1
                self._drain_ready_ops()
            except Exception as e:
                self._metrics.apply_errors += 1
                self._last_error = e
                break
        self._update_lag()
        self._write_status()
        return applied

    def _update_lag(self) -> None:
        now = self._clock()
        lag_seq = max(0, self._target_seq - self._applied_seq)
        if lag_seq == 0 and not self._pending_ops:
            self._caught_up_at = now
            lag_s = 0.0
        else:
            since = (
                self._caught_up_at
                if self._caught_up_at is not None
                else self._started_at
            )
            lag_s = max(0.0, now - since)
        self._metrics.lag_seq = lag_seq
        self._metrics.lag_s = lag_s
        reg = _obs.get()
        if reg is not None:
            # gauges carry the instantaneous lag; histograms accumulate
            # the distribution over polls (what `bench.py ha` reads)
            reg.gauge("replica.lag_seq").set(lag_seq)
            reg.gauge("replica.lag_s").set(lag_s)
            reg.histogram(
                "replica.lag_seq_dist", lo=1e-3, hi=1e9, buckets_per_decade=4
            ).observe(lag_seq)
            reg.histogram("replica.lag_s_dist").observe(lag_s)

    def _write_status(self) -> None:
        """Atomic standby status file (``status_path=``): the standby half
        of what ``reservoir_top`` renders.  Best-effort — a status-write
        failure must never fail replication."""
        if self._status_path is None:
            return
        payload = {
            "ts": time.time(),
            "applied_seq": self._applied_seq,
            "target_seq": self._target_seq,
            "lag_seq": self._metrics.lag_seq,
            "lag_s": self._metrics.lag_s,
            "bootstraps": self._metrics.bootstraps,
            "apply_errors": self._metrics.apply_errors,
            "ship_errors": self._metrics.ship_errors,
            "promoted": self._promoted,
            "last_error": (
                repr(self._last_error) if self._last_error else None
            ),
        }
        reg = _obs.get()
        if reg is not None:
            from ..obs.export import json_snapshot

            payload["telemetry"] = json_snapshot(reg)
        try:
            import tempfile

            directory = (
                os.path.dirname(os.path.abspath(self._status_path)) or "."
            )
            fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp.status")
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as fh:
                    json.dump(payload, fh, default=str)
                os.replace(tmp, self._status_path)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
        except OSError:
            pass

    def lag(self) -> Tuple[int, float]:
        """Replication lag as ``(seq_delta, staleness_s)``: flush
        sequences known-durable but not yet applied, and seconds since
        this replica was last provably caught up (0.0 while caught up).
        The seq target is the newest record the follower has *seen* — a
        ship failure freezes it, so staleness keeps growing while the
        delta may under-report until the next successful read."""
        self._update_lag()
        return self._metrics.lag_seq, self._metrics.lag_s

    def snapshot(self, key: str) -> np.ndarray:
        """Read-only per-session snapshot at the applied watermark (the
        bounded-staleness read-replica path; never flushes, never
        journals)."""
        return self._service.snapshot(key, sync=False)

    # ------------------------------------------------------------- promotion

    def promote(
        self,
        *,
        checkpoint: bool = True,
        checkpoint_every: Optional[int] = None,
        durability: Optional[str] = None,
        drain_attempts: int = 32,
    ) -> ReservoirService:
        """Epoch-fenced failover: make this replica the live primary.

        1. **Fence** — bump the epoch persisted in the checkpoint dir
           (fsynced).  From this instant the old primary's next flush or
           checkpoint raises :class:`~reservoir_tpu.errors.FencedError`
           without mutating the journal — split-brain cannot corrupt the
           durable state.
        2. **Drain** — poll until a clean pass finds nothing left (the
           fenced primary can no longer append; a torn final frame is an
           element batch that was never durable, exactly the crash
           contract).  Injected/real ship failures are retried up to
           ``drain_attempts`` polls; if the tail still cannot be read,
           promote raises and the standby stays a standby (re-callable).
        3. **Flip** — adopt the journal (append mode, no seq-0 anchor) at
           the new epoch, reopen the session journal, and (by default)
           take a handoff checkpoint so the journal rotates and a new
           standby can re-follow from a short tail.

        Returns the promoted, now-journaling
        :class:`~reservoir_tpu.serve.service.ReservoirService`.
        """
        if self._promoted:
            raise RuntimeError("this replica was already promoted")
        reg = _obs.get()
        t0 = time.perf_counter() if reg is not None else 0.0
        with trace_span("reservoir_promote"):
            service = self._promote_steps(
                checkpoint=checkpoint,
                checkpoint_every=checkpoint_every,
                durability=durability,
                drain_attempts=drain_attempts,
            )
        if reg is not None:
            reg.histogram("ha.promote_s").observe(time.perf_counter() - t0)
        _obs.emit(
            "ha.promoted",
            site="ha.promote",
            epoch=self._bridge.epoch,
            flush_seq=self._applied_seq,
        )
        self._write_status()
        return service

    def _promote_steps(
        self,
        *,
        checkpoint: bool,
        checkpoint_every: Optional[int],
        durability: Optional[str],
        drain_attempts: int,
    ) -> ReservoirService:
        """The fence/drain/flip sequence (traced as ``reservoir_promote``)."""
        epoch = advance_epoch(self._dir)
        for _ in range(max(1, drain_attempts)):
            errs = self._metrics.ship_errors + self._metrics.apply_errors
            n = self.poll()
            clean = (
                self._metrics.ship_errors + self._metrics.apply_errors
                == errs
            )
            if n == 0 and clean and not self._pending_ops:
                break
        else:
            raise RuntimeError(
                f"promote: journal tail not drained after {drain_attempts} "
                f"polls (lag={self._metrics.lag_seq}); last error: "
                f"{self._last_error!r}"
            )
        info = self._bridge_info
        self._bridge._attach_journal(
            self._dir,
            checkpoint_every=(
                int(info.get("checkpoint_every", 64))
                if checkpoint_every is None
                else checkpoint_every
            ),
            durability=(
                info.get("durability", "buffered")
                if durability is None
                else durability
            ),
            epoch=epoch,
        )
        self._service._journal_fh = open(
            os.path.join(self._dir, _JOURNAL_NAME), "a", encoding="utf-8"
        )
        if checkpoint:
            # the durable handoff: a fresh checkpoint at the applied
            # watermark rotates the journal, so the fenced primary's tail
            # is settled and a re-following standby bootstraps instantly
            self._bridge._save_snapshot()
        self._promoted = True
        self._metrics.promotions += 1
        return self._service
