"""Multi-tenant reservoir service: many sessions, one batched device engine.

:class:`ReservoirService` is the first traffic-facing entry point of the
stack: it multiplexes dynamically arriving tenant sessions onto the rows of
one :class:`~reservoir_tpu.stream.bridge.DeviceStreamBridge` (one device
engine, tens of thousands of reservoir rows) and serves results while
streams are still open.

What it adds over the raw bridge:

- **session lifecycle** — :meth:`open_session` / :meth:`ingest` /
  :meth:`snapshot` / :meth:`close_session` against opaque string keys,
  backed by the lease/evict :class:`~reservoir_tpu.serve.sessions.SessionTable`
  (TTL + LRU eviction, generation-guarded recycling, counter-keyed Threefry
  sub-seeds so a recycled row restarts statistically fresh without
  reseeding the engine — :meth:`ReservoirEngine.reset_rows`);
- **cross-session coalescing** — per-session ingests append to a pending
  buffer and ship through the bridge's existing ``push_interleaved``
  C-speed demux in batches, so ten thousand tiny ingests cost a handful of
  scatter calls, not ten thousand;
- **admission control** — a bounded in-flight byte budget; when it is
  exceeded *and* the flush pipeline cannot absorb more
  (:meth:`DeviceStreamBridge.flush_would_block`), ingest rejects with
  :class:`~reservoir_tpu.errors.ServiceSaturated` carrying ``retry_after_s``
  instead of queuing unboundedly;
- **live snapshot queries** — :meth:`snapshot` is a NON-destructive
  per-session result read (``ReservoirEngine.peek_arrays``), served from a
  device->host snapshot cache keyed by ``(flushed_seq, reset_epoch)``; the
  sampler never closes, so a session can be queried any number of times
  mid-stream;
- **robustness plane wiring** (ISSUE 3 → this layer): a ``serve.ingest``
  fault-injection site whose failures surface as typed *per-session*
  errors (:class:`~reservoir_tpu.errors.SessionIngestError`) — the service
  stays live; crash recovery via :meth:`recover`, which rebuilds the
  session table from a journaled session map (``sessions.jsonl`` next to
  the bridge's checkpoint/journal pair) and re-applies journaled row
  resets *between* the replayed flushes they originally fell between
  (``DeviceStreamBridge.recover``'s ``replay_hook``) — reservoirs come
  back bit-identical; and :class:`~reservoir_tpu.utils.metrics.ServiceMetrics`
  surfaced through ``bench.py serve``.

Thread-safety matches the stack below: one writer.  Put a lock or a queue
in front for multi-producer traffic.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, List, Optional, Tuple

import numpy as np

from ..config import SamplerConfig
from ..obs import registry as _obs
from ..obs import trace as _trace
from ..errors import (
    CheckpointMismatch,
    RetryPolicy,
    ServiceSaturated,
    SessionIngestError,
)
from ..stream.bridge import DeviceStreamBridge
from ..utils import faults as _faults
from ..utils.metrics import ServiceMetrics
from . import autotune as _serve_tune
from .autotune import DEFAULT_KNOBS, ServiceKnobs
from .sessions import Session, SessionTable

__all__ = ["ReservoirService"]

_JOURNAL_NAME = "sessions.jsonl"
_JOURNAL_VERSION = 1

class _Unset:
    """Distinct from ``None``: ``sweep_interval_s=None`` is a meaningful
    setting (manual sweeps only), so "not passed — resolve from the knob
    cache" needs its own sentinel.  The stable repr keeps generated API
    manifests deterministic across processes."""

    def __repr__(self) -> str:
        return "<UNSET>"


_UNSET: Any = _Unset()


def _read_session_journal(path: str) -> Tuple[dict, List[dict]]:
    """Parse the session journal: ``(header, ops)``.  A torn final line
    (crash mid-append) is dropped — the same tolerance the bridge's tile
    journal extends to its tail record."""
    with open(path, encoding="utf-8") as fh:
        lines = fh.read().splitlines()
    records: List[dict] = []
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break  # torn tail: the op it described never completed
            raise ValueError(
                f"{path!r}: corrupt session journal at line {i + 1}"
            )
    if not records or records[0].get("op") != "base":
        raise ValueError(
            f"{path!r}: session journal has no base header record"
        )
    return records[0], records[1:]


class ReservoirService:
    """Serve many tenant sessions from one batched device engine.

    Args:
      config: engine configuration; ``num_reservoirs`` is the session
        capacity (rows leasable at once) and ``distinct``/``weighted``
        select the sampling mode every session of this service uses.
      key: engine PRNG key/seed (per-row keys are split from it once).
      ttl_s: idle lease time after which a session is evictable (sweep or
        row pressure); ``None`` = LRU-only eviction.
      session_seed: base seed of the per-lease sub-key schedule (recycled
        rows draw from ``fold_in(fold_in(key(session_seed), row), gen)``).
      coalesce_bytes: pending-ingest threshold at which the buffer ships
        through ``push_interleaved`` (cross-session batching lever).
        Like every serving knob below (``max_inflight_bytes`` /
        ``checkpoint_every`` / ``sweep_interval_s`` / ``gate_push_chunk``),
        leaving it unset consumes the swept winner from the persistent
        knob cache (:mod:`reservoir_tpu.serve.autotune`, ISSUE 14) for
        this service's workload fingerprint — exactly the way the engine
        consumes tuned kernel geometry.  An explicit value always wins;
        no cache entry = the builtin default, byte-identical behavior.
      max_inflight_bytes: admission-control budget over pending bytes;
        beyond it, ingest either flushes (pipeline willing) or rejects
        with :class:`ServiceSaturated`.
      retry_after_s: floor of the rejection's retry hint (the live hint
        scales with the observed per-flush dispatch time).
      sweep_interval_s: opportunistic TTL-sweep cadence.  When set (and
        ``ttl_s`` is), every :meth:`ingest` / :meth:`snapshot` /
        :meth:`sync` first evicts TTL-expired sessions if at least this
        many seconds passed since the last sweep — an idle-but-queried
        service sheds expired leases without anyone calling
        :meth:`sweep_expired` manually.  ``None`` (default) keeps sweeps
        manual-only.
      auditor: optional online
        :class:`~reservoir_tpu.obs.audit.SampleQualityAuditor` (ISSUE 7):
        when set, every accepted ingest feeds its stratum ledger and
        every snapshot read feeds its rolling KS pool, lighting up the
        ``audit.*`` instruments the ``sample_quality`` SLO judges.  Both
        hooks are zero-overhead no-ops while telemetry is disabled
        (pinned by the trip-wire in ``tests/test_obs.py``).
      obs_scope: per-shard instrument label (ISSUE 9).  When set, the
        service's ``serve.*`` instruments are recorded under scoped names
        (``serve.ingest_s@<scope>`` — :func:`reservoir_tpu.obs.registry.scoped`)
        so N shard services sharing one registry stay separately
        observable and separately SLO-judged
        (``default_slos(scope=...)``).  ``None`` (default) keeps the
        unscoped names every existing dashboard reads.
      pipelined / retry_policy / flush_timeout_s / checkpoint_dir /
        checkpoint_every / durability / faults / gated / gate_tile:
        forwarded to the underlying :class:`DeviceStreamBridge` (the
        ISSUE-3/5 robustness plane; ``gated`` is the ISSUE-8 ingest-side
        skip gate; ``gate_tile=0`` resolves the tile width from the
        autotune cache, 64 when untuned).  With ``checkpoint_dir`` set
        the service additionally
        journals the session map to ``sessions.jsonl`` there, which is
        what makes :meth:`recover` (and hot-standby replication,
        :class:`~reservoir_tpu.serve.replica.StandbyReplica`) possible.
        Admission control is deliberately PRE-gate: ``coalesce_bytes`` /
        ``max_inflight_bytes`` bound the raw ingested bytes and
        ``flush_would_block`` probes pipeline permits, so enabling the
        gate changes neither the rejection threshold nor what
        ``ServiceSaturated.retry_after_s`` means (pinned by
        ``tests/test_gate.py``).
      device: pin this service's engine (state + flushes) to one device
        (ISSUE 12 per-shard placement; forwarded to the bridge).
    """

    def __init__(
        self,
        config: SamplerConfig,
        key: Any = None,
        *,
        ttl_s: Optional[float] = None,
        session_seed: int = 0,
        coalesce_bytes: Optional[int] = None,
        max_inflight_bytes: Optional[int] = None,
        retry_after_s: float = 0.05,
        sweep_interval_s: Optional[float] = _UNSET,
        auditor: Optional[Any] = None,
        obs_scope: Optional[str] = None,
        pipelined: bool = True,
        retry_policy: Optional[RetryPolicy] = None,
        flush_timeout_s: Optional[float] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: Optional[int] = None,
        durability: str = "buffered",
        faults: Optional[Any] = None,
        gated: bool = False,
        gate_tile: int = 64,
        gate_push_chunk: Optional[int] = None,
        device: Optional[Any] = None,
        _bridge: Optional[DeviceStreamBridge] = None,
        _table: Optional[SessionTable] = None,
    ) -> None:
        # knob-cache consumption (ISSUE 14): any knob left unset resolves
        # to the swept winner for this workload fingerprint, then to the
        # builtin default — the engine's kernel-geometry discipline,
        # applied to the serving plane.  Explicit kwargs always win.
        if (
            coalesce_bytes is None
            or max_inflight_bytes is None
            or checkpoint_every is None
            or gate_push_chunk is None
            or sweep_interval_s is _UNSET
        ):
            mode = (
                "weighted"
                if config.weighted
                else "distinct" if config.distinct else "plain"
            )
            tuned = _serve_tune.lookup_knobs(
                _serve_tune.device_kind_of(device),
                int(config.num_reservoirs),
                int(config.max_sample_size),
                mode,
                bool(gated),
            ) or DEFAULT_KNOBS
            if coalesce_bytes is None:
                coalesce_bytes = tuned.coalesce_bytes
            if max_inflight_bytes is None:
                max_inflight_bytes = tuned.max_inflight_bytes
            if checkpoint_every is None:
                checkpoint_every = tuned.checkpoint_every
            if gate_push_chunk is None:
                gate_push_chunk = tuned.gate_push_chunk
            if sweep_interval_s is _UNSET:
                # cache 0.0 = manual-only, the constructor's None
                sweep_interval_s = tuned.sweep_interval_s or None
        if coalesce_bytes <= 0 or max_inflight_bytes <= 0:
            raise ValueError(
                "coalesce_bytes and max_inflight_bytes must be positive"
            )
        if coalesce_bytes > max_inflight_bytes:
            raise ValueError(
                "coalesce_bytes must not exceed max_inflight_bytes (the "
                "coalesce buffer is what the admission bound bounds)"
            )
        self._faults = faults
        self._bridge = _bridge if _bridge is not None else DeviceStreamBridge(
            config,
            key=key,
            reusable=True,  # the serve plane never spends the lifecycle
            pipelined=pipelined,
            retry_policy=retry_policy,
            flush_timeout_s=flush_timeout_s,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            durability=durability,
            faults=faults,
            gated=gated,
            gate_tile=gate_tile,
            # cache 0 = "no opinion": keep the bridge's builtin default
            # rather than triggering its gate-geometry resolution
            gate_push_chunk=int(gate_push_chunk) if gate_push_chunk else 1 << 20,
            device=device,
        )
        config = self._bridge._config
        self._config = config
        self._table = _table if _table is not None else SessionTable(
            config.num_reservoirs, ttl_s=ttl_s, seed=session_seed
        )
        self._dtype = np.dtype(config.element_dtype)
        self._coalesce_bytes = int(coalesce_bytes)
        self._max_inflight_bytes = int(max_inflight_bytes)
        self._retry_after_s = float(retry_after_s)
        self._sweep_interval_s = (
            float(sweep_interval_s) if sweep_interval_s is not None else None
        )
        self._auditor = auditor
        self._obs_scope = obs_scope
        self._last_sweep = self._table._clock()
        self._tuner = None  # ServiceTuner attaches itself (ISSUE 14)
        self._metrics = ServiceMetrics()
        self._metrics.sessions_open = len(self._table)
        # pending cross-session coalesce buffer: (rows, elems, weights)
        # triples appended per ingest, shipped as ONE interleaved push
        self._pend: List[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]] = []
        self._pend_bytes = 0
        self._pend_t0 = time.perf_counter()
        # snapshot cache: (samples, sizes) host arrays keyed by
        # (flushed_seq, reset_epoch) — reset_epoch invalidates on row
        # recycling, else a cached snapshot could leak the previous
        # tenant's data into a freshly opened session
        self._snap: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._snap_key: Optional[Tuple[int, int]] = None
        self._snap_at = time.monotonic()  # cache fill time (staleness)
        self._reset_epoch = 0
        # session journal (crash recovery of the table itself)
        self._journal_fh = None
        if checkpoint_dir is not None:
            path = os.path.join(checkpoint_dir, _JOURNAL_NAME)
            if _bridge is None:
                # fresh service: the bridge just wrote its seq-0 anchor and
                # rotated its tile journal; start the session map fresh too
                self._journal_fh = open(path, "w", encoding="utf-8")
                self._append_journal(
                    {
                        "op": "base",
                        "v": _JOURNAL_VERSION,
                        "seed": self._table.seed,
                        "rows": self._table.capacity,
                        "ttl_s": self._table.ttl_s,
                    }
                )
            else:
                # recovery adoption: continue appending to the replayed map
                self._journal_fh = open(path, "a", encoding="utf-8")

    # ------------------------------------------------------------ properties

    @property
    def config(self) -> SamplerConfig:
        return self._config

    @property
    def metrics(self) -> ServiceMetrics:
        return self._metrics

    @property
    def table(self) -> SessionTable:
        return self._table

    @property
    def bridge(self) -> DeviceStreamBridge:
        return self._bridge

    @property
    def flushed_seq(self) -> int:
        """The underlying bridge's durable flush watermark."""
        return self._bridge.flushed_seq

    @property
    def device(self) -> Optional[Any]:
        """The device this service's engine is pinned to (``None`` when
        unpinned)."""
        return self._bridge.device

    # ---------------------------------------------------------- live knobs

    def live_knobs(self) -> ServiceKnobs:
        """The serving knobs as currently live (constructor-resolved plus
        any :meth:`apply_knobs` nudges since) — what the
        :class:`~reservoir_tpu.serve.autotune.ServiceTuner` reads before
        every control step and the sweep tool scores."""
        return ServiceKnobs(
            coalesce_bytes=self._coalesce_bytes,
            max_inflight_bytes=self._max_inflight_bytes,
            checkpoint_every=self._bridge.checkpoint_every,
            sweep_interval_s=self._sweep_interval_s or 0.0,
            gate_push_chunk=self._bridge.gate_push_chunk,
        )

    def apply_knobs(self, knobs: ServiceKnobs) -> None:
        """Apply a knob vector to the LIVE service (the online controller's
        write path).  Validates the same invariants as construction; takes
        effect from the next ingest/flush — never retroactively, so a
        nudge can change when bytes ship or state checkpoints, but no
        accepted element is ever dropped or resampled."""
        knobs = ServiceKnobs(*knobs)
        if knobs.coalesce_bytes <= 0 or knobs.max_inflight_bytes <= 0:
            raise ValueError(
                "coalesce_bytes and max_inflight_bytes must be positive"
            )
        if knobs.coalesce_bytes > knobs.max_inflight_bytes:
            raise ValueError(
                "coalesce_bytes must not exceed max_inflight_bytes"
            )
        self._coalesce_bytes = int(knobs.coalesce_bytes)
        self._max_inflight_bytes = int(knobs.max_inflight_bytes)
        self._bridge.set_checkpoint_every(knobs.checkpoint_every)
        if knobs.gate_push_chunk:
            self._bridge.set_gate_push_chunk(knobs.gate_push_chunk)
        self._sweep_interval_s = (
            float(knobs.sweep_interval_s)
            if knobs.sweep_interval_s > 0
            else None
        )

    def attach_tuner(self, tuner: Optional[Any]) -> None:
        """Attach (or detach, with ``None``) the online knob controller:
        every accepted ingest then gives it a rate-limited
        ``maybe_observe`` tick.  With no tuner attached the hot path pays
        one ``None`` test — the trip-wire-pinned zero-overhead bar."""
        self._tuner = tuner

    def _scoped(self, name: str) -> str:
        """Instrument name under this service's per-shard scope (ISSUE 9);
        the unscoped name when the service is not shard-labeled."""
        return _obs.scoped(name, self._obs_scope)

    def _append_journal(self, rec: dict) -> None:
        if self._journal_fh is None:
            return
        self._journal_fh.write(json.dumps(rec) + "\n")
        self._journal_fh.flush()

    # ----------------------------------------------------------- lifecycle

    def open_session(self, key: str) -> Session:
        """Lease a reservoir row to ``key`` and return the live handle.

        A full table evicts first (TTL-expired sessions, then the LRU
        one); a recycled row (generation > 0) is reset on device with this
        lease's counter-keyed sub-seed — after every element already
        accepted for the previous tenant has been flushed, so no byte of
        the old stream can bleed into the new one."""
        sess, evicted = self._table.open(key)
        for ev in evicted:
            self._append_journal(
                {
                    "op": "evict",
                    "key": ev.key,
                    "row": ev.row,
                    "at_seq": self._bridge.flushed_seq,
                }
            )
            self._metrics.evictions += 1
        at_seq = self._bridge.flushed_seq
        if sess.generation > 0:
            # recycle: the previous tenant's staged/pending elements must
            # reach the device BEFORE the reset wipes the row (and the
            # worker must be idle — reset shares the single-writer slot)
            self.sync()
            at_seq = self._bridge.flushed_seq
            self._bridge.engine.reset_rows(
                [sess.row], self._table.sub_key(sess.row, sess.generation)
            )
            self._reset_epoch += 1
            self._metrics.recycles += 1
            _obs.emit(
                "session.recycle",
                site="serve.open",
                session=key,
                row=sess.row,
                gen=sess.generation,
                flush_seq=at_seq,
            )
        self._append_journal(
            {
                "op": "open",
                "key": key,
                "row": sess.row,
                "gen": sess.generation,
                "at_seq": at_seq,
            }
        )
        self._metrics.sessions_opened += 1
        self._metrics.sessions_open = len(self._table)
        _obs.emit(
            "session.open",
            site="serve.open",
            session=key,
            row=sess.row,
            flush_seq=at_seq,
        )
        return sess

    def close_session(self, key: str) -> np.ndarray:
        """End ``key``'s lease and return its final sample (the same
        non-destructive snapshot path — the engine stays open for every
        other session).  The freed row recycles on a later open."""
        final = self.snapshot(key)
        sess = self._table.close(key)
        self._append_journal(
            {
                "op": "close",
                "key": key,
                "row": sess.row,
                "at_seq": self._bridge.flushed_seq,
            }
        )
        self._metrics.closes += 1
        self._metrics.sessions_open = len(self._table)
        _obs.emit(
            "session.close",
            site="serve.close",
            session=key,
            row=sess.row,
            flush_seq=self._bridge.flushed_seq,
        )
        return final

    def _maybe_sweep(self) -> None:
        """Opportunistic TTL sweep (ISSUE-5 satellite): ingest/snapshot/
        sync call this first, so an idle-but-queried service still sheds
        expired leases on its own once ``sweep_interval_s`` elapses."""
        if self._sweep_interval_s is None or self._table.ttl_s is None:
            return
        now = self._table._clock()
        if now - self._last_sweep >= self._sweep_interval_s:
            self._last_sweep = now
            self.sweep_expired(now)

    def sweep_expired(self, now: Optional[float] = None) -> List[str]:
        """Evict every TTL-expired session; returns their keys."""
        evicted = self._table.sweep(now)
        for ev in evicted:
            self._append_journal(
                {
                    "op": "evict",
                    "key": ev.key,
                    "row": ev.row,
                    "at_seq": self._bridge.flushed_seq,
                }
            )
            self._metrics.evictions += 1
            _obs.emit(
                "session.evict",
                site="serve.sweep",
                session=ev.key,
                row=ev.row,
                flush_seq=self._bridge.flushed_seq,
            )
        self._metrics.sessions_open = len(self._table)
        return [ev.key for ev in evicted]

    # -------------------------------------------------------------- ingest

    def ingest(
        self, key: str, elements: Any, weights: Optional[Any] = None
    ) -> int:
        """Accept a 1-D chunk of elements for session ``key``; returns the
        count accepted.  Failures are scoped to this call — a typed
        :class:`SessionIngestError` (or a :class:`ServiceSaturated`
        rejection) leaves the service and every other session live.

        The elements join the cross-session coalesce buffer and ship
        through the bridge's interleaved demux once ``coalesce_bytes``
        accumulate (or at the next sync/snapshot barrier)."""
        # causal trace root (ISSUE 11): head-sampled on the session key —
        # the same stable hash at every site, so a kept session's route/
        # admission/ship/gate spans all land in one trace.  One global
        # load + None test when tracing is disabled (trip-wire pinned).
        # Opened FIRST so the root's duration covers the whole call —
        # sweep and telemetry setup included — and the attribution
        # reconciles with a caller's wall clock up to span bookkeeping.
        tr = _trace.get()
        if tr is not None:
            with tr.span(
                "serve.ingest",
                key=key,
                session=key,
                shard=self._obs_scope,
            ):
                return self._ingest_counted(key, elements, weights, tr)
        return self._ingest_counted(key, elements, weights, None)

    def _ingest_counted(
        self,
        key: str,
        elements: Any,
        weights: Optional[Any],
        tr: Optional[Any],
    ) -> int:
        self._maybe_sweep()
        # telemetry (ISSUE 6): admission latency — accept-path wall time,
        # including any coalesce-buffer ship this call triggers.  One
        # global load + None test when disabled (the trip-wire pin).
        # ISSUE 7 adds the error-rate SLO's event counters: every call
        # into serve.ingest_total, every typed failure/rejection into
        # serve.ingest_errors — the pair the ingest_error_rate objective
        # burns against.
        reg = _obs.get()
        t0 = time.perf_counter() if reg is not None else 0.0
        try:
            n = self._ingest_impl(key, elements, weights)
        except (SessionIngestError, ServiceSaturated) as e:
            if tr is not None:
                # rejections force-sample: the traces worth keeping are
                # never the ones the head sampler happened to keep
                tr.point(
                    "serve.reject",
                    session=key,
                    shard=self._obs_scope,
                    error=type(e).__name__,
                    flush_seq=self._bridge.flushed_seq,
                )
            if reg is not None:
                reg.counter(self._scoped("serve.ingest_total")).inc()
                reg.counter(self._scoped("serve.ingest_errors")).inc()
            raise
        if reg is not None:
            reg.counter(self._scoped("serve.ingest_total")).inc()
            reg.histogram(self._scoped("serve.ingest_s")).observe(
                time.perf_counter() - t0
            )
        if self._tuner is not None:
            # closed loop (ISSUE 14): rate-limited inside, so steady
            # traffic drives SLO evaluation without a background thread
            self._tuner.maybe_observe()
        return n

    def _ingest_impl(
        self, key: str, elements: Any, weights: Optional[Any]
    ) -> int:
        tr = _trace.get()
        adm_cm = (
            tr.span("serve.admission", session=key)
            if tr is not None
            else contextlib.nullcontext()
        )
        with adm_cm:
            sess = self._table.route(key)
            try:
                _faults.fire("serve.ingest", self._faults)
            except Exception as e:
                raise SessionIngestError(
                    key, f"{type(e).__name__}: {e}"
                ) from e
            try:
                arr = np.atleast_1d(
                    np.ascontiguousarray(elements, self._dtype)
                )
            except (TypeError, ValueError) as e:
                raise SessionIngestError(
                    key, f"elements not convertible to {self._dtype}: {e}"
                ) from None
            if arr.ndim != 1:
                raise SessionIngestError(
                    key, f"elements must be 1-D, got shape {arr.shape}"
                )
            warr: Optional[np.ndarray] = None
            if self._config.weighted:
                if weights is None:
                    raise SessionIngestError(
                        key, "weighted service requires weights"
                    )
                warr = np.atleast_1d(
                    np.ascontiguousarray(weights, np.float32)
                )
                if warr.shape != arr.shape:
                    raise SessionIngestError(
                        key,
                        f"weights must match elements shape {arr.shape}, "
                        f"got {warr.shape}",
                    )
                if not np.all(warr >= 0):
                    bad = int(np.argmax(warr < 0))
                    raise SessionIngestError(
                        key,
                        f"weights must be nonnegative (weights[{bad}] = "
                        f"{warr[bad]})",
                    )
            elif weights is not None:
                raise SessionIngestError(
                    key, "weights are only meaningful with weighted=True"
                )
            nbytes = arr.nbytes + (warr.nbytes if warr is not None else 0)
            if nbytes > self._max_inflight_bytes:
                raise SessionIngestError(
                    key,
                    f"single request of {nbytes} bytes exceeds "
                    f"max_inflight_bytes={self._max_inflight_bytes} "
                    "(split it)",
                )
            # Admission: past the coalesce threshold a flush is due, but a
            # saturated pipeline means flushing would BLOCK — buffer on
            # while the hard byte budget allows, then reject with a retry
            # hint.  (Never block the ingest path on a slow device:
            # bounded memory and an explicit 429 is the contract.)
            saturated = (
                self._pend_bytes + nbytes >= self._coalesce_bytes
                and self._bridge.flush_would_block()
            )
            if saturated and (
                self._pend_bytes + nbytes > self._max_inflight_bytes
            ):
                self._metrics.rejections += 1
                _obs.emit(
                    "serve.rejected",
                    site="serve.ingest",
                    session=key,
                    pending_bytes=self._pend_bytes + nbytes,
                    flush_seq=self._bridge.flushed_seq,
                )
                raise ServiceSaturated(
                    f"in-flight bytes {self._pend_bytes + nbytes} over "
                    f"budget {self._max_inflight_bytes} with the flush "
                    "pipeline saturated",
                    retry_after_s=self._retry_hint(),
                )
        n = int(arr.shape[0])
        if not self._pend:
            # coalesce-wait anchor: the first pending append starts the
            # clock the traced ship stage reports as serve.coalesce_wait
            self._pend_t0 = time.perf_counter()
        self._pend.append(
            (np.full(n, sess.row, np.int32), arr, warr)
        )
        self._pend_bytes += nbytes
        sess.elements += n
        self._metrics.ingested_elements += n
        if self._auditor is not None:
            # sample-quality plane (ISSUE 7): stratum ingest ledger —
            # a no-op (one global load, one None test) while obs is off
            self._auditor.record_ingest(key, arr)
        if self._pend_bytes >= self._coalesce_bytes and not saturated:
            self._flush_pending()
        return n

    def _retry_hint(self) -> float:
        """Retry-after estimate: the observed per-flush dispatch time (what
        a permit actually takes to free), floored at ``retry_after_s``."""
        m = self._bridge.metrics
        per_flush = m.dispatch_s / m.flushes if m.flushes else 0.0
        return max(self._retry_after_s, per_flush)

    def _flush_pending(self) -> None:
        """Ship the coalesce buffer as one interleaved push (rows filling
        mid-batch flush tiles to the device as they do on the raw bridge)."""
        if not self._pend:
            return
        reg = _obs.get()
        if reg is not None:
            # coalesce occupancy: how full the cross-session buffer was
            # when it shipped (1.0 = exactly at threshold; < 1.0 = a
            # barrier flushed it early) — the `coalesce_bytes` tuning lever
            reg.histogram(
                self._scoped("serve.coalesce_fill"), lo=1e-3, hi=10.0
            ).observe(self._pend_bytes / self._coalesce_bytes)
        tr = _trace.get()
        ship_cm = contextlib.nullcontext()
        if tr is not None:
            # coalesce wait: age of the buffer when it ships.  Detached —
            # it spans many ingest calls' wall time, so folding it into
            # one call's trace would break the attribution reconciliation.
            marker = tr.point(
                "serve.coalesce_wait",
                force=False,
                detached=True,
                pending_bytes=self._pend_bytes,
                flush_seq=self._bridge.flushed_seq,
            )
            marker.duration_s = time.perf_counter() - self._pend_t0
            ship_cm = tr.span(
                "serve.ship", pending_bytes=self._pend_bytes
            )
        pend, self._pend, self._pend_bytes = self._pend, [], 0
        with ship_cm:
            streams = np.concatenate([p[0] for p in pend])
            elems = np.concatenate([p[1] for p in pend])
            warr = (
                np.concatenate([p[2] for p in pend])
                if self._config.weighted
                else None
            )
            self._bridge.push_interleaved(streams, elems, warr)
            # kick rows the demux filled to the device now instead of
            # waiting for the next push to overflow them — but never at the
            # cost of blocking the ingest path (the pipeline overlaps the
            # dispatch)
            if not self._bridge.flush_would_block():
                self._bridge.flush()

    def sync(self) -> int:
        """Barrier: coalesce buffer -> staging -> device, then wait out the
        pipeline.  Returns the durable ``flushed_seq`` watermark — after
        sync, every accepted element is journaled/applied and visible to
        snapshots."""
        self._maybe_sweep()
        self._flush_pending()
        self._bridge.flush()
        self._bridge.drain_barrier()
        return self._bridge.flushed_seq

    # ------------------------------------------------------- live migration

    def export_rows(self, rows: Any) -> Any:
        """Drain everything pending, then export the state of ``rows`` as
        a fresh pytree (the source half of a live migration, ISSUE 12).
        The sync barrier first makes the export a consistent cut: every
        accepted element for those rows is reflected in it."""
        self.sync()
        return self._bridge.engine.export_rows(rows)

    def adopt_rows(self, rows: Any, sub_state: Any) -> None:
        """Adopt exported reservoir rows into this service's engine (the
        destination half of a live migration, ISSUE 12).  Journaled as one
        RTJA frame by the bridge; the snapshot cache epoch bumps so no
        cached read can serve the rows' previous contents."""
        self.sync()  # pending elements precede the adopt (stream order)
        self._bridge.adopt_rows(rows, sub_state)
        self._reset_epoch += 1

    # ------------------------------------------------------------ snapshots

    def snapshot(self, key: str, sync: bool = True) -> np.ndarray:
        """LIVE per-session result read — non-destructive, any number of
        times, while the session keeps streaming (the ``peek`` path; the
        raw engine's ``result()`` stays terminal and untouched).

        ``sync=True`` (default) gives read-your-writes: everything this
        thread ingested is flushed and visible.  ``sync=False`` serves the
        current durable watermark only (pending coalesced elements are not
        yet visible) — cheaper under heavy ingest.

        Reads are served from a whole-table device->host snapshot cache
        keyed by ``(flushed_seq, reset_epoch)``: N sessions polling between
        flushes cost ONE device readback, not N."""
        self._maybe_sweep()
        reg = _obs.get()
        t0 = time.perf_counter() if reg is not None else 0.0
        sess = self._table.route(key)
        self._table.check(sess)  # generation guard: no stale-row reads
        if sync:
            self.sync()
        else:
            # peek shares the engine's single-writer slot with the worker
            self._bridge.drain_barrier()
        cache_key = (self._bridge.flushed_seq, self._reset_epoch)
        if self._snap_key != cache_key:
            self._snap = self._bridge.engine.peek_arrays()
            self._snap_key = cache_key
            self._snap_at = time.monotonic()
            self._metrics.snapshot_misses += 1
        else:
            self._metrics.snapshot_hits += 1
        samples, sizes = self._snap
        out = samples[sess.row, : int(sizes[sess.row])].copy()
        if self._auditor is not None and sync:
            # sample-quality plane (ISSUE 7): rolling KS pool + stratum
            # inclusion counts; n is this session's own stream length.
            # Only the read-your-writes path feeds the auditor — a
            # sync=False read can trail sess.elements by the coalesce
            # backlog, which would register as low-position bias that the
            # sampler never committed.
            self._auditor.observe_snapshot(key, out, sess.elements)
        if reg is not None:
            # sync=True reads pay a flush barrier — a different latency
            # population than the live cache-read path; keep the two
            # histograms separate so `snapshot_p*` stays the live number
            reg.histogram(
                self._scoped(
                    "serve.snapshot_sync_s" if sync else "serve.snapshot_s"
                )
            ).observe(time.perf_counter() - t0)
            # staleness: age of the device->host snapshot this read was
            # served from (0-ish on a miss; grows while the cache serves)
            reg.histogram(
                self._scoped("serve.snapshot_staleness_s")
            ).observe(time.monotonic() - self._snap_at)
        return out

    # ------------------------------------------------------------- recovery

    @classmethod
    def recover(
        cls,
        checkpoint_dir: str,
        *,
        ttl_s: Optional[float] = None,
        coalesce_bytes: Optional[int] = None,
        max_inflight_bytes: Optional[int] = None,
        retry_after_s: float = 0.05,
        sweep_interval_s: Optional[float] = _UNSET,
        auditor: Optional[Any] = None,
        obs_scope: Optional[str] = None,
        pipelined: Optional[bool] = None,
        retry_policy: Optional[RetryPolicy] = None,
        flush_timeout_s: Optional[float] = None,
        checkpoint_every: Optional[int] = None,
        durability: Optional[str] = None,
        faults: Optional[Any] = None,
        device: Optional[Any] = None,
    ) -> "ReservoirService":
        """Rebuild a crashed service from ``checkpoint_dir``.

        Two journals replay together: the bridge's checkpoint + tile
        journal rebuild the reservoirs, and ``sessions.jsonl`` rebuilds
        the session table (leases, rows, generations, free-list order).
        Row resets from session recycling are re-applied *between* the
        replayed flushes they originally fell between (the ``replay_hook``
        protocol), so recovered reservoirs are bit-identical to an
        uninterrupted run — pinned by ``tests/test_serve.py``.

        Elements ingested but never flushed (the coalesce buffer at crash
        time) are not recoverable — they never left the producer's
        custody; producers resume from :attr:`flushed_seq`, exactly the
        raw bridge's contract."""
        header, ops = _read_session_journal(
            os.path.join(checkpoint_dir, _JOURNAL_NAME)
        )
        if ttl_s is None:
            ttl_s = header.get("ttl_s")  # default to the crashed service's
        table = SessionTable(
            int(header["rows"]), ttl_s=ttl_s, seed=int(header["seed"])
        )
        resets: List[Tuple[int, int, int]] = []  # (at_seq, row, gen)
        for rec in ops:
            if rec["op"] == "open":
                sess, evicted = table.open(rec["key"])
                if evicted or sess.row != rec["row"] or (
                    sess.generation != rec["gen"]
                ):
                    raise ValueError(
                        f"session journal replay diverged at {rec!r}: "
                        f"rebuilt lease (row={sess.row}, "
                        f"gen={sess.generation}) does not match the record"
                    )
                if sess.generation > 0:
                    resets.append(
                        (int(rec["at_seq"]), sess.row, sess.generation)
                    )
            elif rec["op"] in ("close", "evict"):
                table.close(rec["key"])
            else:
                raise ValueError(
                    f"session journal: unknown op {rec.get('op')!r}"
                )
        # interleave journaled row resets into the tile replay at their
        # original positions; resets the checkpoint already covers
        # (at_seq < covered) are skipped — they are baked into its state
        cursor = {"i": 0, "covered": None}

        def replay_hook(bridge: DeviceStreamBridge, watermark: int) -> None:
            if cursor["covered"] is None:
                cursor["covered"] = watermark
                while (
                    cursor["i"] < len(resets)
                    and resets[cursor["i"]][0] < watermark
                ):
                    cursor["i"] += 1
            while (
                cursor["i"] < len(resets)
                and resets[cursor["i"]][0] <= watermark
            ):
                _, row, gen = resets[cursor["i"]]
                bridge.engine.reset_rows([row], table.sub_key(row, gen))
                cursor["i"] += 1

        bridge = DeviceStreamBridge.recover(
            checkpoint_dir,
            pipelined=pipelined,
            retry_policy=retry_policy,
            flush_timeout_s=flush_timeout_s,
            checkpoint_every=checkpoint_every,
            durability=durability,
            faults=faults,
            replay_hook=replay_hook,
            device=device,
        )
        if bridge._config.num_reservoirs != table.capacity:
            # recovery pre-flight (ISSUE-5 satellite): the two journals
            # must describe the SAME plane — a swapped/stale sessions.jsonl
            # would otherwise lease rows the engine does not have
            raise CheckpointMismatch(
                f"session journal in {checkpoint_dir!r} leases "
                f"{table.capacity} rows, but the engine checkpoint has "
                f"num_reservoirs={bridge._config.num_reservoirs}"
            )
        service = cls(
            bridge._config,
            ttl_s=ttl_s,
            coalesce_bytes=coalesce_bytes,
            max_inflight_bytes=max_inflight_bytes,
            retry_after_s=retry_after_s,
            sweep_interval_s=sweep_interval_s,
            auditor=auditor,
            obs_scope=obs_scope,
            faults=faults,
            checkpoint_dir=checkpoint_dir,
            _bridge=bridge,
            _table=table,
        )
        service._metrics.recoveries += 1
        return service

    # ------------------------------------------------------------- teardown

    def shutdown(self) -> None:
        """Flush everything pending, wait out the pipeline, and close the
        session journal.  Sessions stay leased (the table is durable via
        the journal) — this is a clean process exit, not a mass close."""
        self.sync()
        if self._journal_fh is not None:
            self._journal_fh.close()
            self._journal_fh = None

    def __del__(self) -> None:
        fh = getattr(self, "_journal_fh", None)
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass
