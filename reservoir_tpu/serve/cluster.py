"""Sharded serving plane: N independent shard units behind one front-end.

ROADMAP item 1's horizontal-scale story, built as a *robustness* layer
(ISSUE 9): :class:`ShardedReservoirService` fronts N fully independent
:class:`~reservoir_tpu.serve.shard.ShardUnit` failure domains — engine +
bridge + journal/checkpoint directory + epoch fence + optional hot
standby each — so one demoted, wedged, or fenced shard degrades exactly
``1/N`` of the key space while every other shard keeps serving.

Design points:

- **deterministic routing** — ``shard_of(key) = crc32(routing_epoch:key)
  % n_shards``: a stable hash with a pinned *routing epoch*, the
  split-by-hash discipline of Sanders et al.'s SIMD stream partitioning
  (arXiv:1610.05141) applied at session granularity.  The header of
  ``routing.jsonl`` journals ``(n_shards, routing_epoch, key)`` and every
  open appends a ``route`` record, so :meth:`recover` provably re-routes
  identically (each replayed record is cross-checked against the hash;
  a torn tail — crash mid-append — is dropped, same tolerance as every
  other journal in the stack).
- **per-shard admission and partial degradation** — a saturated shard's
  :class:`~reservoir_tpu.errors.ServiceSaturated` already only rejects
  its own sessions; a fenced or killed shard rejects with the new
  :class:`~reservoir_tpu.errors.ShardUnavailable` (a ``ServiceSaturated``
  subclass carrying ``shard`` + ``retry_after_s``), and nothing routed
  elsewhere notices.  The ``shard.route`` fault site fires on every
  resolution; injected failures surface as typed per-call
  :class:`~reservoir_tpu.errors.SessionIngestError` — the routing table
  and the cluster stay live.
- **cluster health over shard-scoped HA** — each unit runs the PR-5
  heartbeat/controller loop against its own directory; :meth:`beat`
  aggregates the per-shard beats into ONE cluster ``heartbeat.json``
  (per-shard epoch/seq/lag/SLO rows + the worst verdict) that
  ``tools/reservoir_top.py`` renders as a per-shard panel.
- **cross-shard merged snapshots** — *Parallel Streaming Random
  Sampling* (arXiv:1906.04120) makes per-shard reservoirs mergeable into
  one logical sample; :meth:`merged_snapshot` reads each named session
  at its shard and merges with
  :func:`~reservoir_tpu.parallel.merge.merge_samples_host` — the exact
  hypergeometric pairwise merge in a deterministic log-depth tree, so
  the result bit-reconciles with a single-shard oracle merging the same
  per-session oracle replays (pinned by ``tests/test_cluster.py``).

Single-writer like everything below: one thread drives the cluster.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import SamplerConfig
from ..errors import (
    FencedError,
    SessionIngestError,
    ShardUnavailable,
)
from ..obs import registry as _obs
from ..obs import trace as _ctrace
from ..utils import faults as _faults
from ..utils.tracing import trace_span
from .service import ReservoirService
from .shard import ShardUnit

__all__ = ["ShardedReservoirService", "shard_of"]

_ROUTING_NAME = "routing.jsonl"
_ROUTING_VERSION = 1
_HEARTBEAT_NAME = "heartbeat.json"

#: Verdict severity order shared with the SLO plane.
_SEVERITY = {"ok": 0, "warn": 1, "page": 2}


def shard_of(key: str, n_shards: int, routing_epoch: int = 0) -> int:
    """The deterministic session->shard route: a stable 32-bit hash of
    ``routing_epoch:key`` mod ``n_shards``.  Pure function — recovery,
    standbys, and external routers all agree by construction; bumping
    ``routing_epoch`` re-deals the whole key space (the future live-
    resharding lever of ROADMAP item 2)."""
    h = zlib.crc32(f"{routing_epoch}:{key}".encode("utf-8"))
    return h % int(n_shards)


def _resolve_devices(devices: Optional[Any], n_shards: int) -> List[Any]:
    """Normalize the cluster ``devices=`` knob into one entry per shard:
    ``None`` -> all-None (backend default placement), ``"spread"`` ->
    round-robin over the addressable devices, a sequence -> validated
    verbatim (length must match — silent truncation would strand shards
    on the wrong chip)."""
    if devices is None:
        return [None] * n_shards
    if isinstance(devices, str):
        if devices != "spread":
            raise ValueError(
                f"devices= accepts None, 'spread', or a sequence of "
                f"{n_shards} devices; got {devices!r}"
            )
        from ..parallel.multihost import spread_devices

        return list(spread_devices(n_shards))
    devs = list(devices)
    if len(devs) != n_shards:
        raise ValueError(
            f"devices= sequence has {len(devs)} entries for "
            f"{n_shards} shards"
        )
    return devs


class ShardedReservoirService:
    """N independent shard units behind one session-keyed front-end.

    The public surface mirrors :class:`ReservoirService` — open/ingest/
    snapshot/close/sync — so traffic harnesses (``tools/loadgen.py``)
    drive a cluster unchanged; each call routes to exactly one shard and
    fails (typed, with ``retry_after_s``) only with that shard.

    Args:
      config: PER-SHARD engine config (total capacity =
        ``n_shards * config.num_reservoirs``).
      n_shards: shard count (pinned in the routing journal).
      cluster_dir: the cluster's root directory; shard ``i`` owns
        ``<cluster_dir>/shard<i>`` and the cluster itself journals
        routing (``routing.jsonl``) and aggregates health
        (``heartbeat.json``) here.
      key: base engine seed; shard ``i`` seeds its engine with
        ``key + 7919 * i`` (distinct, deterministic, replayable — kept on
        each unit's ``engine_seed`` for oracle replays).
      routing_epoch: the pinned routing-epoch of :func:`shard_of`.
      standby: run a hot standby + failover controller per shard.
      retry_after_s: the retry hint a down shard's
        :class:`ShardUnavailable` carries.
      faults: fault plane reaching the cluster's ``shard.*`` sites and
        every unit's lower-layer sites.
      devices: per-shard device placement — ``None`` (backend default),
        ``"spread"`` (round-robin the addressable devices via
        :func:`~reservoir_tpu.parallel.multihost.spread_devices`), or an
        explicit sequence of ``n_shards`` ``jax.Device``s.  Shard ``i``'s
        engine state is pinned to its device, so :meth:`migrate` ships
        rows device-to-device instead of through the host.
      **shard_kwargs: forwarded to every :class:`ShardUnit` (and through
        it to each :class:`ReservoirService`): ``ttl_s``, ``gated``,
        ``coalesce_bytes``, ``durability``, ``heartbeat_timeout_s``, ...
    """

    def __init__(
        self,
        config: SamplerConfig,
        n_shards: int,
        cluster_dir: str,
        *,
        key: int = 0,
        routing_epoch: int = 0,
        standby: bool = True,
        retry_after_s: float = 0.05,
        faults: Optional[Any] = None,
        devices: Optional[Any] = None,
        _units: Optional[List[ShardUnit]] = None,
        **shard_kwargs: Any,
    ) -> None:
        if n_shards < 1:
            raise ValueError("n_shards must be positive")
        self._config = config
        self.n_shards = int(n_shards)
        self.cluster_dir = cluster_dir
        self.routing_epoch = int(routing_epoch)
        self._base_key = int(key)
        self._retry_after_s = float(retry_after_s)
        self._faults = faults
        #: session-key -> shard overrides left by :meth:`migrate`; consulted
        #: before the hash so migrated keys keep landing on their new home.
        self._overrides: Dict[str, int] = {}
        os.makedirs(cluster_dir, exist_ok=True)
        if _units is not None:
            self._units = _units
            self._routing_fh = open(
                os.path.join(cluster_dir, _ROUTING_NAME),
                "a",
                encoding="utf-8",
            )
        else:
            devs = _resolve_devices(devices, self.n_shards)
            self._units = [
                ShardUnit(
                    config,
                    i,
                    self.shard_dir(i),
                    key=self.shard_seed(i),
                    standby=standby,
                    faults=faults,
                    device=devs[i],
                    **shard_kwargs,
                )
                for i in range(self.n_shards)
            ]
            self._routing_fh = open(
                os.path.join(cluster_dir, _ROUTING_NAME),
                "w",
                encoding="utf-8",
            )
            self._append_routing(
                {
                    "op": "base",
                    "v": _ROUTING_VERSION,
                    "shards": self.n_shards,
                    "routing_epoch": self.routing_epoch,
                    "key": self._base_key,
                }
            )

    # ------------------------------------------------------------ structure

    def shard_dir(self, shard: int) -> str:
        return os.path.join(self.cluster_dir, f"shard{int(shard)}")

    def shard_seed(self, shard: int) -> int:
        """Shard ``i``'s engine seed: distinct per shard, derived from the
        cluster base key deterministically (oracle replays re-derive it)."""
        return self._base_key + 7919 * int(shard)

    @property
    def config(self) -> SamplerConfig:
        return self._config

    @property
    def units(self) -> List[ShardUnit]:
        return self._units

    def unit(self, shard: int) -> ShardUnit:
        return self._units[int(shard)]

    def _append_routing(self, rec: dict) -> None:
        self._routing_fh.write(json.dumps(rec) + "\n")
        self._routing_fh.flush()

    # -------------------------------------------------------------- routing

    def shard_of(self, key: str) -> int:
        """Resolve ``key``'s shard (no fault site, no journal): the
        :meth:`migrate` override if one exists, else the pinned hash."""
        ov = self._overrides.get(key)
        if ov is not None:
            return ov
        return shard_of(key, self.n_shards, self.routing_epoch)

    def _route(self, key: str) -> Tuple[ShardUnit, int]:
        """The serving-path resolution: fires the ``shard.route`` fault
        site (injected failures surface as a typed per-call
        :class:`SessionIngestError` — the cluster stays live) and turns a
        down shard into :class:`ShardUnavailable` scoped to it."""
        tr = _ctrace.get()
        cm = (
            tr.span("cluster.route", key=key, session=key)
            if tr is not None
            else contextlib.nullcontext()
        )
        with cm, trace_span("reservoir_cluster_route"):
            return self._route_impl(key, tr)

    def _route_impl(
        self, key: str, tr: Optional[Any]
    ) -> Tuple[ShardUnit, int]:
        try:
            _faults.fire("shard.route", self._faults)
        except Exception as e:
            raise SessionIngestError(
                key, f"shard routing failed: {type(e).__name__}: {e}"
            ) from e
        shard = self.shard_of(key)
        unit = self._units[shard]
        if not unit.alive:
            if tr is not None:
                # a routed-to-dead-shard reject is exactly the trace a
                # postmortem wants: force it past the sampler
                tr.point(
                    "cluster.reject",
                    session=key,
                    shard=shard,
                    error="ShardUnavailable",
                    reason=unit.unavailable_reason or "unavailable",
                )
            raise ShardUnavailable(
                f"session {key!r} routes to shard {shard}, which is "
                f"{unit.unavailable_reason or 'unavailable'}; retry after "
                "failover/recovery completes",
                retry_after_s=self._retry_after_s,
                shard=shard,
                reason=unit.unavailable_reason or "unavailable",
            )
        return unit, shard

    def _guard(self, unit: ShardUnit, shard: int, exc: FencedError):
        """A delegated call hit the shard's fence mid-flight: the primary
        is a zombie (a standby was promoted, or a chaos fence landed).
        Mark the shard down and re-raise scoped — every other shard is
        untouched."""
        unit.mark_fenced()
        tr = _ctrace.get()
        if tr is not None:
            tr.point(
                "cluster.reject",
                shard=shard,
                error="FencedError",
                reason="fenced",
                epoch=exc.observed_epoch,
            )
        raise ShardUnavailable(
            f"shard {shard} primary is fenced (epoch "
            f"{exc.observed_epoch} > {exc.own_epoch}); promote its standby "
            "or recover it",
            retry_after_s=self._retry_after_s,
            shard=shard,
            reason="fenced",
        ) from exc

    # ------------------------------------------------------------- sessions

    def open_session(self, key: str):
        """Lease ``key`` on its (deterministic) shard; the route is
        journaled so recovery re-routes identically."""
        unit, shard = self._route(key)
        try:
            sess = unit.service.open_session(key)
        except FencedError as e:
            self._guard(unit, shard, e)
        self._append_routing({"op": "route", "key": key, "shard": shard})
        _obs.emit(
            "shard.route", site="shard.route", session=key, shard=shard
        )
        return sess

    def ingest(self, key: str, elements: Any, weights: Optional[Any] = None) -> int:
        tr = _ctrace.get()
        if tr is None:
            return self._ingest_impl(key, elements, weights)
        with tr.span("cluster.ingest", key=key, session=key):
            return self._ingest_impl(key, elements, weights)

    def _ingest_impl(
        self, key: str, elements: Any, weights: Optional[Any]
    ) -> int:
        unit, shard = self._route(key)
        try:
            return unit.service.ingest(key, elements, weights)
        except FencedError as e:
            self._guard(unit, shard, e)

    def snapshot(self, key: str, sync: bool = True) -> np.ndarray:
        unit, shard = self._route(key)
        try:
            return unit.service.snapshot(key, sync=sync)
        except FencedError as e:
            self._guard(unit, shard, e)

    def close_session(self, key: str) -> np.ndarray:
        unit, shard = self._route(key)
        try:
            return unit.service.close_session(key)
        except FencedError as e:
            self._guard(unit, shard, e)

    # ------------------------------------------------------- live migration

    def migrate(self, key: str, dst_shard: int) -> Any:
        """Move ``key``'s live reservoir row to ``dst_shard`` without
        losing an element or serving a stale row.

        The move is fence-then-drain on the source (close the lease, so
        the source row's generation bumps and any straggler touch raises
        :class:`~reservoir_tpu.errors.StaleSessionError`), ship the row's
        state device-to-device (``jax.device_put`` straight onto the
        destination's pinned device; host staging when unpinned), then
        reset-and-adopt on the destination at a journaled adopt record.
        The routing override is journaled LAST — every crash window fails
        CLOSED: before the record lands, ``key`` still routes to the
        source, where the session is already closed, so a caller gets
        :class:`~reservoir_tpu.errors.UnknownSessionError` (never a stale
        or double-served row; at worst one orphaned lease leaks on the
        destination until its TTL sweep).  :meth:`recover` and the
        standbys replay the same records bit-exactly.

        Returns the destination's new :class:`~.sessions.Session`.
        """
        import jax

        dst_shard = int(dst_shard)
        if not 0 <= dst_shard < self.n_shards:
            raise ValueError(
                f"dst_shard {dst_shard} out of range [0, {self.n_shards})"
            )
        src_unit, src_shard = self._route(key)
        if dst_shard == src_shard:
            raise ValueError(
                f"session {key!r} already lives on shard {src_shard}"
            )
        dst_unit = self._units[dst_shard]
        if not dst_unit.alive:
            raise ShardUnavailable(
                f"migration target shard {dst_shard} is "
                f"{dst_unit.unavailable_reason or 'unavailable'}",
                retry_after_s=self._retry_after_s,
                shard=dst_shard,
                reason=dst_unit.unavailable_reason or "unavailable",
            )
        reg = _obs.get()
        t0 = time.perf_counter()
        tr = _ctrace.get()
        cm = (
            tr.span(
                "cluster.migrate",
                force=True,
                session=key,
                src=src_shard,
                dst=dst_shard,
            )
            if tr is not None
            else contextlib.nullcontext()
        )
        with cm, trace_span("reservoir_cluster_migrate"):
            try:
                sess = src_unit.service.table.route(key)
                elements = int(sess.elements)
                # export drains the source first (sync inside), so the
                # shipped state holds every ingested element
                sub = src_unit.service.export_rows([sess.row])
                # device-to-device when the destination is pinned; the
                # backend default device otherwise (np staging would drop
                # the typed per-row PRNG keys)
                dst_dev = dst_unit.service.device
                if dst_dev is None:
                    dst_dev = jax.devices()[0]
                shipped = jax.device_put(sub, dst_dev)
                src_unit.service.close_session(key)
            except FencedError as e:
                self._guard(src_unit, src_shard, e)
            try:
                new_sess = dst_unit.service.open_session(key)
                dst_unit.service.adopt_rows([new_sess.row], shipped)
                new_sess.elements = elements
            except FencedError as e:
                self._guard(dst_unit, dst_shard, e)
        self._overrides[key] = dst_shard
        self._append_routing(
            {
                "op": "migrate",
                "key": key,
                "src": src_shard,
                "dst": dst_shard,
                "elements": elements,
            }
        )
        dt = time.perf_counter() - t0
        if reg is not None:
            reg.histogram("cluster.migrate_s").observe(dt)
        _obs.emit(
            "shard.migrate",
            site="shard.migrate",
            session=key,
            src=src_shard,
            dst=dst_shard,
            elements=elements,
        )
        return new_sess

    def sync(self) -> Dict[int, int]:
        """Barrier every LIVE shard; returns ``{shard: flushed_seq}``.
        A shard hitting its fence mid-sync is marked down and skipped —
        partial degradation, not a cluster-wide failure."""
        seqs: Dict[int, int] = {}
        for unit in self._units:
            if not unit.alive:
                continue
            try:
                seqs[unit.shard_id] = unit.service.sync()
            except FencedError:
                unit.mark_fenced()
        return seqs

    def sessions_open(self) -> int:
        return sum(
            len(u.service.table) for u in self._units if u.alive
        )

    # ------------------------------------------------------------ HA plane

    def poll(self) -> int:
        """One replication step on every shard's standby; returns total
        sequences advanced."""
        return sum(unit.poll() for unit in self._units)

    def health(self) -> Dict[int, Any]:
        """Per-shard controller verdicts (shards without standbys omitted)."""
        out = {}
        for unit in self._units:
            report = unit.health()
            if report is not None:
                out[unit.shard_id] = report
        return out

    def maybe_promote(self) -> List[Tuple[int, str]]:
        """One cluster control-loop step: promote every shard whose OWN
        health verdict says so; returns ``[(shard, reason), ...]``."""
        promoted = []
        for unit in self._units:
            report = unit.health()
            if report is None or not report.should_promote:
                continue
            unit.promote(
                reason="; ".join(report.reasons) or "unhealthy",
                triggers=report.triggers,
            )
            promoted.append((unit.shard_id, ",".join(report.triggers)))
        return promoted

    def kill_shard(self, shard: int):
        return self._units[int(shard)].kill()

    def fence_shard(self, shard: int) -> int:
        return self._units[int(shard)].fence()

    def promote_shard(self, shard: int, reason: str = "manual"):
        return self._units[int(shard)].promote(reason=reason)

    def recover_shard(self, shard: int, **kwargs):
        return self._units[int(shard)].recover(**kwargs)

    def beat(self) -> dict:
        """Beat every live shard, then aggregate ONE cluster heartbeat
        (``<cluster_dir>/heartbeat.json``, atomic): per-shard
        epoch/seq/lag/SLO rows plus the worst verdict — what
        ``tools/reservoir_top.py`` renders as the per-shard panel.  A
        shard whose beacon fails (fenced zombie, injected fault) is
        recorded down, never skipped silently."""
        shards: Dict[str, dict] = {}
        worst = "ok"
        for unit in self._units:
            try:
                unit.beat()
                row = unit.status()
            except Exception as e:  # fenced/faulted beacon: the row says so
                row = unit.status()
                row["beat_error"] = f"{type(e).__name__}: {e}"
            if not row.get("alive"):
                worst = "page"
            worst = max(
                (worst, row.get("slo_worst", "ok")),
                key=lambda v: _SEVERITY.get(v, 0),
            )
            shards[str(unit.shard_id)] = row
        payload = {
            "ts": time.time(),
            "cluster": True,
            "n_shards": self.n_shards,
            "routing_epoch": self.routing_epoch,
            "sessions_open": self.sessions_open(),
            "worst": worst,
            "shards": shards,
        }
        import tempfile

        fd, tmp = tempfile.mkstemp(dir=self.cluster_dir, suffix=".tmp.hb")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(payload, fh)
            os.replace(tmp, os.path.join(self.cluster_dir, _HEARTBEAT_NAME))
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return payload

    # ------------------------------------------------------ merged snapshots

    def merged_snapshot(
        self,
        keys: Sequence[str],
        *,
        merge_key: int = 0,
        sync: bool = True,
        device: Optional[str] = None,
    ) -> np.ndarray:
        """One logical uniform sample over the named sessions' combined
        streams, merged across shards with the exact mergeable-reservoir
        math (arXiv:1906.04120 via
        :func:`~reservoir_tpu.parallel.merge.merge_samples_host`).
        Deterministic for a fixed ``merge_key`` and key order, and
        bit-reconcilable with a single-shard oracle merging per-session
        oracle replays with the same function.  Uniform (plain) mode
        only — weighted/distinct merges are state-keyed and ride the mesh
        mergers in :mod:`reservoir_tpu.parallel.merge`.

        ``device=None`` merges on the host; ``"auto"``/``"xla"``/
        ``"pallas"`` runs the same deterministic merge tree as a device
        collective
        (:func:`~reservoir_tpu.parallel.merge.merge_samples_device`) —
        bit-identical by construction, timed under
        ``cluster.merge_device_s`` instead of ``cluster.merge_s``."""
        if self._config.weighted or self._config.distinct:
            raise ValueError(
                "merged_snapshot is uniform-mode only: weighted/distinct "
                "merges need state-level keys (ES keys / hash planes); use "
                "the mesh mergers in reservoir_tpu.parallel.merge"
            )
        if not keys:
            raise ValueError("merged_snapshot needs at least one session key")
        from ..parallel.merge import merge_samples_device, merge_samples_host

        reg = _obs.get()
        t0 = time.perf_counter() if reg is not None else 0.0
        parts = []
        for key in keys:
            unit, _ = self._route(key)
            sample = unit.service.snapshot(key, sync=sync)
            parts.append((sample, unit.service.table.route(key).elements))
        if device is None:
            merged, _total = merge_samples_host(
                parts, merge_key, max_sample_size=self._config.max_sample_size
            )
        else:
            merged, _total = merge_samples_device(
                parts,
                merge_key,
                max_sample_size=self._config.max_sample_size,
                impl=device,
            )
            merged = np.asarray(merged)
        if reg is not None:
            name = "cluster.merge_s" if device is None else "cluster.merge_device_s"
            reg.histogram(name).observe(time.perf_counter() - t0)
        return merged

    # -------------------------------------------------------------- recovery

    @classmethod
    def recover(
        cls,
        cluster_dir: str,
        *,
        standby: bool = True,
        retry_after_s: float = 0.05,
        faults: Optional[Any] = None,
        devices: Optional[Any] = None,
        **shard_kwargs: Any,
    ) -> "ShardedReservoirService":
        """Rebuild a crashed cluster from ``cluster_dir``.

        The routing journal's header re-pins ``(n_shards, routing_epoch,
        key)`` — the entire routing function — so every session re-routes
        identically; each replayed ``route`` record is cross-checked
        against the hash *with the migration overrides replayed in
        order* (a ``migrate`` record re-homes its key exactly as the live
        :meth:`migrate` did; divergence is a hard error, it would strand
        sessions on the wrong shard) and a torn final line is dropped
        (crash mid-append: the open it described is re-journaled by the
        shard's own session journal or never happened; a torn ``migrate``
        fails CLOSED — the key re-routes to its source, whose session
        journal already closed the lease).  Each shard then recovers
        independently via :meth:`ReservoirService.recover` — including
        the ISSUE-9 epoch pre-flight, so a shard whose lineage was fenced
        by a promotion fails typed instead of double-serving.  Element
        counts for migrated sessions (plain session-table state, not
        engine state) are restored from the last ``migrate`` record per
        key.  ``devices=`` re-pins shard engines exactly as at
        construction — placement is process-local, never journaled."""
        path = os.path.join(cluster_dir, _ROUTING_NAME)
        with open(path, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        records: List[dict] = []
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    break  # torn tail: crash mid-append, dropped
                raise ValueError(
                    f"{path!r}: corrupt routing journal at line {i + 1}"
                )
        if not records or records[0].get("op") != "base":
            raise ValueError(
                f"{path!r}: routing journal has no base header record"
            )
        header = records[0]
        n_shards = int(header["shards"])
        routing_epoch = int(header["routing_epoch"])
        base_key = int(header["key"])
        overrides: Dict[str, int] = {}
        migrated: Dict[str, dict] = {}
        for rec in records[1:]:
            op = rec.get("op")
            if op == "route":
                want = overrides.get(
                    rec["key"],
                    shard_of(rec["key"], n_shards, routing_epoch),
                )
                if int(rec["shard"]) != want:
                    raise ValueError(
                        f"routing journal replay diverged at {rec!r}: the "
                        f"pinned routing function routes {rec['key']!r} to "
                        f"shard {want}"
                    )
            elif op == "migrate":
                want = overrides.get(
                    rec["key"],
                    shard_of(rec["key"], n_shards, routing_epoch),
                )
                if int(rec["src"]) != want:
                    raise ValueError(
                        f"routing journal replay diverged at {rec!r}: "
                        f"{rec['key']!r} lived on shard {want}, not "
                        f"{rec['src']}"
                    )
                overrides[rec["key"]] = int(rec["dst"])
                migrated[rec["key"]] = rec
            else:
                raise ValueError(
                    f"routing journal: unknown op {op!r}"
                )
        devs = _resolve_devices(devices, n_shards)
        units = []
        for i in range(n_shards):
            shard_dir = os.path.join(cluster_dir, f"shard{i}")
            service = ReservoirService.recover(
                shard_dir,
                obs_scope=f"shard{i}",
                faults=faults,
                device=devs[i],
                **{
                    k: v
                    for k, v in shard_kwargs.items()
                    if k in (
                        "ttl_s", "coalesce_bytes", "max_inflight_bytes",
                        "retry_after_s", "sweep_interval_s", "auditor",
                        "retry_policy", "flush_timeout_s",
                        "checkpoint_every", "durability", "pipelined",
                    )
                },
            )
            units.append(
                ShardUnit(
                    service.config,
                    i,
                    shard_dir,
                    key=base_key + 7919 * i,
                    standby=standby,
                    faults=faults,
                    device=devs[i],
                    _service=service,
                    **shard_kwargs,
                )
            )
        inst = cls(
            units[0].service.config,
            n_shards,
            cluster_dir,
            key=base_key,
            routing_epoch=routing_epoch,
            standby=standby,
            retry_after_s=retry_after_s,
            faults=faults,
            _units=units,
        )
        inst._overrides = overrides
        # Session.elements is front-end bookkeeping the shard journals
        # don't carry for an adopted row; the migrate record does.
        for key, rec in migrated.items():
            table = units[int(rec["dst"])].service.table
            if key in table:
                table.route(key).elements = int(rec["elements"])
        return inst

    # -------------------------------------------------------------- teardown

    def metrics_snapshot(self) -> dict:
        """Per-shard metric blocks plus cluster totals (bench evidence)."""
        shards = {
            str(u.shard_id): (
                u.service.metrics.snapshot() if u.alive else None
            )
            for u in self._units
        }
        live = [u.service.metrics for u in self._units if u.alive]
        return {
            "shards": shards,
            "ingested_elements": sum(m.ingested_elements for m in live),
            "rejections": sum(m.rejections for m in live),
            "sessions_open": self.sessions_open(),
        }

    def shutdown(self) -> None:
        for unit in self._units:
            if unit.alive:
                unit.shutdown()
        if self._routing_fh is not None:
            self._routing_fh.close()
            self._routing_fh = None

    def __del__(self) -> None:
        fh = getattr(self, "_routing_fh", None)
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass
