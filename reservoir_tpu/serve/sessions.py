"""Session table: lease/evict reservoir rows to opaque tenant session keys.

The batched engine runs tens of thousands of independent reservoirs per
device (*Parallel Streaming Random Sampling*, arXiv:1906.04120, is exactly
this many-independent-substream shape); what maps dynamically arriving
tenant sessions onto those rows is this table.  It is deliberately
host-only and device-free: pure bookkeeping a service front-end
(:mod:`reservoir_tpu.serve.service`) pairs with engine row resets.

Design points:

- **free-list + generation counters**: each row carries a monotonically
  increasing generation, bumped whenever the row is freed.  A
  :class:`Session` handle is a ``(row, generation)`` lease; :meth:`check`
  refuses a handle whose generation moved on
  (:class:`~reservoir_tpu.errors.StaleSessionError`) — a recycled row can
  never serve another tenant's read.
- **TTL + LRU eviction**: sessions idle past ``ttl_s`` are evictable
  (:meth:`sweep`), and :meth:`open` on a full table evicts the
  least-recently-used session (long-lived queryable handles in the style
  of *StreamSampling.jl*, arXiv:2603.21996, must not leak rows forever).
  Sweep cost is O(expired·log n), not O(n): every touch pushes an
  ``(expiry, seq, key)`` entry onto a lazy-deletion heap, and stale
  entries (the session was touched again, closed, or evicted since the
  push) are skipped on pop — the amortized-constant batching discipline
  of Sanders et al., arXiv:1610.05141, applied to TTL eviction so a
  million-session table never pays a full scan per sweep.
- **counter-keyed sub-seeds**: :meth:`sub_key` derives a per-lease Threefry
  key by folding ``(row, generation)`` into a table-level base key — the
  engine is never reseeded, yet every re-lease of a row gets a
  statistically fresh, *deterministically replayable* draw stream
  (the bit-exact-recovery contract of the serve plane).
"""

from __future__ import annotations

import heapq
import time
from collections import OrderedDict, deque
from typing import Callable, List, Optional, Tuple

from ..errors import StaleSessionError, UnknownSessionError

__all__ = ["Session", "SessionTable"]


class Session:
    """One live lease: session ``key`` owns reservoir ``row`` at
    ``generation``.  ``elements`` counts ingested elements (the service
    maintains it); ``opened_at``/``last_used`` drive TTL/LRU."""

    __slots__ = (
        "key", "row", "generation", "opened_at", "last_used", "elements"
    )

    def __init__(
        self, key: str, row: int, generation: int, now: float
    ) -> None:
        self.key = key
        self.row = row
        self.generation = generation
        self.opened_at = now
        self.last_used = now
        self.elements = 0

    def __repr__(self) -> str:  # debugging aid, not API
        return (
            f"Session({self.key!r}, row={self.row}, "
            f"gen={self.generation}, elements={self.elements})"
        )


class SessionTable:
    """Lease ``num_rows`` reservoir rows to opaque session keys.

    Args:
      num_rows: rows available for lease (the engine's ``num_reservoirs``).
      ttl_s: idle time after which a session becomes evictable by
        :meth:`sweep` / lazily on :meth:`route` (``None`` disables TTL).
      seed: base seed of the per-lease sub-key schedule (:meth:`sub_key`).
      clock: monotonic time source (injectable for tests).

    Single-writer like the engine and bridge it fronts: wrap calls in your
    own lock for multi-producer use.  Keys must be strings — they are
    journaled as JSON by the service's crash-recovery plane.
    """

    def __init__(
        self,
        num_rows: int,
        *,
        ttl_s: Optional[float] = None,
        seed: int = 0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if num_rows <= 0:
            raise ValueError("num_rows must be positive")
        self._rows = int(num_rows)
        self._ttl = ttl_s
        self._seed = int(seed)
        self._clock = clock
        self._free: deque = deque(range(self._rows))
        self._gen: List[int] = [0] * self._rows
        # insertion order == recency order (route() moves to end): the
        # front is always the LRU eviction candidate
        self._sessions: "OrderedDict[str, Session]" = OrderedDict()
        # lazy-deletion expiry heap: (last_used + ttl, push_seq, key).  A
        # touch pushes a fresh entry and orphans the old one; sweep skips
        # entries whose expiry no longer matches the session's live
        # last_used + ttl.  Bounded by periodic compaction (_maybe_compact)
        self._expiry: List[Tuple[float, int, str]] = []
        self._eseq = 0
        self._base_key = None  # jax key, built lazily (host-only until then)

    # ------------------------------------------------------------ introspection

    def __len__(self) -> int:
        return len(self._sessions)

    def __contains__(self, key: str) -> bool:
        return key in self._sessions

    @property
    def capacity(self) -> int:
        return self._rows

    @property
    def seed(self) -> int:
        return self._seed

    @property
    def ttl_s(self) -> Optional[float]:
        return self._ttl

    def sessions(self) -> List[Session]:
        """Live sessions in LRU order (least recently used first)."""
        return list(self._sessions.values())

    def generation_of(self, row: int) -> int:
        """Current generation of ``row`` (bumped each time it is freed)."""
        return self._gen[row]

    # ----------------------------------------------------------------- leasing

    def open(
        self, key: str, now: Optional[float] = None
    ) -> Tuple[Session, List[Session]]:
        """Lease a row to ``key``.  Returns ``(session, evicted)`` where
        ``evicted`` lists the LRU sessions removed to make room (at most
        one).  Raises ``ValueError`` for a key that is already open and
        :class:`UnknownSessionError` never — open is the entry point."""
        if not isinstance(key, str):
            raise TypeError(
                f"session keys must be str (journaled as JSON), got "
                f"{type(key).__name__}"
            )
        if key in self._sessions:
            raise ValueError(f"session {key!r} is already open")
        now = self._clock() if now is None else now
        evicted: List[Session] = []
        if not self._free:
            # TTL-expired sessions go first; otherwise the LRU one pays
            expired = self.sweep(now)
            evicted.extend(expired)
            if not self._free:
                lru_key = next(iter(self._sessions))
                evicted.append(self._remove(lru_key))
        row = self._free.popleft()
        sess = Session(key, row, self._gen[row], now)
        self._sessions[key] = sess
        self._push_expiry(sess)
        return sess, evicted

    def route(self, key: str, now: Optional[float] = None) -> Session:
        """Resolve ``key`` to its live session (refreshing LRU recency).

        TTL is a *lease* model, not a hard expiry: an idle session is
        evicted only under row pressure (:meth:`open`) or by an explicit
        :meth:`sweep` — never silently inside a lookup, because every
        eviction must be journalable by the service's crash-recovery
        plane.  Routing to an idle-but-unevicted session revives it."""
        sess = self._sessions.get(key)
        if sess is None:
            raise UnknownSessionError(
                f"session {key!r} is not open (never opened, closed, or "
                "evicted)"
            )
        sess.last_used = self._clock() if now is None else now
        self._sessions.move_to_end(key)
        self._push_expiry(sess)
        return sess

    def check(self, sess: Session) -> None:
        """Validate a held handle: the lease must still be current.  Raises
        :class:`StaleSessionError` when the row's generation moved past the
        handle (the row was freed, and possibly re-leased) — the guard that
        makes a recycled row unable to serve a stale read."""
        live = self._sessions.get(sess.key)
        if live is sess and self._gen[sess.row] == sess.generation:
            return
        raise StaleSessionError(
            f"session {sess.key!r} handle is stale: row {sess.row} is at "
            f"generation {self._gen[sess.row]}, handle holds "
            f"{sess.generation}"
        )

    def close(self, key: str) -> Session:
        """End the lease: the row returns to the free list with its
        generation bumped (any outstanding handle goes stale)."""
        if key not in self._sessions:
            raise UnknownSessionError(f"session {key!r} is not open")
        return self._remove(key)

    def sweep(self, now: Optional[float] = None) -> List[Session]:
        """Evict every TTL-expired session; returns them (empty when TTL is
        disabled).  The service journals each eviction.

        O(expired·log n): pops the expiry heap while its head is past
        ``now``, skipping entries orphaned by a later touch/close (the
        session's live ``last_used + ttl`` no longer matches the popped
        expiry).  Eviction order is expiry order, which for a
        recency-refreshed heap equals LRU order — the same order the old
        full-scan produced."""
        if self._ttl is None:
            return []
        now = self._clock() if now is None else now
        heap, ttl = self._expiry, self._ttl
        evicted: List[Session] = []
        while heap and heap[0][0] < now:
            expiry, _, key = heapq.heappop(heap)
            sess = self._sessions.get(key)
            # exact-float match: the live entry for this session is the one
            # pushed with its current last_used; any earlier push is stale
            if sess is not None and sess.last_used + ttl == expiry:
                evicted.append(self._remove(key))
        return evicted

    def _remove(self, key: str) -> Session:
        sess = self._sessions.pop(key)
        self._gen[sess.row] += 1  # stale handles can never read this row
        self._free.append(sess.row)
        return sess

    def _push_expiry(self, sess: Session) -> None:
        """Push this session's current expiry onto the lazy-deletion heap
        (no-op when TTL is disabled).  Earlier entries for the same key
        become orphans that sweep skips on pop; compaction keeps the heap
        from growing unboundedly under touch-heavy traffic."""
        if self._ttl is None:
            return
        self._eseq += 1
        heapq.heappush(
            self._expiry, (sess.last_used + self._ttl, self._eseq, sess.key)
        )
        # amortized O(1): rebuild from live sessions once orphans dominate
        if len(self._expiry) > max(1024, 8 * len(self._sessions)):
            ttl = self._ttl
            self._expiry = [
                (s.last_used + ttl, i, s.key)
                for i, s in enumerate(self._sessions.values())
            ]
            heapq.heapify(self._expiry)
            self._eseq = len(self._expiry)

    # ---------------------------------------------------------------- sub-keys

    def sub_key(self, row: int, generation: int):
        """Counter-keyed Threefry sub-seed for lease ``(row, generation)``:
        ``fold_in(fold_in(key(seed), row), generation)``.  Pure counter
        derivation — no mutable RNG state — so a recovery replay that sees
        the same journaled ``(row, generation)`` pairs rebuilds the exact
        same fresh-row randomness without reseeding the engine."""
        import jax.random as jr

        if self._base_key is None:
            self._base_key = jr.key(self._seed)
        return jr.fold_in(jr.fold_in(self._base_key, row), generation)
