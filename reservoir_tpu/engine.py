"""ReservoirEngine — R lockstep device reservoirs behind the Sampler lifecycle.

This is the batch/device counterpart of :mod:`reservoir_tpu.api`: the same
construction-time validation, single-use/reusable lifecycle and result
truncation contract as the reference factories, but the "element" granularity
is a ``[R, B]`` tile — reservoir ``r`` consumes ``tile[r, :valid[r]]`` of its
own stream.  The engine owns:

- the pure :class:`~reservoir_tpu.ops.algorithm_l.ReservoirState` pytree
  (device-resident, never mutated in place — every sampler is copy-on-write
  for free, making ``reusable`` trivial; cf. the reference's aliasing
  machinery ``Sampler.scala:353-381``);
- jitted update functions cached per (tile width, steady, map_fn) —
  jit-compile is the engine's analog of the reference release-build inliner
  (``build.sbt:134-141``);
- the fill/steady dispatch: reservoirs advance in lockstep, so a host-side
  lower bound on ``count`` (no device sync) decides when the fill-phase
  scatter can be dropped from the compiled program.

``SamplerConfig(distinct=True)`` selects the bottom-k kernel of
:mod:`reservoir_tpu.ops.distinct` and ``weighted=True`` the A-ExpJ kernel of
:mod:`reservoir_tpu.ops.weighted` (weights tile required per sample call),
both behind the same lifecycle surface.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from .config import SamplerConfig, validate_max_sample_size
from .errors import SamplerClosedError
from .ops import algorithm_l as _algl
from .ops import distinct as _distinct
from .ops import weighted as _weighted

__all__ = ["ReservoirEngine"]


class ReservoirEngine:
    """R independent k-reservoirs updated in lockstep on device.

    Args:
      config: engine configuration (k, R, dtypes, tile size, distinct).
      key: JAX PRNG key (or ``seed`` int).  Explicit-by-construction
        reproducibility (``SamplerTest.scala:16-54``'s lesson).
      map_fn: traceable map; applied on accept in duplicates mode
        (``Sampler.scala:116``), to every element in distinct mode (``:155``).
      hash_fn: distinct mode only — traceable tile hash returning a
        ``(hi, lo)`` uint32 pair (``Sampler.distinct``'s hash hook, ``:173``).
      reusable: reference lifecycle switch (``Sampler.scala:130-136``);
        single-use engines free device buffers on ``result()``.
    """

    def __init__(
        self,
        config: SamplerConfig,
        key: Union[int, jax.Array, None] = None,
        map_fn: Optional[Callable] = None,
        hash_fn: Optional[Callable] = None,
        reusable: bool = False,
        _initial_state: Any = None,
    ) -> None:
        validate_max_sample_size(config.max_sample_size)
        if config.weighted and config.distinct:
            raise ValueError("weighted and distinct modes are mutually exclusive")
        self._config = config
        self._map_fn = map_fn
        self._hash_fn = hash_fn
        self._reusable = reusable
        self._open = True
        if hash_fn is not None and not config.distinct:
            raise ValueError("hash_fn is only meaningful with distinct=True")
        if config.distinct:
            self._ops = _distinct
        elif config.weighted:
            self._ops = _weighted
        else:
            self._ops = _algl
        if _initial_state is not None:
            # checkpoint-restore path (utils.checkpoint.load_engine): adopt
            # the restored pytree instead of paying ops.init for buffers
            # that would be thrown away
            self._state = _initial_state
        else:
            if key is None or isinstance(key, int):
                key = jr.key(0 if key is None else key)
            self._state = self._ops.init(
                key,
                config.num_reservoirs,
                config.max_sample_size,
                sample_dtype=jnp.dtype(config.resolved_sample_dtype()),
                count_dtype=jnp.dtype(config.count_dtype),
            )
        # Host-side lower bound on every reservoir's count — exact when all
        # tiles are full-width, conservative under ragged `valid`.  Decides
        # fill vs steady dispatch with no device readback.
        self._min_count = 0
        self._jit_cache: dict = {}

    # ------------------------------------------------------------ properties

    @property
    def config(self) -> SamplerConfig:
        return self._config

    @property
    def is_open(self) -> bool:
        """Reference ``isOpen`` (``Sampler.scala:67``): reusable engines are
        always open (``:380``); single-use close on ``result()``."""
        return True if self._reusable else self._open

    @property
    def state(
        self,
    ) -> Union[
        _algl.ReservoirState, _distinct.DistinctState, _weighted.WeightedState
    ]:
        """A snapshot of the state pytree (one of ``ReservoirState``/
        ``DistinctState``/``WeightedState`` by mode).  Copied, because the engine's
        jitted updates donate the previous state's buffers (the streaming
        fast path) — handing out the live buffers would let a later
        ``sample()`` delete them out from under the caller."""
        self._check_open()
        return jax.tree.map(lambda x: x.copy(), self._state)

    # ------------------------------------------------------------- lifecycle

    def _check_open(self) -> None:
        if not self._reusable and not self._open:
            raise SamplerClosedError(
                "this engine is single-use, and no longer open"
            )

    # -------------------------------------------------------------- sampling

    def _update_fn(self, width: int, steady: bool):
        cache_key = (width, steady)
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            base = self._ops.update_steady if steady else self._ops.update
            kwargs = {"map_fn": self._map_fn}
            if self._config.distinct:
                kwargs["hash_fn"] = self._hash_fn
            fn = jax.jit(
                functools.partial(base, **kwargs),
                donate_argnums=(0,),
            )
            self._jit_cache[cache_key] = fn
        return fn

    def sample(
        self, tile: Any, valid: Optional[Any] = None, weights: Optional[Any] = None
    ) -> None:
        """Consume one ``[R, B]`` tile (the engine's per-element hot path —
        the batched analog of ``Sampler.scala:248-259``).  Weighted engines
        additionally require a strictly positive ``[R, B]`` weight tile."""
        self._check_open()
        tile = jnp.asarray(tile)
        if tile.ndim != 2 or tile.shape[0] != self._config.num_reservoirs:
            raise ValueError(
                f"tile must be [num_reservoirs={self._config.num_reservoirs}, B], "
                f"got {tile.shape}"
            )
        if self._config.weighted:
            if weights is None:
                raise ValueError("weighted engine requires a weights tile")
            # Positivity is validated on host inputs only — device-resident
            # weight tiles are accepted as-is so the hot path never forces a
            # device->host sync (nonpositive weights there are a contract
            # violation with undefined sampling bias, as documented).
            if isinstance(weights, (np.ndarray, list, tuple)):
                weights = np.asarray(weights, np.float32)
                if not np.all(weights > 0):
                    raise ValueError("weights must be strictly positive")
            weights = jnp.asarray(weights, jnp.float32)
            if tuple(weights.shape) != tuple(tile.shape):
                raise ValueError(
                    f"weights must match tile shape {tuple(tile.shape)}, "
                    f"got {tuple(weights.shape)}"
                )
        elif weights is not None:
            raise ValueError("weights are only meaningful with weighted=True")
        width = tile.shape[1]
        # distinct mode has one code path (update_steady is update); collapse
        # the cache key so crossing the fill boundary never recompiles
        steady = (
            not self._config.distinct
            and self._min_count >= self._config.max_sample_size
        )
        fn = self._update_fn(width, steady)
        args = (tile, weights) if self._config.weighted else (tile,)
        if valid is None:
            self._state = fn(self._state, *args)
            self._min_count += width
        else:
            valid_np = np.asarray(valid, np.int32)
            if valid_np.shape != (self._config.num_reservoirs,):
                raise ValueError(
                    f"valid must be [{self._config.num_reservoirs}], got {valid_np.shape}"
                )
            if np.any(valid_np < 0) or np.any(valid_np > width):
                raise ValueError(
                    f"valid entries must be in [0, {width}], got "
                    f"[{valid_np.min()}, {valid_np.max()}]"
                )
            self._state = fn(self._state, *args, jnp.asarray(valid_np))
            self._min_count += int(valid_np.min())

    def sample_all(self, tiles: Any) -> None:
        """Consume an iterable of tiles (bulk path, ``Sampler.scala:341``).

        Unweighted engines take ``tile`` or ``(tile, valid)`` items; weighted
        engines take ``(tile, weights)`` or ``(tile, weights, valid)``.
        """
        self._check_open()
        for item in tiles:
            if not isinstance(item, tuple):
                self.sample(item)
            elif self._config.weighted:
                tile, weights = item[0], item[1]
                valid = item[2] if len(item) > 2 else None
                self.sample(tile, valid=valid, weights=weights)
            else:
                self.sample(item[0], valid=item[1] if len(item) > 1 else None)

    def sample_stream(
        self,
        stream: Any,
        tile_width: Optional[int] = None,
        weights: Optional[Any] = None,
    ) -> None:
        """Feed one ``[R, N]`` array, auto-tiled to ``config.tile_size``
        columns with a masked ragged tail — never re-jitting per remainder.
        Weighted engines pass a parallel ``[R, N]`` ``weights`` array."""
        self._check_open()
        stream = np.asarray(stream)
        R, N = stream.shape
        if self._config.weighted:
            if weights is None:
                raise ValueError("weighted engine requires a weights array")
            weights = np.asarray(weights, np.float32)
            if weights.shape != stream.shape:
                raise ValueError(
                    f"weights must match stream shape {stream.shape}, "
                    f"got {weights.shape}"
                )
        B = tile_width or self._config.tile_size
        for start in range(0, N, B):
            chunk = stream[:, start : start + B]
            wchunk = weights[:, start : start + B] if weights is not None else None
            w = chunk.shape[1]
            if w < B:
                pad = np.zeros((R, B - w), chunk.dtype)
                chunk = np.concatenate([chunk, pad], axis=1)
                if wchunk is not None:
                    # padding weight 1.0 keeps the positivity contract; the
                    # valid mask excludes the padding from sampling anyway
                    wchunk = np.concatenate(
                        [wchunk, np.ones((R, B - w), np.float32)], axis=1
                    )
                self.sample(chunk, np.full((R,), w, np.int32), weights=wchunk)
            else:
                self.sample(chunk, weights=wchunk)

    # ----------------------------------------------------------- checkpoints

    def save(self, path: str, metadata: Optional[dict] = None) -> None:
        """Checkpoint state + config to ``path`` (atomic ``.npz``); resume
        with :meth:`restore` — bit-exact, because draws are keyed on absolute
        stream indices (SURVEY §5 checkpoint row)."""
        from .utils.checkpoint import save_engine

        save_engine(path, self, metadata=metadata)

    @classmethod
    def restore(
        cls,
        path: str,
        map_fn: Optional[Callable] = None,
        hash_fn: Optional[Callable] = None,
    ) -> "ReservoirEngine":
        """Reconstruct a checkpointed engine; ``map_fn``/``hash_fn`` are code
        and must be re-supplied when the checkpoint was taken with them."""
        from .utils.checkpoint import load_engine

        return load_engine(path, map_fn=map_fn, hash_fn=hash_fn, engine_cls=cls)

    # --------------------------------------------------------------- results

    def result_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """Device->host result: ``(samples [R, k], sizes [R])`` with the
        truncation contract of ``Sampler.scala:318-331``.  Single-use engines
        close and free device buffers (``:345-350``); reusable engines
        snapshot — earlier results are never clobbered because state arrays
        are immutable (the copy-on-write guarantee of ``Sampler.scala:353-381``
        holds structurally)."""
        self._check_open()
        samples, sizes = self._ops.result(self._state)
        out = (np.asarray(samples), np.asarray(sizes))
        if not self._reusable:
            self._open = False
            self._state = None  # free device buffers
            self._jit_cache.clear()
        return out

    def result(self) -> List[np.ndarray]:
        """Per-reservoir samples, truncated to their fill level."""
        samples, sizes = self.result_arrays()
        return [samples[r, : sizes[r]] for r in range(samples.shape[0])]
