"""ReservoirEngine — R lockstep device reservoirs behind the Sampler lifecycle.

This is the batch/device counterpart of :mod:`reservoir_tpu.api`: the same
construction-time validation, single-use/reusable lifecycle and result
truncation contract as the reference factories, but the "element" granularity
is a ``[R, B]`` tile — reservoir ``r`` consumes ``tile[r, :valid[r]]`` of its
own stream.  The engine owns:

- the pure :class:`~reservoir_tpu.ops.algorithm_l.ReservoirState` pytree
  (device-resident, never mutated in place — every sampler is copy-on-write
  for free, making ``reusable`` trivial; cf. the reference's aliasing
  machinery ``Sampler.scala:353-381``);
- jitted update functions cached per (tile width, steady, map_fn) —
  jit-compile is the engine's analog of the reference release-build inliner
  (``build.sbt:134-141``);
- the fill/steady dispatch: reservoirs advance in lockstep, so a host-side
  lower bound on ``count`` (no device sync) decides when the fill-phase
  scatter can be dropped from the compiled program.

``SamplerConfig(distinct=True)`` selects the bottom-k kernel of
:mod:`reservoir_tpu.ops.distinct` and ``weighted=True`` the A-ExpJ kernel of
:mod:`reservoir_tpu.ops.weighted` (weights tile required per sample call),
both behind the same lifecycle surface.

Robustness (SURVEY §5 failure-detection row, ISSUE 3): every update carries
the ``engine.update``/``engine.pallas`` fault-injection sites
(:mod:`reservoir_tpu.utils.faults`, no-ops unless a plane is installed),
and a runtime Pallas failure demotes the engine to the XLA path instead of
killing the stream (see the class docstring).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from .config import SamplerConfig, validate_max_sample_size
from .errors import SamplerClosedError
from .ops import algorithm_l as _algl
from .ops import distinct as _distinct
from .ops import weighted as _weighted
from .utils import faults as _faults
from .utils.log import info_once, warn_once

__all__ = ["ReservoirEngine"]

# Cross-engine jit caches (ISSUE 5).  A warm standby bootstrap, a crash
# recovery, or a 1-row oracle replay constructs a FRESH engine whose first
# tile used to pay a full XLA re-trace+compile identical to one another
# engine of the same mode had already compiled — ~1s per engine on the CPU
# backend, the dominant cost of "warm" failover.  The traced computation
# is fully determined by (ops module, fill/steady regime) when there is no
# custom map_fn/hash_fn and no mesh (shapes/dtypes are jit's own cache
# axes), and by (ops module, batch size, k, dtypes) for row resets — share
# those jitted callables process-wide.  Pallas, meshed, and custom-fn
# engines keep per-instance caching (their traces close over instance
# state or arbitrary callables).
_SHARED_UPDATE_JIT: dict = {}
_SHARED_RESET_JIT: dict = {}


def _adopt_scatter(state, part, idx):
    """Scatter a whole exported per-row sub-state over ``idx`` — the
    migration adopt (tree structure and shapes are jit's own cache axes,
    so one process-wide wrapper serves every engine)."""
    return jax.tree.map(
        lambda full, one: full.at[idx].set(one), state, part
    )


_ADOPT_JIT = jax.jit(_adopt_scatter, donate_argnums=(0,))


class ReservoirEngine:
    """R independent k-reservoirs updated in lockstep on device.

    Args:
      config: engine configuration (k, R, dtypes, tile size, distinct).
      key: JAX PRNG key (or ``seed`` int).  Explicit-by-construction
        reproducibility (``SamplerTest.scala:16-54``'s lesson).
      map_fn: traceable map; applied on accept in duplicates mode
        (``Sampler.scala:116``), to every element in distinct mode (``:155``).
      hash_fn: distinct mode only — traceable tile hash returning a
        ``(hi, lo)`` uint32 pair (``Sampler.distinct``'s hash hook, ``:173``).
      reusable: reference lifecycle switch (``Sampler.scala:130-136``);
        single-use engines free device buffers on ``result()``.
      mesh: device mesh for multi-chip engines.  Only meaningful with
        ``config.mesh_axis`` set; defaults to a 1-D mesh over all visible
        devices.  State shards over the reservoir axis; updates compile to
        collective-free SPMD; results gather over ICI (``parallel.sharded``).
      faults: instance-scoped fault plane for the ``engine.update`` /
        ``engine.pallas`` injection sites
        (:mod:`reservoir_tpu.utils.faults`); ``None`` defers to the
        globally installed plane — zero-overhead no-op when neither exists.

    Graceful degradation (ISSUE 3): a *runtime* Pallas launch/compile
    failure — a Mosaic lowering bug on a new device, a kernel-side OOM —
    demotes the engine to the XLA path for the rest of its life (logged
    once, counted in :attr:`demotions`) and re-runs the failed tile;
    sampling continues instead of killing the stream.  Demotion is only
    possible while the state buffers survived the failed call (donation
    hands them to the runtime at execution; compile/lowering failures — the
    common case — leave them alive).
    """

    def __init__(
        self,
        config: SamplerConfig,
        key: Union[int, jax.Array, None] = None,
        map_fn: Optional[Callable] = None,
        hash_fn: Optional[Callable] = None,
        reusable: bool = False,
        mesh: Optional[jax.sharding.Mesh] = None,
        *,
        device: Optional[Any] = None,
        faults: Optional[Any] = None,
        _initial_state: Any = None,
    ) -> None:
        validate_max_sample_size(config.max_sample_size)
        if config.weighted and config.distinct:
            raise ValueError("weighted and distinct modes are mutually exclusive")
        self._config = config
        self._map_fn = map_fn
        self._hash_fn = hash_fn
        self._reusable = reusable
        self._open = True
        if hash_fn is not None and not config.distinct:
            raise ValueError("hash_fn is only meaningful with distinct=True")
        if config.distinct:
            self._ops = _distinct
        elif config.weighted:
            self._ops = _weighted
        else:
            self._ops = _algl
        # 64-bit distinct keys ride as (hi, lo) uint32 bit-planes on device
        # (ops.distinct wide mode) — host tiles split here, results
        # reassemble in result_arrays; x64 never needs to be enabled
        self._wide = (
            config.distinct
            and jnp.dtype(config.resolved_sample_dtype()).itemsize == 8
        )
        if config.impl == "pallas":
            # Fail construction, not first sample, if this config can never
            # reach a kernel (the "fail fast" validation philosophy of
            # ``Sampler.scala:79-95``).  All three kernels are
            # fill-capable and take every full tile; ragged tiles use XLA
            # (logged once per engine at first fallback).
            if map_fn is not None:
                raise ValueError("impl='pallas' requires an identity map_fn")
            if config.count_dtype == "wide":
                raise ValueError(
                    "impl='pallas' requires int32 counters (the kernel's "
                    "supports() contract); count_dtype='wide' dispatches "
                    "XLA — use impl='auto'"
                )
            if hash_fn is not None:
                raise ValueError(
                    "impl='pallas' requires the default hash (the kernel "
                    "owns the value-bits embedding); use impl='auto'"
                )
            # No R-divisibility requirement: every kernel pads a partial
            # last row-block with inert lanes.  mesh_axis is fine too: the
            # kernels are collective-free over the reservoir grid, so they
            # run under shard_map with each chip padding its own shard.
        # Multi-chip placement (SamplerConfig.mesh_axis makes the mesh real,
        # VERDICT r1 item 4): state shards over the reservoir axis and every
        # incoming tile is device_put with the matching sharding, so the
        # cached jitted updates compile to collective-free SPMD programs.
        self._pallas_fallback_logged = False
        self._tuned_geometry_ignored_logged = False
        self._faults = faults
        # Pallas->XLA demotion state (graceful degradation, ISSUE 3)
        self._demoted = False
        self._demotion_logged = False
        #: runtime Pallas failures absorbed by demoting to XLA (0 or 1 —
        #: the first demotion is permanent for this engine)
        self.demotions = 0
        #: row resets applied so far (reset_rows calls).  The ingest-side
        #: skip gate (ISSUE 8) keys its host replica's staleness on this:
        #: a serve-plane row recycle mutates (count, nxt, log_w) behind the
        #: gate's back, and the replica must re-pull before its next eval.
        self.reset_epochs = 0
        self._mesh = None
        self._tile_sharding = None
        self._row_sharding = None
        if config.mesh_axis is not None:
            from .parallel import make_mesh

            self._mesh = mesh if mesh is not None else make_mesh(
                axis=config.mesh_axis
            )
            n_shards = self._mesh.shape[config.mesh_axis]
            if config.num_reservoirs % n_shards != 0:
                raise ValueError(
                    f"num_reservoirs={config.num_reservoirs} must divide "
                    f"evenly over the {n_shards}-device '{config.mesh_axis}' "
                    "mesh axis"
                )
            self._tile_sharding = jax.sharding.NamedSharding(
                self._mesh, jax.sharding.PartitionSpec(config.mesh_axis, None)
            )
            self._row_sharding = jax.sharding.NamedSharding(
                self._mesh, jax.sharding.PartitionSpec(config.mesh_axis)
            )
        elif mesh is not None:
            raise ValueError("mesh requires config.mesh_axis to be set")
        # Per-shard device placement (ISSUE 12, ROADMAP item-1 remainder):
        # pin this engine's whole state to one device so N shard engines
        # spread over the chips of a slice instead of stacking on the
        # default device.  Every host input is device_put onto the pin, so
        # updates never see mixed placements.  Orthogonal to mesh sharding
        # (one engine over many chips) — mutually exclusive by contract.
        self._device = device
        if device is not None and self._mesh is not None:
            raise ValueError(
                "device pinning and mesh sharding are mutually exclusive "
                "(a pinned engine lives on one chip)"
            )
        if _initial_state is not None:
            # checkpoint-restore path (utils.checkpoint.load_engine): adopt
            # the restored pytree instead of paying ops.init for buffers
            # that would be thrown away
            self._state = _initial_state
        else:
            if key is None or isinstance(key, int):
                key = jr.key(0 if key is None else key)
            self._state = self._ops.init(
                key,
                config.num_reservoirs,
                config.max_sample_size,
                sample_dtype=jnp.dtype(config.resolved_sample_dtype()),
                # "wide" rides through as the emulated-uint64 sentinel
                # (duplicates mode only; config.__post_init__ validates)
                count_dtype=(
                    config.count_dtype
                    if config.count_dtype == "wide"
                    else jnp.dtype(config.count_dtype)
                ),
            )
        if self._mesh is not None:
            from .parallel import shard_state

            self._state = shard_state(
                self._state, self._mesh, config.mesh_axis
            )
        if self._device is not None:
            self._state = jax.device_put(self._state, self._device)
        # Host-side lower bound on every reservoir's count — exact when all
        # tiles are full-width, conservative under ragged `valid`.  Decides
        # fill vs steady dispatch with no device readback.
        self._min_count = 0
        self._jit_cache: dict = {}
        # reset_rows scatter jits, keyed by batch size (separate from
        # _jit_cache: its keys carry the Pallas-dispatch layout that
        # pallas_used()/xla_used() introspect positionally)
        self._reset_jit: dict = {}
        # jit-cache key -> autotuned Geometry (or None = kernel defaults);
        # observability for tests and the capture tooling
        self._geometry_by_key: dict = {}
        # set by sample_stream around its per-tile loop after it validated
        # the whole weights array, so sample() skips the per-tile re-scan
        self._weights_prevalidated = False

    # ------------------------------------------------------------ properties

    @property
    def config(self) -> SamplerConfig:
        return self._config

    @staticmethod
    def _key_uses_pallas(key) -> bool:
        """THE owner of the jit-cache key layouts: per-tile keys are
        ``(width, steady, ragged, use_pallas)``, fused-stream keys are
        ``("stream_fused", n, B, steady, use_pallas, dtype)``."""
        return key[4] if key[0] == "stream_fused" else key[3]

    def pallas_used(self) -> bool:
        """True iff any update compiled so far dispatched to a Pallas
        kernel — callers (bench.py's impl-tag guard, dispatch tests) use
        this instead of probing cache keys positionally."""
        return any(self._key_uses_pallas(k) for k in self._jit_cache)

    def xla_used(self) -> bool:
        """True iff any update compiled so far took the XLA path (fill and
        ragged tiles always do in duplicates mode)."""
        return any(not self._key_uses_pallas(k) for k in self._jit_cache)

    @property
    def device(self) -> Optional[Any]:
        """The device this engine is pinned to (``None`` = default
        placement or mesh-sharded)."""
        return self._device

    def _pin_device(self, device: Optional[Any]) -> None:
        """Pin a restored engine's state to ``device`` (the checkpoint
        recover path: ``load_engine`` adopts the state first, the owning
        bridge/service then pins it where the shard lives)."""
        if device is None:
            return
        if self._mesh is not None:
            raise ValueError(
                "device pinning and mesh sharding are mutually exclusive"
            )
        self._device = device
        self._state = jax.device_put(self._state, device)

    @property
    def is_open(self) -> bool:
        """Reference ``isOpen`` (``Sampler.scala:67``): reusable engines are
        always open (``:380``); single-use close on ``result()``."""
        return True if self._reusable else self._open

    @property
    def state(
        self,
    ) -> Union[
        _algl.ReservoirState, _distinct.DistinctState, _weighted.WeightedState
    ]:
        """A snapshot of the state pytree (one of ``ReservoirState``/
        ``DistinctState``/``WeightedState`` by mode).  Copied, because the engine's
        jitted updates donate the previous state's buffers (the streaming
        fast path) — handing out the live buffers would let a later
        ``sample()`` delete them out from under the caller."""
        self._check_open()
        return jax.tree.map(lambda x: x.copy(), self._state)

    # ------------------------------------------------------------- lifecycle

    def _check_open(self) -> None:
        if not self._reusable and not self._open:
            raise SamplerClosedError(
                "this engine is single-use, and no longer open"
            )

    # -------------------------------------------------------------- sampling

    def _pallas_module(self):
        """The Pallas kernel module for this mode."""
        if self._ops is _algl:
            from .ops import algorithm_l_pallas as _alp

            return _alp
        if self._ops is _weighted:
            from .ops import weighted_pallas as _wp

            return _wp
        from .ops import distinct_pallas as _dp

        return _dp

    def _pallas_eligible(self, steady: bool, ragged: bool, tile_dtype) -> bool:
        """Dispatch gate for the Pallas kernels (VERDICT r1 item 2): the
        hot path goes through Mosaic when the kernel's ``supports()``
        contract holds; everything else falls back to XLA.  All three
        kernels (algl M4, weighted M4b, distinct) are fill-capable.

        When ``impl="pallas"`` was requested and a tile still falls back,
        the dispatch decision is no longer invisible: the first fallback
        logs the reason once per engine (VERDICT r3 item 7)."""
        reason = self._pallas_fallback_reason(steady, ragged, tile_dtype)
        if reason is not None and self._config.impl == "pallas":
            info_once(
                self,
                "_pallas_fallback_logged",
                "impl='pallas' requested but this tile takes the XLA "
                "path: %s (logged once per engine)",
                reason,
                logger=__name__,
                site="engine.update",
            )
        return reason is None

    def _pallas_fallback_reason(
        self, steady: bool, ragged: bool, tile_dtype
    ) -> "str | None":
        """None if the Pallas kernel takes the tile, else why not."""
        if self._config.impl == "xla":
            return "impl='xla' configured"
        if self._demoted:
            return "engine demoted to XLA after a runtime Pallas failure"
        if ragged:
            return "ragged tile (valid mask)"
        if self._map_fn is not None or self._hash_fn is not None:
            return "custom map_fn/hash_fn"
        mod = self._pallas_module()
        if not mod.supports(self._state, None, None):
            return "kernel supports() contract (counter/sample dtype)"
        if self._config.distinct:
            # the kernel owns the default-hash embedding: 4-byte *integer*
            # tiles (the XLA path value-converts other dtypes, the kernel
            # bit-views — only integers agree) and (hi, lo) planes for wide
            # keys (validated by engine.sample)
            if not self._wide and (
                jnp.dtype(tile_dtype).itemsize != 4
                or jnp.dtype(tile_dtype).kind not in "iu"
            ):
                return f"distinct tile dtype {jnp.dtype(tile_dtype)} needs a 4-byte integer"
        elif jnp.dtype(tile_dtype) != self._state.samples.dtype:
            return (
                f"tile dtype {jnp.dtype(tile_dtype)} != samples dtype "
                f"{self._state.samples.dtype}"
            )
        if self._config.impl == "pallas":
            return None
        # auto: Mosaic lowers on TPU only — GPU/CPU backends take the XLA
        # path (the CPU interpreter would also be far slower than XLA)
        if jax.default_backend() != "tpu":
            return f"impl='auto' on backend {jax.default_backend()!r}"
        return None

    def _kernel_name(self) -> str:
        """The autotune-cache kernel dimension for this engine's mode."""
        if self._ops is _algl:
            return "algl"
        if self._ops is _weighted:
            return "weighted"
        return "distinct"

    def _kernel_geometry(self, kernel: str, width: int, tile_dtype):
        """Tuned ``(block_r, chunk_b, gather_chunk)`` for ``kernel`` at
        this tile shape from the persistent autotune cache
        (:mod:`reservoir_tpu.ops.autotune`), or None — the kernel then
        uses its hardcoded defaults, so untuned devices (every
        CPU/interpret run) behave exactly as before."""
        from .ops import autotune

        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:  # backend init failure surfaces elsewhere
            return None
        return autotune.lookup(
            device_kind,
            self._config.num_reservoirs,
            self._config.max_sample_size,
            width,
            tile_dtype,
            kernel=kernel,
        )

    def _log_ignored_geometry(
        self, width: int, tile_dtype, steady: bool, ragged: bool
    ) -> None:
        """A tuned cache entry that exists but cannot be used (the tile
        dispatched XLA) must not be silently skipped — log it once per
        engine with the dispatch reason, so a mis-shaped production config
        that defeats its own tuning is visible."""
        if self._tuned_geometry_ignored_logged:
            return
        geometry = self._kernel_geometry(self._kernel_name(), width, tile_dtype)
        if geometry is None:
            return
        info_once(
            self,
            "_tuned_geometry_ignored_logged",
            "tuned %s geometry %s for this tile shape is ignored — the "
            "tile takes the XLA path: %s (logged once per engine)",
            self._kernel_name(),
            tuple(geometry),
            self._pallas_fallback_reason(steady, ragged, tile_dtype),
            logger=__name__,
            site="engine.update",
        )

    def _base_update(self, steady: bool, use_pallas: bool, geometry=None):
        """The traceable per-tile update ``(state, tile[, weights][, valid])
        -> state`` for this mode — Pallas kernel (shard_map-wrapped on a
        mesh) or XLA path.  Shared by the per-tile jit cache and the fused
        stream scan.  ``geometry`` is an autotuned
        :class:`~reservoir_tpu.ops.autotune.Geometry` overriding the
        dispatched kernel's block/chunk defaults (all three kernels take
        one; ``gather_chunk`` is algl-only)."""
        if use_pallas:
            mod = self._pallas_module()
            if self._ops is _algl:
                kernel = (
                    mod.update_steady_pallas if steady else mod.update_pallas
                )
            else:
                kernel = mod.update_pallas
            if geometry is not None:
                # 0 = "kernel default" for block (auto-size) and chunk
                # (whole tile); gather 0 is meaningful (full-width) and
                # passes through as-is
                kwargs = {
                    "block_r": geometry.block_r or None,
                    "chunk_b": geometry.chunk_b or None,
                }
                if self._ops is _algl:
                    kwargs["gather_chunk"] = geometry.gather_chunk
                kernel = functools.partial(kernel, **kwargs)
            base = functools.partial(
                kernel, interpret=jax.default_backend() == "cpu"
            )
            if self._mesh is not None:
                # pallas_call is not auto-partitionable — run it under
                # shard_map so each chip takes its reservoir row-blocks
                # (the kernel is collective-free over the grid)
                from jax.sharding import PartitionSpec as _P

                from .parallel.sharded import shard_map as _shard_map

                axis = self._config.mesh_axis
                specs = jax.tree.map(
                    lambda x: _P(axis, *([None] * (x.ndim - 1))),
                    self._state,
                )
                tile_specs = (_P(axis, None),) * (
                    2 if self._config.weighted else 1
                )
                base = _shard_map(
                    base,
                    mesh=self._mesh,
                    in_specs=(specs,) + tile_specs,
                    out_specs=specs,
                    # pallas_call out_shapes carry no varying-mesh-axes
                    # info; the kernel is collective-free over the grid,
                    # so the vma check adds nothing here
                    check_vma=False,
                )
            return base
        base = self._ops.update_steady if steady else self._ops.update
        kwargs = {"map_fn": self._map_fn}
        if self._config.distinct:
            kwargs["hash_fn"] = self._hash_fn
        return functools.partial(base, **kwargs)

    def _update_fn(
        self,
        width: int,
        steady: bool,
        ragged: bool,
        tile_dtype,
        use_pallas: Optional[bool] = None,
    ):
        if use_pallas is None:
            use_pallas = self._pallas_eligible(steady, ragged, tile_dtype)
        cache_key = (width, steady, ragged, use_pallas)
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            # autotuned geometry is resolved once per jit-cache entry (a
            # stat + dict hit) — the compiled program then carries it
            if use_pallas:
                geometry = self._kernel_geometry(
                    self._kernel_name(), width, tile_dtype
                )
            else:
                geometry = None
                self._log_ignored_geometry(width, tile_dtype, steady, ragged)
            self._geometry_by_key[cache_key] = geometry
            shared_key = None
            if (
                not use_pallas
                and self._mesh is None
                and self._map_fn is None
                and self._hash_fn is None
            ):
                # _base_update is then a partial over the ops module alone
                # (shapes/dtypes/raggedness are jit's own cache axes)
                shared_key = (self._ops, steady)
                fn = _SHARED_UPDATE_JIT.get(shared_key)
            if fn is None:
                fn = jax.jit(
                    self._base_update(steady, use_pallas, geometry),
                    donate_argnums=(0,),
                )
                if shared_key is not None:
                    _SHARED_UPDATE_JIT[shared_key] = fn
            self._jit_cache[cache_key] = fn
        return fn

    # -------------------------------------------- Pallas->XLA demotion

    def _state_alive(self) -> bool:
        """False once any state buffer was consumed by a failed donated
        call — demotion cannot re-run the tile then."""
        for leaf in jax.tree.leaves(self._state):
            is_deleted = getattr(leaf, "is_deleted", None)
            if is_deleted is not None and is_deleted():
                return False
        return True

    def _demote(self, exc: BaseException) -> None:
        self._demoted = True
        self.demotions += 1
        warn_once(
            self,
            "_demotion_logged",
            "Pallas update failed (%s: %s); engine demoted to the XLA "
            "path — sampling continues (logged once per engine)",
            type(exc).__name__,
            exc,
            logger=__name__,
            site="engine.pallas",
        )

    def _call_update(self, fn, use_pallas: bool, rebuild_xla, state, args):
        """Run one jitted update, demoting the engine to XLA on a runtime
        Pallas failure (graceful degradation).  ``rebuild_xla`` builds the
        equivalent XLA update for the same tile shape; the failed tile is
        re-run through it, so no element is lost to the demotion.  The
        ``engine.pallas`` fault site fires only on the Pallas branch — it
        is the deterministic stand-in for a Mosaic launch failure."""
        if not use_pallas:
            return fn(state, *args)
        try:
            _faults.fire("engine.pallas", self._faults)
            return fn(state, *args)
        except Exception as e:
            if not self._state_alive():
                raise  # buffers already donated: the tile cannot re-run
            self._demote(e)
            return rebuild_xla()(state, *args)

    def sample(
        self, tile: Any, valid: Optional[Any] = None, weights: Optional[Any] = None
    ) -> None:
        """Consume one ``[R, B]`` tile (the engine's per-element hot path —
        the batched analog of ``Sampler.scala:248-259``).  Weighted engines
        additionally require a strictly positive ``[R, B]`` weight tile."""
        self._check_open()
        _faults.fire("engine.update", self._faults)
        tile_host: Optional[np.ndarray] = None  # host part staged below
        weights_host: Optional[np.ndarray] = None
        if self._wide:
            tile_np = np.asarray(tile)
            if (
                tile_np.ndim != 2
                or tile_np.shape[0] != self._config.num_reservoirs
            ):
                raise ValueError(
                    f"tile must be [num_reservoirs="
                    f"{self._config.num_reservoirs}, B], got {tile_np.shape}"
                )
            tile = _distinct.split_values(tile_np)  # (hi, lo) uint32 planes
            tile_shape, tile_dtype = tile_np.shape, tile_np.dtype
        else:
            if not isinstance(tile, jax.Array):
                # snapshot now (callers may reuse their buffer under the
                # async transfer — the bridge's staging tile does exactly
                # that), but defer the device_put: all host parts of this
                # call ship in ONE async transfer below.  Never jnp.asarray:
                # on tunneled backends it transfers synchronously in chunks
                # (measured 228ms vs 2.5ms pipelined for a 4MB tile).
                tile_host = np.asarray(tile)
                canon = jax.dtypes.canonicalize_dtype(tile_host.dtype)
                if tile_host.dtype != canon:
                    # canonicalize on host (int64 -> int32 with x64 off):
                    # halves the transfer AND keeps the Pallas dispatch
                    # probe seeing the dtype the device will actually hold;
                    # astype already yields a fresh snapshot buffer
                    tile_host = tile_host.astype(canon)
                elif not isinstance(tile, (list, tuple)):
                    # snapshot: ndarrays/views alias the caller's buffer,
                    # and __array__-protocol wrappers may hand out their
                    # live internal array — only builtin sequences are
                    # guaranteed fresh from asarray and skip the copy
                    tile_host = tile_host.copy()
                tile_probe = tile_host
            else:
                tile_probe = tile
            if (
                tile_probe.ndim != 2
                or tile_probe.shape[0] != self._config.num_reservoirs
            ):
                raise ValueError(
                    f"tile must be [num_reservoirs="
                    f"{self._config.num_reservoirs}, B], got {tile_probe.shape}"
                )
            tile_shape, tile_dtype = tile_probe.shape, tile_probe.dtype
        if self._config.weighted:
            if weights is None:
                raise ValueError("weighted engine requires a weights tile")
            # Nonnegativity is validated on host inputs only — device-resident
            # weight tiles are accepted as-is so the hot path never forces a
            # device->host sync (negative weights there are a contract
            # violation with undefined sampling bias, as documented).
            # w == 0 is legal everywhere: counted, never sampled (the
            # oracle's contract, ops.weighted module docs).
            if not isinstance(weights, jax.Array):
                w_in = weights
                weights_host = np.asarray(w_in, np.float32)
                if not self._weights_prevalidated and not np.all(
                    weights_host >= 0
                ):
                    raise ValueError("weights must be nonnegative")
                if weights_host is w_in:
                    # no conversion copy happened — snapshot before the
                    # async device_put (caller may reuse its buffer)
                    weights_host = weights_host.copy()
                w_probe = weights_host
            else:
                if weights.dtype != jnp.float32:
                    weights = weights.astype(jnp.float32)
                w_probe = weights
            if tuple(w_probe.shape) != tuple(tile_shape):
                raise ValueError(
                    f"weights must match tile shape {tuple(tile_shape)}, "
                    f"got {tuple(w_probe.shape)}"
                )
        elif weights is not None:
            raise ValueError("weights are only meaningful with weighted=True")
        width = tile_shape[1]
        # distinct mode has one code path (update_steady is update); collapse
        # the cache key so crossing the fill boundary never recompiles.
        # weighted mode always takes the fill-capable path: zero-weight items
        # advance count without filling slots, so an element-count lower
        # bound cannot prove the fill is over (the fill scatter is a no-op
        # once slots are full — ops.weighted gates on the device side).
        steady = (
            not self._config.distinct
            and not self._config.weighted
            and self._min_count >= self._config.max_sample_size
        )
        ragged = valid is not None
        use_pallas = self._pallas_eligible(steady, ragged, tile_dtype)
        fn = self._update_fn(
            width, steady, ragged, tile_dtype, use_pallas=use_pallas
        )
        valid_np: Optional[np.ndarray] = None
        if valid is not None:
            valid_np = np.array(valid, np.int32, copy=True)  # async-put safe
            if valid_np.shape != (self._config.num_reservoirs,):
                raise ValueError(
                    f"valid must be [{self._config.num_reservoirs}], got {valid_np.shape}"
                )
            if np.any(valid_np < 0) or np.any(valid_np > width):
                raise ValueError(
                    f"valid entries must be in [0, {width}], got "
                    f"[{valid_np.min()}, {valid_np.max()}]"
                )
        # ONE async device_put for every host-resident part of this call:
        # per-op RPC latency dominates flushes on tunneled backends
        # (~30ms each), so tile+weights+valid ride a single transfer.
        stage = {}
        if tile_host is not None:
            stage["tile"] = tile_host
        if weights_host is not None:
            stage["weights"] = weights_host
        if valid_np is not None:
            stage["valid"] = valid_np
        if stage:
            if self._mesh is not None:
                shards = {
                    "tile": self._tile_sharding,
                    "weights": self._tile_sharding,
                    "valid": self._row_sharding,
                }
                placed = jax.device_put(
                    stage, {key: shards[key] for key in stage}
                )
            else:
                placed = jax.device_put(stage, self._device)
        else:
            placed = {}
        if tile_host is not None:
            tile = placed["tile"]
        if weights_host is not None:
            weights = placed["weights"]
        if self._mesh is not None:
            # commit device-resident inputs to the mesh too, so each chip
            # receives only its reservoir shard and the update compiles
            # collective-free (wide tiles are (hi, lo) plane pairs)
            if tile_host is None:
                tile = jax.tree.map(
                    lambda t: jax.device_put(t, self._tile_sharding), tile
                )
            if weights is not None and weights_host is None:
                weights = jax.device_put(weights, self._tile_sharding)
        args = (tile, weights) if self._config.weighted else (tile,)

        def rebuild_xla():
            return self._update_fn(
                width, steady, ragged, tile_dtype, use_pallas=False
            )

        if valid is None:
            self._state = self._call_update(
                fn, use_pallas, rebuild_xla, self._state, args
            )
            self._min_count += width
        else:
            self._state = self._call_update(
                fn, use_pallas, rebuild_xla, self._state,
                args + (placed["valid"],),
            )
            self._min_count += int(valid_np.min())

    def sample_gated(self, tile: Any, nvalid: Any, advance: Any) -> None:
        """Consume one PRE-GATED ``[R, Bg]`` candidate tile (ISSUE 8).

        The ingest-side skip gate (:mod:`reservoir_tpu.stream.gate`) ships
        only the elements that can win: row ``r`` advances by
        ``advance[r]`` logical stream elements of which the ``nvalid[r]``
        candidates in ``tile[r, :nvalid[r]]`` (fill-phase prefix + every
        Algorithm-L acceptance, in order) were shipped.  Bit-identical to
        :meth:`sample` over the full tiles — acceptance draws are keyed on
        the same absolute indices either way (:func:`ops.algorithm_l.update_gated`).

        Duplicates mode with narrow int32 counters on an unmeshed engine
        only — exactly the :func:`~reservoir_tpu.stream.gate.gate_ineligible_reason`
        contract; the gated apply always takes the XLA path (candidate
        tiles are too small to feed a Mosaic grid).
        """
        self._check_open()
        _faults.fire("engine.update", self._faults)
        if self._ops is not _algl:
            raise ValueError(
                "sample_gated requires duplicates mode (the skip gate "
                "replicates the Algorithm-L recursion only)"
            )
        if self._state.count.ndim != 1 or (
            self._state.count.dtype != jnp.int32
        ):
            raise ValueError(
                "sample_gated requires narrow int32 counters"
            )
        if self._mesh is not None:
            raise ValueError("sample_gated does not support meshed engines")
        R = self._config.num_reservoirs
        # snapshot (gated tiles are small): async-device_put safe even if
        # the caller reuses its buffer, the discipline sample() keeps
        tile_host = np.array(tile, order="C")
        if tile_host.ndim != 2 or tile_host.shape[0] != R:
            raise ValueError(
                f"gated tile must be [num_reservoirs={R}, Bg], got "
                f"{tile_host.shape}"
            )
        bg = tile_host.shape[1]
        nvalid_np = np.array(nvalid, np.int32, copy=True)
        advance_np = np.array(advance, np.int32, copy=True)
        if nvalid_np.shape != (R,) or advance_np.shape != (R,):
            raise ValueError(
                f"nvalid/advance must be [{R}], got {nvalid_np.shape} / "
                f"{advance_np.shape}"
            )
        if np.any(nvalid_np < 0) or np.any(nvalid_np > bg):
            raise ValueError(
                f"nvalid entries must be in [0, {bg}], got "
                f"[{nvalid_np.min()}, {nvalid_np.max()}]"
            )
        if np.any(advance_np < 0):
            raise ValueError("advance entries must be nonnegative")
        canon = jax.dtypes.canonicalize_dtype(tile_host.dtype)
        if tile_host.dtype != canon:
            tile_host = tile_host.astype(canon)
        cache_key = ("gated", bg, False, False)  # [3] = use_pallas: False
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            shared_key = (
                (self._ops, "gated") if self._map_fn is None else None
            )
            if shared_key is not None:
                fn = _SHARED_UPDATE_JIT.get(shared_key)
            if fn is None:
                fn = jax.jit(
                    functools.partial(
                        _algl.update_gated, map_fn=self._map_fn
                    ),
                    donate_argnums=(0,),
                )
                if shared_key is not None:
                    _SHARED_UPDATE_JIT[shared_key] = fn
            self._jit_cache[cache_key] = fn
        placed = jax.device_put(
            {"tile": tile_host, "nvalid": nvalid_np, "advance": advance_np},
            self._device,
        )
        self._state = fn(
            self._state, placed["tile"], placed["nvalid"], placed["advance"]
        )
        self._min_count += int(advance_np.min())

    def sample_all(self, tiles: Any) -> None:
        """Consume an iterable of tiles (bulk path, ``Sampler.scala:341``).

        Unweighted engines take ``tile`` or ``(tile, valid)`` items; weighted
        engines take ``(tile, weights)`` or ``(tile, weights, valid)``.
        A shape/dtype error names the offending item index — at tens of
        thousands of streams "tile must be [R, B]" alone is undebuggable.
        """
        self._check_open()
        for i, item in enumerate(tiles):
            try:
                if not isinstance(item, tuple):
                    self.sample(item)
                elif self._config.weighted:
                    tile, weights = item[0], item[1]
                    valid = item[2] if len(item) > 2 else None
                    self.sample(tile, valid=valid, weights=weights)
                else:
                    self.sample(
                        item[0], valid=item[1] if len(item) > 1 else None
                    )
            except (TypeError, ValueError) as e:
                raise type(e)(f"tiles[{i}]: {e}") from None

    def sample_stream(
        self,
        stream: Any,
        tile_width: Optional[int] = None,
        weights: Optional[Any] = None,
        fused: bool = False,
    ) -> None:
        """Feed one ``[R, N]`` array, auto-tiled to ``config.tile_size``
        columns with a masked ragged tail — never re-jitting per remainder.
        Weighted engines pass a parallel ``[R, N]`` ``weights`` array.

        ``fused=True`` runs every full tile inside ONE jitted ``lax.scan``
        (one transfer + one dispatch instead of one per tile) — on tunneled
        backends where each dispatch costs a ~30ms round-trip this is the
        difference between wire speed and RPC-bound feeding.  Results are
        bit-identical to the unfused path (tile-split invariance: draws are
        keyed on absolute indices).  The ragged tail still goes per-tile."""
        self._check_open()
        stream = np.asarray(stream)
        R, N = stream.shape
        if self._config.weighted:
            if weights is None:
                raise ValueError("weighted engine requires a weights array")
            weights = np.asarray(weights, np.float32)
            if weights.shape != stream.shape:
                raise ValueError(
                    f"weights must match stream shape {stream.shape}, "
                    f"got {weights.shape}"
                )
            # validate the WHOLE array before consuming any tile: a bad
            # weight in tile i must not leave tiles 0..i-1 already folded
            # into the reservoir state (callers could not roll back)
            if not np.all(weights >= 0):
                raise ValueError("weights must be nonnegative")
        B = tile_width or self._config.tile_size
        start0 = 0
        if fused and N >= 2 * B:
            n_full = N // B
            self._sample_stream_fused(
                stream[:, : n_full * B],
                weights[:, : n_full * B] if weights is not None else None,
                B,
                n_full,
            )
            start0 = n_full * B
        self._weights_prevalidated = weights is not None
        try:
            for start in range(start0, N, B):
                chunk = stream[:, start : start + B]
                wchunk = (
                    weights[:, start : start + B]
                    if weights is not None
                    else None
                )
                w = chunk.shape[1]
                if w < B:
                    pad = np.zeros((R, B - w), chunk.dtype)
                    chunk = np.concatenate([chunk, pad], axis=1)
                    if wchunk is not None:
                        # padding weight 1.0 keeps the positivity contract;
                        # the valid mask excludes the padding from sampling
                        wchunk = np.concatenate(
                            [wchunk, np.ones((R, B - w), np.float32)], axis=1
                        )
                    self.sample(
                        chunk, np.full((R,), w, np.int32), weights=wchunk
                    )
                else:
                    self.sample(chunk, weights=wchunk)
        finally:
            self._weights_prevalidated = False

    def _fused_update_fn(
        self, n_full: int, B: int, steady: bool, stream_dtype, use_pallas: bool
    ):
        """Build/cache the jitted ``lax.scan`` over ``n_full`` full tiles
        (the fused-stream analog of :meth:`_update_fn`; shares the
        demotion-rebuild contract — an XLA variant exists for every key)."""
        cache_key = ("stream_fused", n_full, B, steady, use_pallas,
                     np.dtype(stream_dtype).str)
        fn = self._jit_cache.get(cache_key)
        if fn is not None:
            return fn
        if use_pallas:
            geometry = self._kernel_geometry(
                self._kernel_name(), B, stream_dtype
            )
        else:
            geometry = None
            self._log_ignored_geometry(B, stream_dtype, steady, False)
        self._geometry_by_key[cache_key] = geometry
        base = self._base_update(steady, use_pallas, geometry)
        weighted = self._config.weighted
        wide = self._wide

        def scan_fn(state, tiles, wtiles=None):
            def body(st, xs):
                if weighted:
                    tile, wt = xs
                    return base(st, tile, wt), None
                if wide:
                    hi, lo = xs
                    return base(st, (hi, lo)), None
                return base(st, xs), None

            if weighted:
                xs = (tiles, wtiles)
            else:
                xs = tiles  # wide mode: a (hi, lo) pair of [n, R, B]
            state, _ = jax.lax.scan(body, state, xs)
            return state

        fn = jax.jit(scan_fn, donate_argnums=(0,))
        self._jit_cache[cache_key] = fn
        return fn

    def _sample_stream_fused(
        self,
        stream: np.ndarray,
        weights: Optional[np.ndarray],
        B: int,
        n_full: int,
    ) -> None:
        """Every full tile in one jitted ``lax.scan``: host reshapes to
        ``[n, R, B]`` (a C-speed transpose copy), one async transfer ships
        it, one dispatch consumes it."""
        _faults.fire("engine.update", self._faults)
        R = self._config.num_reservoirs
        # weights were already validated whole-array (incl. NaN rejection)
        # by sample_stream, the sole caller
        wide = self._wide
        if wide:
            # 64-bit distinct keys ride as (hi, lo) uint32 bit-planes, the
            # same wide-tile format sample() ships per tile — split ONCE on
            # the host, then the whole plane pair goes in one transfer
            stream_hi, stream_lo = _distinct.split_values_host(stream)
        else:
            canon = jax.dtypes.canonicalize_dtype(stream.dtype)
            if stream.dtype != canon:
                stream = stream.astype(canon)  # pre-transfer, like sample()
        steady = (
            not self._config.distinct
            and not self._config.weighted
            and self._min_count >= self._config.max_sample_size
        )
        use_pallas = self._pallas_eligible(steady, False, stream.dtype)
        fn = self._fused_update_fn(n_full, B, steady, stream.dtype, use_pallas)
        def to_tiles(arr):
            t = np.ascontiguousarray(arr.reshape(R, n_full, B).swapaxes(0, 1))
            if np.shares_memory(t, arr):
                # R == 1 makes the transpose a no-op view of the CALLER's
                # buffer — snapshot before the async device_put (the same
                # contract sample() keeps with np.array(copy=True))
                t = t.copy()
            return t

        if wide:
            # hi/lo are freshly allocated above, so the async read is safe
            stage = {"tiles": (to_tiles(stream_hi), to_tiles(stream_lo))}
        else:
            stage = {"tiles": to_tiles(stream)}
        if weights is not None:
            stage["weights"] = to_tiles(weights)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as _P

            sh = NamedSharding(
                self._mesh, _P(None, self._config.mesh_axis, None)
            )
            placed = jax.device_put(stage, jax.tree.map(lambda _: sh, stage))
        else:
            placed = jax.device_put(stage, self._device)
        def rebuild_xla():
            return self._fused_update_fn(
                n_full, B, steady, stream.dtype, False
            )

        if weights is not None:
            self._state = self._call_update(
                fn, use_pallas, rebuild_xla, self._state,
                (placed["tiles"], placed["weights"]),
            )
        else:
            self._state = self._call_update(
                fn, use_pallas, rebuild_xla, self._state, (placed["tiles"],)
            )
        self._min_count += n_full * B

    # ------------------------------------------------------------ row leasing

    def reset_rows(self, rows: Any, key: Union[int, jax.Array]) -> None:
        """Re-initialize the given reservoir rows in place to empty state
        with fresh randomness derived from ``key`` — the session-recycling
        primitive of the serving plane (:mod:`reservoir_tpu.serve`).

        The engine is NOT reseeded: only the named rows are rebuilt, by
        scattering a freshly ``init``-ed sub-state over them, so every
        other row's stream continues bit-identically.  Callers derive
        ``key`` per ``(row, generation)`` with counter-keyed Threefry
        fold-ins (``SessionTable.sub_key``), which makes a recycled row
        statistically fresh AND the reset deterministic — replayable after
        :meth:`~reservoir_tpu.stream.bridge.DeviceStreamBridge.recover`.

        Single-writer contract as :meth:`sample`: callers using a pipelined
        bridge must drain it first.  Resets drop the host-side fill lower
        bound to 0, so later duplicates-mode tiles re-take the fill-capable
        path (a device-side no-op for rows already full).
        """
        self._check_open()
        rows = self._validate_rows(rows)
        if isinstance(key, int):
            key = jr.key(key)
        fn = self._reset_jit.get(rows.size)
        if fn is None:
            # ONE jitted dispatch per reset batch: sub-state init fused
            # with the scatter (an eager init costs ~100ms of per-op
            # dispatch; session churn makes this a serving hot path)
            n = int(rows.size)
            k = self._config.max_sample_size
            sample_dtype = jnp.dtype(self._config.resolved_sample_dtype())
            count_dtype = (
                self._config.count_dtype
                if self._config.count_dtype == "wide"
                else jnp.dtype(self._config.count_dtype)
            )
            ops = self._ops
            shared_key = (
                (ops, n, k, sample_dtype, count_dtype)
                if self._mesh is None
                else None
            )
            if shared_key is not None:
                fn = _SHARED_RESET_JIT.get(shared_key)
            if fn is None:

                def reset(state, reset_key, idx):
                    part = ops.init(
                        reset_key, n, k,
                        sample_dtype=sample_dtype, count_dtype=count_dtype,
                    )
                    return jax.tree.map(
                        lambda full, one: full.at[idx].set(one), state, part
                    )

                fn = jax.jit(reset, donate_argnums=(0,))
                if shared_key is not None:
                    _SHARED_RESET_JIT[shared_key] = fn
            self._reset_jit[rows.size] = fn
        idx = rows
        if self._mesh is not None:
            idx = jax.device_put(rows)  # scatter indices are replicated
        self._state = fn(self._state, key, idx)
        if self._mesh is not None:
            from .parallel import shard_state

            # the scatter may have loosened the reservoir-axis sharding;
            # re-pin it so later updates stay collective-free SPMD
            self._state = shard_state(
                self._state, self._mesh, self._config.mesh_axis
            )
        self._min_count = 0
        self.reset_epochs += 1

    def _validate_rows(self, rows: Any) -> np.ndarray:
        rows = np.asarray(rows, np.int32)
        if rows.ndim != 1 or rows.size == 0:
            raise ValueError(
                f"rows must be a non-empty 1-D index array, got shape {rows.shape}"
            )
        R = self._config.num_reservoirs
        if int(rows.min()) < 0 or int(rows.max()) >= R:
            bad = int(rows[np.argmax((rows < 0) | (rows >= R))])
            raise ValueError(f"row {bad} out of range [0, {R})")
        return rows

    def export_rows(self, rows: Any):
        """Gather the COMPLETE per-row sub-state for ``rows`` — samples,
        counters, and the per-row PRNG keys — as a pytree with leading
        axis ``len(rows)``: the live-migration export (ISSUE 12).

        Every state field carries the reservoir axis first (the same
        invariant :meth:`reset_rows` scatters against), so the export is a
        uniform gather and :meth:`adopt_rows` on another engine of the
        SAME config/mode reproduces the rows bit-exactly — including
        future acceptance draws, because per-row keys travel with the
        rows.  The gathered arrays are fresh buffers, safe against the
        donation fast path.  Single-writer contract as :meth:`sample`:
        drain a pipelined bridge first.
        """
        self._check_open()
        rows = self._validate_rows(rows)
        idx = jnp.asarray(rows)
        return jax.tree.map(lambda x: x[idx], self._state)

    def adopt_rows(self, rows: Any, sub_state: Any) -> None:
        """Scatter an :meth:`export_rows` sub-state over ``rows`` — the
        live-migration adopt.  One jitted dispatch (shared process-wide;
        the dual of :meth:`reset_rows`'s init-scatter).  The adopted rows
        continue their source streams bit-identically; like a reset, the
        adopt drops the host-side fill lower bound and bumps
        :attr:`reset_epochs` so an ingest-side skip gate re-pulls.
        """
        self._check_open()
        rows = self._validate_rows(rows)
        lead = {int(x.shape[0]) for x in jax.tree.leaves(sub_state)}
        if lead != {int(rows.size)}:
            raise ValueError(
                f"sub_state leading axis {sorted(lead)} does not match "
                f"{rows.size} rows"
            )
        if self._device is not None:
            # the exported rows may be committed to the SOURCE shard's
            # device — re-commit before the scatter (mixed committed
            # placements are an error under jit)
            sub_state = jax.device_put(sub_state, self._device)
        idx: Any = rows
        if self._mesh is not None:
            idx = jax.device_put(rows)  # scatter indices are replicated
            sub_state = jax.device_put(sub_state)
        self._state = _ADOPT_JIT(self._state, sub_state, idx)
        if self._mesh is not None:
            from .parallel import shard_state

            self._state = shard_state(
                self._state, self._mesh, self._config.mesh_axis
            )
        self._min_count = 0
        self.reset_epochs += 1

    # ----------------------------------------------------------- checkpoints

    def save(self, path: str, metadata: Optional[dict] = None) -> None:
        """Checkpoint state + config to ``path`` (atomic ``.npz``); resume
        with :meth:`restore` — bit-exact, because draws are keyed on absolute
        stream indices (SURVEY §5 checkpoint row)."""
        from .utils.checkpoint import save_engine

        save_engine(path, self, metadata=metadata)

    @classmethod
    def restore(
        cls,
        path: str,
        map_fn: Optional[Callable] = None,
        hash_fn: Optional[Callable] = None,
    ) -> "ReservoirEngine":
        """Reconstruct a checkpointed engine; ``map_fn``/``hash_fn`` are code
        and must be re-supplied when the checkpoint was taken with them."""
        from .utils.checkpoint import load_engine

        return load_engine(path, map_fn=map_fn, hash_fn=hash_fn, engine_cls=cls)

    # --------------------------------------------------------------- results

    def result_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """Device->host result: ``(samples [R, k], sizes [R])`` with the
        truncation contract of ``Sampler.scala:318-331``.  Single-use engines
        close and free device buffers (``:345-350``); reusable engines
        snapshot — earlier results are never clobbered because state arrays
        are immutable (the copy-on-write guarantee of ``Sampler.scala:353-381``
        holds structurally)."""
        self._check_open()
        samples, sizes = self._ops.result(self._state)
        if self._wide:
            samples = _distinct.assemble_values(
                samples,
                self._state.value_hi,
                np.dtype(self._config.resolved_sample_dtype()),
            )
        out = (np.asarray(samples), np.asarray(sizes))
        if not self._reusable:
            self._open = False
            self._state = None  # free device buffers
            self._jit_cache.clear()
            self._reset_jit.clear()
        return out

    def peek_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """Non-destructive :meth:`result_arrays`: the same device->host
        ``(samples [R, k], sizes [R])`` with the same truncation contract,
        but the engine stays open — single-use or not — and keeps
        streaming.  This is the serving plane's live snapshot path
        (:mod:`reservoir_tpu.serve`): results are readable while streams
        are still open, without spending the single-use lifecycle.

        Safe against the donation fast path because the host copy is taken
        before any later update can consume the state buffers; callers
        sharing the engine with a pipelined bridge must drain it first
        (the engine's single-writer contract)."""
        self._check_open()
        state = self._state
        samples, sizes = self._ops.result(state)
        if self._wide:
            samples = _distinct.assemble_values(
                samples,
                state.value_hi,
                np.dtype(self._config.resolved_sample_dtype()),
            )
        return np.asarray(samples), np.asarray(sizes)

    def result(self) -> List[np.ndarray]:
        """Per-reservoir samples, truncated to their fill level."""
        samples, sizes = self.result_arrays()
        return [samples[r, : sizes[r]] for r in range(samples.shape[0])]
