"""ReservoirEngine — R lockstep device reservoirs behind the Sampler lifecycle.

This is the batch/device counterpart of :mod:`reservoir_tpu.api`: the same
construction-time validation, single-use/reusable lifecycle and result
truncation contract as the reference factories, but the "element" granularity
is a ``[R, B]`` tile — reservoir ``r`` consumes ``tile[r, :valid[r]]`` of its
own stream.  The engine owns:

- the pure :class:`~reservoir_tpu.ops.algorithm_l.ReservoirState` pytree
  (device-resident, never mutated in place — every sampler is copy-on-write
  for free, making ``reusable`` trivial; cf. the reference's aliasing
  machinery ``Sampler.scala:353-381``);
- jitted update functions cached per (tile width, steady, map_fn) —
  jit-compile is the engine's analog of the reference release-build inliner
  (``build.sbt:134-141``);
- the fill/steady dispatch: reservoirs advance in lockstep, so a host-side
  lower bound on ``count`` (no device sync) decides when the fill-phase
  scatter can be dropped from the compiled program.

``SamplerConfig(distinct=True)`` selects the bottom-k kernel of
:mod:`reservoir_tpu.ops.distinct` behind the same surface; weighted mode
arrives with SURVEY §7.2 M6.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from .config import SamplerConfig, validate_max_sample_size
from .errors import SamplerClosedError
from .ops import algorithm_l as _algl
from .ops import distinct as _distinct

__all__ = ["ReservoirEngine"]


class ReservoirEngine:
    """R independent k-reservoirs updated in lockstep on device.

    Args:
      config: engine configuration (k, R, dtypes, tile size, distinct).
      key: JAX PRNG key (or ``seed`` int).  Explicit-by-construction
        reproducibility (``SamplerTest.scala:16-54``'s lesson).
      map_fn: traceable map; applied on accept in duplicates mode
        (``Sampler.scala:116``), to every element in distinct mode (``:155``).
      hash_fn: distinct mode only — traceable tile hash returning a
        ``(hi, lo)`` uint32 pair (``Sampler.distinct``'s hash hook, ``:173``).
      reusable: reference lifecycle switch (``Sampler.scala:130-136``);
        single-use engines free device buffers on ``result()``.
    """

    def __init__(
        self,
        config: SamplerConfig,
        key: Union[int, jax.Array, None] = None,
        map_fn: Optional[Callable] = None,
        hash_fn: Optional[Callable] = None,
        reusable: bool = False,
    ) -> None:
        validate_max_sample_size(config.max_sample_size)
        if config.weighted:
            raise NotImplementedError("weighted mode arrives with M6")
        self._config = config
        self._map_fn = map_fn
        self._hash_fn = hash_fn
        self._reusable = reusable
        self._open = True
        if hash_fn is not None and not config.distinct:
            raise ValueError("hash_fn is only meaningful with distinct=True")
        self._ops = _distinct if config.distinct else _algl
        if key is None or isinstance(key, int):
            key = jr.key(0 if key is None else key)
        self._state = self._ops.init(
            key,
            config.num_reservoirs,
            config.max_sample_size,
            sample_dtype=jnp.dtype(config.resolved_sample_dtype()),
            count_dtype=jnp.dtype(config.count_dtype),
        )
        # Host-side lower bound on every reservoir's count — exact when all
        # tiles are full-width, conservative under ragged `valid`.  Decides
        # fill vs steady dispatch with no device readback.
        self._min_count = 0
        self._jit_cache: dict = {}

    # ------------------------------------------------------------ properties

    @property
    def config(self) -> SamplerConfig:
        return self._config

    @property
    def is_open(self) -> bool:
        """Reference ``isOpen`` (``Sampler.scala:67``): reusable engines are
        always open (``:380``); single-use close on ``result()``."""
        return True if self._reusable else self._open

    @property
    def state(self) -> Union[_algl.ReservoirState, _distinct.DistinctState]:
        """A snapshot of the state pytree (``ReservoirState`` in duplicates
        mode, ``DistinctState`` in distinct mode).  Copied, because the engine's
        jitted updates donate the previous state's buffers (the streaming
        fast path) — handing out the live buffers would let a later
        ``sample()`` delete them out from under the caller."""
        self._check_open()
        return jax.tree.map(lambda x: x.copy(), self._state)

    # ------------------------------------------------------------- lifecycle

    def _check_open(self) -> None:
        if not self._reusable and not self._open:
            raise SamplerClosedError(
                "this engine is single-use, and no longer open"
            )

    # -------------------------------------------------------------- sampling

    def _update_fn(self, width: int, steady: bool):
        cache_key = (width, steady)
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            base = self._ops.update_steady if steady else self._ops.update
            kwargs = {"map_fn": self._map_fn}
            if self._config.distinct:
                kwargs["hash_fn"] = self._hash_fn
            fn = jax.jit(
                functools.partial(base, **kwargs),
                donate_argnums=(0,),
            )
            self._jit_cache[cache_key] = fn
        return fn

    def sample(self, tile: Any, valid: Optional[Any] = None) -> None:
        """Consume one ``[R, B]`` tile (the engine's per-element hot path —
        the batched analog of ``Sampler.scala:248-259``)."""
        self._check_open()
        tile = jnp.asarray(tile)
        if tile.ndim != 2 or tile.shape[0] != self._config.num_reservoirs:
            raise ValueError(
                f"tile must be [num_reservoirs={self._config.num_reservoirs}, B], "
                f"got {tile.shape}"
            )
        width = tile.shape[1]
        # distinct mode has one code path (update_steady is update); collapse
        # the cache key so crossing the fill boundary never recompiles
        steady = (
            not self._config.distinct
            and self._min_count >= self._config.max_sample_size
        )
        fn = self._update_fn(width, steady)
        if valid is None:
            self._state = fn(self._state, tile)
            self._min_count += width
        else:
            valid_np = np.asarray(valid, np.int32)
            if valid_np.shape != (self._config.num_reservoirs,):
                raise ValueError(
                    f"valid must be [{self._config.num_reservoirs}], got {valid_np.shape}"
                )
            if np.any(valid_np < 0) or np.any(valid_np > width):
                raise ValueError(
                    f"valid entries must be in [0, {width}], got "
                    f"[{valid_np.min()}, {valid_np.max()}]"
                )
            self._state = fn(self._state, tile, jnp.asarray(valid_np))
            self._min_count += int(valid_np.min())

    def sample_all(self, tiles: Any) -> None:
        """Consume an iterable of tiles (bulk path, ``Sampler.scala:341``)."""
        self._check_open()
        for tile in tiles:
            if isinstance(tile, tuple):
                self.sample(tile[0], tile[1])
            else:
                self.sample(tile)

    def sample_stream(self, stream: Any, tile_width: Optional[int] = None) -> None:
        """Feed one ``[R, N]`` array, auto-tiled to ``config.tile_size``
        columns with a masked ragged tail — never re-jitting per remainder."""
        self._check_open()
        stream = np.asarray(stream)
        R, N = stream.shape
        B = tile_width or self._config.tile_size
        for start in range(0, N, B):
            chunk = stream[:, start : start + B]
            w = chunk.shape[1]
            if w < B:
                pad = np.zeros((R, B - w), chunk.dtype)
                self.sample(
                    np.concatenate([chunk, pad], axis=1),
                    np.full((R,), w, np.int32),
                )
            else:
                self.sample(chunk)

    # --------------------------------------------------------------- results

    def result_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """Device->host result: ``(samples [R, k], sizes [R])`` with the
        truncation contract of ``Sampler.scala:318-331``.  Single-use engines
        close and free device buffers (``:345-350``); reusable engines
        snapshot — earlier results are never clobbered because state arrays
        are immutable (the copy-on-write guarantee of ``Sampler.scala:353-381``
        holds structurally)."""
        self._check_open()
        samples, sizes = self._ops.result(self._state)
        out = (np.asarray(samples), np.asarray(sizes))
        if not self._reusable:
            self._open = False
            self._state = None  # free device buffers
            self._jit_cache.clear()
        return out

    def result(self) -> List[np.ndarray]:
        """Per-reservoir samples, truncated to their fill level."""
        samples, sizes = self.result_arrays()
        return [samples[r, : sizes[r]] for r in range(samples.shape[0])]
