"""Multi-host scale-out: joining the JAX process group.

The reference has no distributed layer (SURVEY §2.4); this framework's
communication backend is XLA collectives over whatever mesh
:func:`~reservoir_tpu.parallel.make_mesh` builds.  Scaling from one host to
a pod slice needs exactly one extra step — every process joins the JAX
distributed runtime BEFORE first backend use.  After that ``jax.devices()``
returns the *global* device list, ``make_mesh`` spans hosts, and the same
``shard_map`` programs ride ICI within a host group and DCN across them
(XLA chooses the transport; there is no NCCL/MPI analog to manage).

Typical pod usage::

    from reservoir_tpu.parallel import multihost
    multihost.initialize()            # no-op single-process; auto-detects pods
    mesh = make_mesh()                # now spans every host's chips
    eng = ReservoirEngine(SamplerConfig(..., mesh_axis="res"), mesh=mesh)

Result gathers (``sharded_result``) and stream-axis merges
(:mod:`.merge`) are ordinary XLA collectives and work unchanged on a
multi-host mesh.
"""

from __future__ import annotations

from typing import List, Optional

__all__ = ["initialize", "is_initialized", "spread_devices"]


def _cluster_env_detected() -> bool:
    """Whether JAX's cluster auto-detection would find an environment.

    Uses the same registry ``jax.distributed.initialize`` consults
    (``ClusterEnv`` subclasses: GCE TPU pod metadata, SLURM, Open MPI, ...)
    so :func:`initialize` can tell "nothing to join" apart from "cluster
    present but the join failed".  Falls back to well-known env markers if
    the private registry moves.
    """
    try:
        from jax._src.clusters import ClusterEnv

        # mirror jax's auto_detect filter: opt-in-only detectors (e.g.
        # Mpi4pyCluster, whose is_env_present is just "mpi4py importable")
        # are NOT consulted by a no-arg initialize, so their presence must
        # not promote a plain single-process run into a re-raise
        return any(
            c.is_env_present()
            for c in ClusterEnv._cluster_types
            if not getattr(c, "opt_in_only_method", False)
        )
    except Exception:  # pragma: no cover - jax internal layout changed
        import os

        markers = (
            "SLURM_JOB_ID",
            "OMPI_COMM_WORLD_SIZE",
            "TPU_WORKER_HOSTNAMES",
            "CLOUD_TPU_TASK_ID",
            "MEGASCALE_COORDINATOR_ADDRESS",
        )
        return any(m in os.environ for m in markers)


def spread_devices(n: int) -> List:
    """Round-robin this process's addressable devices across ``n`` slots.

    The serving plane's placement helper: ``n`` shards (or any other
    per-unit state owners) each get one ``jax.Device``, cycling through
    ``jax.local_devices()`` so consecutive shards land on distinct chips
    when there are enough and share fairly when there are not.  Only
    *addressable* devices are handed out — a shard must be able to commit
    arrays to its device, so global (other-process) devices from a joined
    pod are never returned.
    """
    import jax

    if n < 1:
        raise ValueError(f"spread_devices: n must be >= 1, got {n}")
    devs = jax.local_devices()
    return [devs[i % len(devs)] for i in range(int(n))]


def is_initialized() -> bool:
    """Whether this process has joined a JAX distributed runtime."""
    try:  # public location in newer jax; private module before that
        import jax.distributed as jd

        state = getattr(jd, "global_state", None)
        if state is None:
            from jax._src.distributed import global_state as state
    except ImportError:  # pragma: no cover - layout changed again
        return False
    return getattr(state, "client", None) is not None


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> bool:
    """Join the JAX process group; safe to call unconditionally.

    - already joined -> True (idempotent, never re-initializes);
    - explicit ``coordinator_address``/``num_processes``/``process_id``
      -> joins (errors surface: the caller meant it);
    - no arguments -> defers to JAX's own cluster auto-detection (GCE TPU
      pod metadata, SLURM, Open MPI, ...); a plain single-process run has
      nothing to detect and returns False without touching the backend
      (``make_mesh`` then spans the local devices only).

    Extra ``kwargs`` (e.g. ``local_device_ids``) pass through to
    ``jax.distributed.initialize``.
    """
    import jax

    if is_initialized():
        return True
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
        or bool(kwargs)
    )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
    except (RuntimeError, ValueError) as e:
        if explicit or _cluster_env_detected():
            # The caller meant to join (explicit params), or a cluster
            # environment IS present and the join still failed (e.g.
            # coordinator unreachable on a real pod) — silently degrading
            # to single-process would hand back per-host-only results.
            raise
        # JAX found no cluster to auto-detect: ordinary single-process run.
        # Still surface the swallowed error — "no cluster" is an inference,
        # not a certainty (ADVICE r2).
        import warnings

        warnings.warn(
            "multihost.initialize(): no cluster environment detected; "
            f"running single-process (jax.distributed.initialize said: {e})",
            RuntimeWarning,
            stacklevel=2,
        )
        return False
    return True
