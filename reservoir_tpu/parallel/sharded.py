"""Reservoir-axis sharding over a device mesh ("DP over reservoirs").

The scaling story (SURVEY §2.4, new component): R reservoirs shard over the
mesh's reservoir axis — 65,536 streams on a v5e-8 = 8,192 reservoirs per
chip, updated by exactly the same pure :mod:`reservoir_tpu.ops` kernels.  We
follow the pjit recipe (annotate shardings, let XLA insert collectives):

- ``update`` is embarrassingly parallel along R -> with state and tiles
  sharded ``P('res')``, XLA compiles a collective-free SPMD program; tiles
  arriving from the host are device_put with the same sharding so each chip
  only receives its shard over PCIe/ICI.
- ``result`` gathers are expressed by requesting replicated (or host-bound)
  output shardings -> XLA inserts the ``all_gather`` over ICI.
- cross-reservoir reductions (global counts, eviction stats) are plain
  ``jnp`` reductions on sharded arrays -> XLA lowers to ``psum`` over ICI.

Every helper here is mode-generic: the three state pytrees
(:class:`~reservoir_tpu.ops.algorithm_l.ReservoirState`,
:class:`~reservoir_tpu.ops.distinct.DistinctState`,
:class:`~reservoir_tpu.ops.weighted.WeightedState`) are NamedTuples whose
leaves all carry the reservoir dimension first, so "shard the leading axis,
replicate the rest" is a ``tree.map``.  Pass the matching ``ops`` module to
:func:`sharded_update`/:func:`sharded_result` (default: Algorithm L).

Stream-axis parallelism (one logical stream split across chips) is the
mergeable-summary path in :mod:`reservoir_tpu.parallel.merge`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import algorithm_l as _algl

__all__ = [
    "make_mesh",
    "reservoir_sharding",
    "shard_map",
    "state_shardings",
    "shard_state",
    "sharded_update",
    "sharded_result",
]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across the jax versions this repo meets.

    Newer jax exposes it as ``jax.shard_map`` (with ``check_vma``); 0.4.x
    only has ``jax.experimental.shard_map.shard_map`` (same semantics, the
    flag is spelled ``check_rep``).  One compat seam so the engine's
    Pallas-under-mesh path and the stream-axis mergers don't each carry
    version probes."""
    if hasattr(jax, "shard_map"):
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _sm

    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def make_mesh(
    num_devices: Optional[int] = None, axis: str = "res", devices=None
) -> Mesh:
    """A 1-D mesh over the reservoir axis.

    On real hardware the devices are the chips of the slice (ICI-connected);
    in tests they are virtual CPU devices (SURVEY §4.4).
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if len(devices) < num_devices:
            raise ValueError(
                f"requested a {num_devices}-device mesh but only "
                f"{len(devices)} devices are available"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis,))


def reservoir_sharding(mesh: Mesh, axis: str = "res") -> NamedSharding:
    """Shard the leading (reservoir) dimension over ``axis``."""
    return NamedSharding(mesh, P(axis))


def _leaf_sharding(mesh: Mesh, axis: str, ndim: int) -> NamedSharding:
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def state_shardings(state, mesh: Mesh, axis: str = "res"):
    """The sharding pytree for any mode's state: leading (reservoir) dim
    over ``axis``, everything else replicated."""
    return jax.tree.map(lambda x: _leaf_sharding(mesh, axis, x.ndim), state)


def shard_state(state, mesh: Mesh, axis: str = "res"):
    """Place every ``[R, ...]`` leaf of any mode's state with its reservoir
    dimension sharded over ``axis`` (e.g. samples ``[R,k]`` -> ``P(axis, None)``)."""
    return jax.tree.map(
        lambda x: jax.device_put(x, _leaf_sharding(mesh, axis, x.ndim)), state
    )


def sharded_update(mesh: Mesh, axis: str = "res", steady: bool = False, ops=_algl):
    """Tile update with explicit reservoir-axis shardings, any mode.

    Returns ``fn(state, batch, *extra) -> state`` where ``batch`` (and any
    ``extra`` array, e.g. the weighted mode's weights tile) is ``[R, B]``
    sharded ``P(axis, None)``.  Collective-free SPMD: each chip updates its
    reservoir shard independently (verified in ``tests/test_sharding.py`` on a
    virtual 8-device mesh).  The jit is built on first call, when the state's
    pytree structure is known.
    """
    base = ops.update_steady if steady else ops.update
    tile_sh = NamedSharding(mesh, P(axis, None))
    cache: dict = {}

    def call(state, batch, *extra):
        fn = cache.get(len(extra))
        if fn is None:
            sh = state_shardings(state, mesh, axis)
            fn = jax.jit(
                lambda st, b, *e: base(st, b, *e),
                in_shardings=(sh, tile_sh) + (tile_sh,) * len(extra),
                out_shardings=sh,
                donate_argnums=(0,),
            )
            cache[len(extra)] = fn
        return fn(state, batch, *extra)

    return call


def sharded_result(mesh: Mesh, axis: str = "res", ops=_algl):
    """``result`` that replicates the gathered sample matrix on every chip —
    the ``all_gather`` over ICI is inserted by XLA from the replicated output
    sharding — plus a global count reduction (psum), any mode."""
    replicated = NamedSharding(mesh, P())
    cache: dict = {}

    def call(state):
        fn = cache.get("fn")
        if fn is None:

            def body(st):
                samples, sizes = ops.result(st)
                if st.count.ndim == 2:  # WIDE planes: f32 total (a stat,
                    # not sampling state — counts this large exceed int32)
                    from ..ops import u64e

                    total = jnp.sum(u64e.to_f32(st.count))
                else:
                    total = jnp.sum(st.count)  # lowers to psum over the mesh
                return samples, sizes, total

            fn = jax.jit(
                body,
                in_shardings=(state_shardings(state, mesh, axis),),
                out_shardings=(replicated, replicated, replicated),
            )
            cache["fn"] = fn
        return fn(state)

    return call
