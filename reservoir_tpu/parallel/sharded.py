"""Reservoir-axis sharding over a device mesh ("DP over reservoirs").

The scaling story (SURVEY §2.4, new component): R reservoirs shard over the
mesh's reservoir axis — 65,536 streams on a v5e-8 = 8,192 reservoirs per
chip, updated by exactly the same pure :func:`reservoir_tpu.ops.algorithm_l`
kernels.  We follow the pjit recipe (annotate shardings, let XLA insert
collectives):

- ``update`` is embarrassingly parallel along R -> with state and tiles
  sharded ``P('res')``, XLA compiles a collective-free SPMD program; tiles
  arriving from the host are device_put with the same sharding so each chip
  only receives its shard over PCIe/ICI.
- ``result`` gathers are expressed by requesting replicated (or host-bound)
  output shardings -> XLA inserts the ``all_gather`` over ICI.
- cross-reservoir reductions (global counts, eviction stats) are plain
  ``jnp`` reductions on sharded arrays -> XLA lowers to ``psum`` over ICI.

Stream-axis parallelism (one logical stream split across chips) is the
mergeable-summary path in :mod:`reservoir_tpu.parallel.merge`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import algorithm_l as _algl

__all__ = [
    "make_mesh",
    "reservoir_sharding",
    "shard_state",
    "sharded_update",
    "sharded_result",
]


def make_mesh(
    num_devices: Optional[int] = None, axis: str = "res", devices=None
) -> Mesh:
    """A 1-D mesh over the reservoir axis.

    On real hardware the devices are the chips of the slice (ICI-connected);
    in tests they are virtual CPU devices (SURVEY §4.4).
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if len(devices) < num_devices:
            raise ValueError(
                f"requested a {num_devices}-device mesh but only "
                f"{len(devices)} devices are available"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis,))


def reservoir_sharding(mesh: Mesh, axis: str = "res") -> NamedSharding:
    """Shard the leading (reservoir) dimension over ``axis``."""
    return NamedSharding(mesh, P(axis))


def shard_state(
    state: _algl.ReservoirState, mesh: Mesh, axis: str = "res"
) -> _algl.ReservoirState:
    """Place every ``[R, ...]`` leaf of the state with its reservoir dimension
    sharded over ``axis`` (samples ``[R,k]`` -> ``P(axis, None)``)."""
    s1 = NamedSharding(mesh, P(axis))
    s2 = NamedSharding(mesh, P(axis, None))
    return _algl.ReservoirState(
        samples=jax.device_put(state.samples, s2),
        count=jax.device_put(state.count, s1),
        nxt=jax.device_put(state.nxt, s1),
        log_w=jax.device_put(state.log_w, s1),
        key=jax.device_put(state.key, s1),
    )


def sharded_update(mesh: Mesh, axis: str = "res", steady: bool = False):
    """Jitted tile update with explicit reservoir-axis shardings.

    Returns ``fn(state, batch) -> state`` where ``batch`` is ``[R, B]``
    sharded ``P(axis, None)``.  Collective-free SPMD: each chip updates its
    reservoir shard independently (verified in ``tests/test_sharding.py`` on a
    virtual 8-device mesh).
    """
    base = _algl.update_steady if steady else _algl.update
    s1 = NamedSharding(mesh, P(axis))
    s2 = NamedSharding(mesh, P(axis, None))
    state_shardings = _algl.ReservoirState(
        samples=s2, count=s1, nxt=s1, log_w=s1, key=s1
    )
    return jax.jit(
        lambda state, batch: base(state, batch),
        in_shardings=(state_shardings, s2),
        out_shardings=state_shardings,
        donate_argnums=(0,),
    )


def sharded_result(mesh: Mesh, axis: str = "res"):
    """Jitted ``result`` that replicates the gathered sample matrix on every
    chip — the ``all_gather`` over ICI is inserted by XLA from the replicated
    output sharding."""
    s1 = NamedSharding(mesh, P(axis))
    s2 = NamedSharding(mesh, P(axis, None))
    state_shardings = _algl.ReservoirState(
        samples=s2, count=s1, nxt=s1, log_w=s1, key=s1
    )
    replicated = NamedSharding(mesh, P())

    def fn(state):
        samples, sizes = _algl.result(state)
        total = jnp.sum(state.count)  # lowers to psum over the mesh
        return samples, sizes, total

    return jax.jit(
        fn,
        in_shardings=(state_shardings,),
        out_shardings=(replicated, replicated, replicated),
    )
