"""Multi-chip scale: mesh sharding, collectives, reservoir merge.

The reference has no distributed layer at all (SURVEY §2.4) — this package is
the new first-class component: reservoir-axis data parallelism over a
``jax.sharding.Mesh``, XLA collectives over ICI/DCN for result gathers, and
stream-axis parallelism via mergeable reservoir summaries.
"""

from . import multihost
from .sharded import (
    make_mesh,
    reservoir_sharding,
    shard_state,
    sharded_update,
    sharded_result,
    state_shardings,
)

__all__ = [
    "make_mesh",
    "multihost",
    "reservoir_sharding",
    "shard_state",
    "sharded_update",
    "sharded_result",
    "state_shardings",
]
