"""Stream-axis parallelism: one logical stream sharded across chips.

The reference handles its "long" dimension — the unbounded stream — in O(k)
memory on one thread (``Sampler.scala:11-12``); the TPU framework adds the
axis the reference cannot: split a logical stream across D devices, sample
each shard independently (zero communication in the hot loop), and combine
with an exact merge that rides ICI collectives (SURVEY §5 long-context row).

Mechanism per mode:

- uniform (Algorithm L): hypergeometric pairwise merge
  (:func:`reservoir_tpu.ops.algorithm_l.merge_samples`), combined across
  the device axis by a log-depth tree after an ``all_gather``;
- distinct: bottom-k union (shared salts across shards);
- weighted: top-k union of ES keys.

The collective is one ``all_gather`` of O(R·k) summary state per result —
amortized over arbitrarily long shard streams; the hot sampling loop stays
collective-free.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import jax.random as jr
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharded import shard_map as _shard_map
from ..ops import algorithm_l as _algl
from ..ops import distinct as _distinct
from ..ops import weighted as _weighted
from ..utils.tracing import trace_span

from ..utils.log import warn_once

__all__ = [
    "uniform_stream_merger",
    "distinct_stream_merger",
    "weighted_stream_merger",
    "merge_samples_host",
    "merge_samples_device",
    "host_pairwise_trace_count",
]

_MODES = ("uniform", "weighted", "distinct")


class _Flags:
    """Module-scoped once-flags for the device-merge demotion logs."""


_flags = _Flags()


def _pairwise_fn(mode: str):
    """The eager pairwise merge over per-part leaf tuples for ``mode``
    (uniform takes a key and is handled separately — its tree is
    node-numbered)."""
    if mode == "weighted":

        def pw(a, b):
            return _weighted.merge_parts(a[0], a[1], a[2], b[0], b[1], b[2])

        return pw

    def pw(a, b):  # distinct: leaves (values, hash_hi, hash_lo, size,
        # count, salts) — salts shared (same init key), A's carried
        sa = _distinct.DistinctState(a[0], a[1], a[2], a[3], a[4], a[5])
        sb = _distinct.DistinctState(b[0], b[1], b[2], b[3], b[4], b[5])
        m = _distinct.merge(sa, sb)
        return (m.values, m.hash_hi, m.hash_lo, m.size, m.count, a[5])

    return pw


@functools.lru_cache(maxsize=None)
def _host_pairwise(mode: str = "uniform"):
    """One process-wide jitted pairwise merge per mode (shapes/dtypes are
    jit's own cache axes).  Hoisted out of :func:`merge_samples_host`'s
    module global into the same memoization discipline as the stream-merger
    constructors: repeated cluster ``merged_snapshot`` calls reuse one
    wrapper, so the second merge at any shape is trace-free (asserted by
    ``bench.py merge``)."""
    if mode == "uniform":
        return jax.jit(_algl.merge_samples)
    pw = _pairwise_fn(mode)
    return jax.jit(lambda a, b: pw(a, b))


def host_pairwise_trace_count(mode: str = "uniform") -> int:
    """Number of distinct pairwise-merge traces compiled so far for
    ``mode`` — stable across repeated same-shape merges (the satellite
    trace-free assertion ``bench.py merge`` pins in-run)."""
    return _host_pairwise(mode)._cache_size()


def merge_samples_host(
    parts: Sequence[Tuple[np.ndarray, int]],
    key,
    *,
    max_sample_size: int,
) -> Tuple[np.ndarray, int]:
    """Host-side log-depth tree merge of per-shard uniform samples.

    The sharded serving plane (ISSUE 9) routes whole sessions to shards,
    so a cross-shard "one logical sample" query — N sessions, possibly on
    N different shards, read as a single uniform sample of their combined
    streams — merges *host* snapshot arrays, not meshed device state.
    This is the same exact hypergeometric pairwise merge the mesh mergers
    ride (:func:`reservoir_tpu.ops.algorithm_l.merge_samples`, one
    reservoir row per part), combined by the same deterministic log-depth
    node-numbered tree as :func:`uniform_stream_merger` — so for a fixed
    ``key`` and part order the result is bit-reproducible, and a
    single-shard oracle that merges its per-session oracle replays with
    this very function reconciles bit-for-bit (pinned by
    ``tests/test_cluster.py``).

    Args:
      parts: ``(sample, count)`` pairs — each sample a 1-D array already
        truncated to its fill (``ReservoirService.snapshot`` output), each
        count that session's total stream length.
      key: PRNG key or int seed for the merge draws.
      max_sample_size: the configs' ``k`` (merged size is
        ``min(sum(counts), k)``).

    Returns ``(merged_sample, total_count)`` with the merged sample
    truncated to its size.  Uniform (plain) mode only: weighted/distinct
    merges are state-keyed (ES keys / hash planes) and ride the mesh
    mergers below.
    """
    if not parts:
        raise ValueError("merge_samples_host needs at least one part")
    k = int(max_sample_size)
    if isinstance(key, int):
        key = jr.key(key)
    dtype = np.asarray(parts[0][0]).dtype
    # one jitted pairwise merge, shape/dtype-cached by jit itself: the
    # eager k-step scan costs ~100x per pair on the host path
    pairwise = _host_pairwise("uniform")

    def _lift(sample, count):
        arr = np.zeros((1, k), dtype)
        s = np.atleast_1d(np.asarray(sample, dtype))[:k]
        arr[0, : s.shape[0]] = s
        return jnp.asarray(arr), jnp.asarray([int(count)], jnp.uint32)

    with trace_span("reservoir_merge_host"):
        items = [_lift(s, c) for s, c in parts]
        node = 0
        while len(items) > 1:
            nxt = []
            for i in range(0, len(items) - 1, 2):
                node += 1
                s, c = pairwise(
                    items[i][0], items[i][1],
                    items[i + 1][0], items[i + 1][1],
                    jr.fold_in(key, node),
                )
                nxt.append((s, c))
            if len(items) % 2:
                nxt.append(items[-1])
            items = nxt
        samples, count = items[0]
    total = int(np.asarray(count)[0])
    return np.asarray(samples)[0, : min(total, k)], total


_MERGE_AXIS = "part"


@functools.lru_cache(maxsize=None)
def _device_tree_merger(
    n_parts: int, d: int, mode: str, n_leaves: int, use_pallas: bool
):
    """Jitted collective tree merge over ``n_parts`` stacked part rows on a
    ``d``-device 1-D mesh.

    Inputs are the stacked per-part leaves ``[Ppad, ...]`` (``Ppad`` a
    multiple of ``d``; rows past ``n_parts`` are layout padding), sharded
    ``P(part)`` on the leading axis — each device holds a contiguous block
    of parts.  Inside ``shard_map`` the blocks are exchanged — a Pallas
    ``make_async_remote_copy`` ring (:mod:`reservoir_tpu.ops.merge_pallas`)
    or an XLA ``all_gather`` — and every device then runs the SAME
    deterministic node-numbered log-depth tree over the first ``n_parts``
    parts (a static Python loop, unrolled at trace time), so the output is
    replicated by construction and bit-identical to
    :func:`merge_samples_host` (same pairwise math, same tree order).
    Memoized per ``(n_parts, d, mode, impl)``; shapes/dtypes are jit's own
    cache axes.
    """
    mesh = Mesh(np.asarray(jax.devices()[:d]), (_MERGE_AXIS,))

    def local(*args):
        if mode == "uniform":
            leaves, key = args[:-1], args[-1]
        else:
            leaves = args
        if use_pallas:
            from ..ops import merge_pallas as _mp

            gathered = _mp.gather_parts(
                leaves, axis=_MERGE_AXIS, axis_size=d
            )
        else:
            gathered = [
                jnp.reshape(
                    jax.lax.all_gather(leaf, _MERGE_AXIS),
                    (-1,) + leaf.shape[1:],
                )
                for leaf in leaves
            ]
        items = [
            tuple(g[p][None] for g in gathered) for p in range(n_parts)
        ]
        if mode == "uniform":
            node = 0
            while len(items) > 1:
                nxt = []
                for i in range(0, len(items) - 1, 2):
                    node += 1
                    s, c = _algl.merge_samples(
                        items[i][0], items[i][1],
                        items[i + 1][0], items[i + 1][1],
                        jr.fold_in(key, node),
                    )
                    nxt.append((s, c))
                if len(items) % 2:
                    nxt.append(items[-1])
                items = nxt
        else:
            pairwise = _pairwise_fn(mode)
            while len(items) > 1:
                nxt = [
                    pairwise(items[i], items[i + 1])
                    for i in range(0, len(items) - 1, 2)
                ]
                if len(items) % 2:
                    nxt.append(items[-1])
                items = nxt
        return items[0]

    in_specs = (P(_MERGE_AXIS),) * n_leaves
    if mode == "uniform":
        in_specs = in_specs + (P(),)  # the merge key is replicated
    return jax.jit(
        _shard_map(
            local,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(P(),) * n_leaves,
            check_vma=False,
        )
    )


def _resolve_merge_impl(impl: str, n_parts: int, four_byte: bool) -> str:
    """auto|pallas|xla|host -> the path actually taken, with graceful
    demotion (Pallas needs a TPU backend, >= 2 devices, and 4-byte leaves;
    any collective needs a live backend)."""
    if impl not in ("auto", "pallas", "xla", "host"):
        raise ValueError(
            f"impl must be one of 'auto'|'pallas'|'xla'|'host', got {impl!r}"
        )
    if impl == "host" or n_parts == 1:
        return "host"
    try:
        n_dev = len(jax.devices())
        backend = jax.default_backend()
    except Exception as e:  # backend init failure: the host path needs none
        warn_once(
            _flags, "_backend_down_logged",
            "merge_samples_device: device backend unreachable (%s); "
            "demoting to the host merge path (logged once)", e,
            logger=__name__,
        )
        return "host"
    d = min(n_dev, n_parts)
    pallas_ok = backend == "tpu" and d >= 2 and four_byte
    if impl == "auto":
        if pallas_ok:
            return "pallas"
        return "xla" if d >= 2 else "host"
    if impl == "pallas" and not pallas_ok:
        warn_once(
            _flags, "_pallas_demoted_logged",
            "merge_samples_device: impl='pallas' unavailable (backend=%s, "
            "devices=%d, 4-byte leaves=%s); demoting to the XLA-collective "
            "path (logged once)", backend, d, four_byte,
            logger=__name__,
        )
        return "xla" if d >= 2 else "host"
    return impl


def _merge_leaf_dtypes_4byte(leaves) -> bool:
    return all(np.dtype(leaf.dtype).itemsize == 4 for leaf in leaves)


def merge_samples_device(
    parts,
    key=None,
    *,
    max_sample_size: int,
    mode: str = "uniform",
    impl: str = "auto",
):
    """Device-side collective counterpart of :func:`merge_samples_host`:
    the same deterministic node-numbered log-depth merge tree, but part
    state moves between devices over the interconnect — a Pallas
    ``make_async_remote_copy`` ring permute
    (:mod:`reservoir_tpu.ops.merge_pallas`) on TPU, an XLA ``all_gather``
    collective otherwise — and every pairwise merge runs on-chip.
    Bit-reconcilable with the host path by construction: identical lifted
    inputs, identical pairwise math (:func:`~reservoir_tpu.ops.algorithm_l.merge_samples`
    / :func:`~reservoir_tpu.ops.weighted.merge_parts` /
    :func:`~reservoir_tpu.ops.distinct.merge`), identical
    ``fold_in(key, node)`` tree numbering (pinned by
    ``tests/test_merge_device.py``).

    Args:
      parts: per-mode part tuples —

        - ``mode="uniform"``: ``(sample, count)`` pairs exactly as
          :func:`merge_samples_host` takes (1-D samples already truncated
          to their fill, total stream counts);
        - ``mode="weighted"``: ``(samples [k], lkeys [k], count)`` rows of
          a :class:`~reservoir_tpu.ops.weighted.WeightedState` (full
          ``k``-wide slot rows, empty slots at ``-inf`` lkeys);
        - ``mode="distinct"``: ``(values [k], hash_hi [k], hash_lo [k],
          size, count, salts [4])`` rows of a narrow
          :class:`~reservoir_tpu.ops.distinct.DistinctState`; all parts
          must share salts (shards of one logical stream).
      key: PRNG key or int seed for the uniform merge draws (ignored by
        the state-keyed weighted/distinct merges).
      max_sample_size: the configs' ``k``.
      mode: ``"uniform"`` | ``"weighted"`` | ``"distinct"``.
      impl: ``"auto"`` (Pallas on TPU, else XLA collectives, else host),
        ``"pallas"``/``"xla"`` to force a path (Pallas demotes gracefully
        when unavailable), ``"host"`` for the host tree.

    Returns per mode: uniform ``(merged_sample, total)`` exactly like the
    host path; weighted ``(samples [k], lkeys [k], total)``; distinct
    ``(values [k], hash_hi [k], hash_lo [k], size, total)`` — all host
    ``np.ndarray``/int.
    """
    if mode not in _MODES:
        raise ValueError(f"mode must be one of {_MODES}, got {mode!r}")
    parts = list(parts)
    if not parts:
        raise ValueError("merge_samples_device needs at least one part")
    k = int(max_sample_size)
    if mode == "uniform":
        if isinstance(key, int):
            key = jr.key(key)
        elif key is None:
            raise ValueError("uniform mode requires a merge key")
        dtype = np.asarray(parts[0][0]).dtype
        rows = np.zeros((len(parts), k), dtype)
        counts = np.zeros((len(parts),), np.uint32)
        for p, (sample, count) in enumerate(parts):
            s = np.atleast_1d(np.asarray(sample, dtype))[:k]
            rows[p, : s.shape[0]] = s
            counts[p] = int(count)
        leaves = (rows, counts)
    elif mode == "weighted":
        leaves = _stack_state_rows(parts, k, 3, mode)
    else:
        leaves = _stack_state_rows(parts, k, 6, mode)
    impl_taken = _resolve_merge_impl(
        impl, len(parts), _merge_leaf_dtypes_4byte(leaves)
    )
    if impl_taken == "host":
        return _merge_tree_host(parts, leaves, key, k, mode)
    d = min(len(jax.devices()), len(parts))
    with trace_span(f"reservoir_merge_device_{impl_taken}"):
        out = _run_device_merge(leaves, key, mode, impl_taken, d)
    if mode == "uniform":
        s, c = out
        total = int(np.asarray(c)[0])
        return np.asarray(s)[0, : min(total, k)], total
    if mode == "weighted":
        s, lk, c = out
        return np.asarray(s)[0], np.asarray(lk)[0], int(np.asarray(c)[0])
    v, hi, lo, size, c, _salts = out
    return (
        np.asarray(v)[0],
        np.asarray(hi)[0],
        np.asarray(lo)[0],
        int(np.asarray(size)[0]),
        int(np.asarray(c)[0]),
    )


def _stack_state_rows(parts, k: int, n_leaves: int, mode: str):
    """Stack per-part state-row tuples into ``[P, ...]`` leaf arrays."""
    cols = [[] for _ in range(n_leaves)]
    for p, part in enumerate(parts):
        if len(part) != n_leaves:
            raise ValueError(
                f"{mode} parts take {n_leaves}-tuples, got "
                f"{len(part)} fields in part {p}"
            )
        for i, field in enumerate(part):
            arr = np.asarray(field)
            if arr.ndim == 1 and arr.shape[0] not in (k, 4):
                raise ValueError(
                    f"part {p} field {i} must be [{k}]-wide state rows, "
                    f"got shape {arr.shape}"
                )
            cols[i].append(arr)
    return tuple(np.stack(col) for col in cols)


def _merge_tree_host(parts, leaves, key, k: int, mode: str):
    """Host demotion target: the same tree over the same lifted rows, one
    jitted pairwise dispatch per node (:func:`_host_pairwise`)."""
    if mode == "uniform":
        return merge_samples_host(parts, key, max_sample_size=k)
    pairwise = _host_pairwise(mode)
    with trace_span("reservoir_merge_host"):
        items = [
            tuple(jnp.asarray(leaf[p][None]) for leaf in leaves)
            for p in range(len(parts))
        ]
        while len(items) > 1:
            nxt = [
                tuple(pairwise(items[i], items[i + 1]))
                for i in range(0, len(items) - 1, 2)
            ]
            if len(items) % 2:
                nxt.append(items[-1])
            items = nxt
    out = items[0]
    if mode == "weighted":
        s, lk, c = out
        return np.asarray(s)[0], np.asarray(lk)[0], int(np.asarray(c)[0])
    v, hi, lo, size, c, _salts = out
    return (
        np.asarray(v)[0],
        np.asarray(hi)[0],
        np.asarray(lo)[0],
        int(np.asarray(size)[0]),
        int(np.asarray(c)[0]),
    )


def _run_device_merge(leaves, key, mode: str, impl: str, d: int):
    """Pad part rows to the mesh, dispatch the memoized merger, and demote
    Pallas -> XLA on a runtime kernel failure (same graceful-degradation
    contract as the engine)."""
    n_parts = leaves[0].shape[0]
    block = -(-n_parts // d)  # parts per device
    if impl == "pallas":
        block = -(-block // 8) * 8  # sublane-friendly DMA blocks
    ppad = block * d
    if ppad != n_parts:
        leaves = tuple(
            np.pad(leaf, ((0, ppad - n_parts),) + ((0, 0),) * (leaf.ndim - 1))
            for leaf in leaves
        )
    args = leaves + ((key,) if mode == "uniform" else ())
    fn = _device_tree_merger(n_parts, d, mode, len(leaves), impl == "pallas")
    if impl != "pallas":
        return fn(*args)
    try:
        return fn(*args)
    except Exception as e:
        warn_once(
            _flags, "_pallas_runtime_demoted_logged",
            "Pallas collective merge failed (%s: %s); demoting to the "
            "XLA-collective path (logged once)", type(e).__name__, e,
            logger=__name__,
        )
        fn = _device_tree_merger(n_parts, d, mode, len(leaves), False)
        return fn(*args)


@functools.lru_cache(maxsize=None)
def uniform_stream_merger(mesh: Mesh, axis: str = "stream"):
    """Jitted ``fn(samples [D, R, k], count [D, R], key) -> (samples [R, k],
    count [R])`` merging per-device Algorithm-L results into one logical
    sample, replicated on every device.

    Memoized per ``(mesh, axis)``: each call used to build a fresh
    ``jax.jit`` wrapper, so repeated construction over the same mesh
    re-traced and re-compiled the whole tree — the jit cache is keyed on
    the wrapper identity, not the HLO.

    Inputs are the stacked per-shard results, sharded ``P(axis)`` on the
    leading device axis; the combine happens after an ``all_gather`` over
    ``axis`` and is identical on every device (same key), so the output is
    replicated by construction.  The combine is a log-depth TREE of
    pairwise merges (depth ``ceil(log2 D)``), not a sequential fold —
    D is static, so the tree unrolls at trace time and XLA runs each
    level's merges in parallel.
    """
    D = mesh.shape[axis]

    def local(samples, count, key):
        # inside shard_map: samples [1, R, k], count [1, R]; key replicated
        g_s = jax.lax.all_gather(samples[0], axis)  # [D, R, k]
        g_c = jax.lax.all_gather(count[0], axis)  # [D, R]

        items = [(g_s[d], g_c[d]) for d in range(D)]
        node = 0
        while len(items) > 1:
            nxt = []
            for i in range(0, len(items) - 1, 2):
                node += 1
                s, c = _algl.merge_samples(
                    items[i][0], items[i][1],
                    items[i + 1][0], items[i + 1][1],
                    jr.fold_in(key, node),
                )
                nxt.append((s, c))
            if len(items) % 2:
                nxt.append(items[-1])
            items = nxt
        return items[0]

    return jax.jit(
        _shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P()),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def _summary_merger(mesh: Mesh, axis: str, pairwise, n_leaves: int):
    """Shared all_gather + log-depth tree combine for key/hash-based merges
    (no RNG).  Depth ``ceil(log2 D)`` pairwise merges, unrolled at trace
    time; each level's merges are independent, so XLA schedules them in
    parallel."""
    D = mesh.shape[axis]

    def local(*leaves):
        gathered = [jax.lax.all_gather(leaf[0], axis) for leaf in leaves]

        items = [tuple(g[d] for g in gathered) for d in range(D)]
        while len(items) > 1:
            nxt = [
                pairwise(items[i], items[i + 1])
                for i in range(0, len(items) - 1, 2)
            ]
            if len(items) % 2:
                nxt.append(items[-1])
            items = nxt
        return items[0]

    return jax.jit(
        _shard_map(
            local,
            mesh=mesh,
            in_specs=tuple(P(axis) for _ in range(n_leaves)),
            out_specs=tuple(P() for _ in range(n_leaves)),
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=None)
def distinct_stream_merger(mesh: Mesh, axis: str = "stream"):
    """Jitted merger for stacked per-device ``DistinctState`` leaves
    ``(values, hash_hi, hash_lo, size, count)`` (salts shared across shards,
    passed separately): returns the replicated merged leaves.  Memoized
    per ``(mesh, axis)`` like :func:`uniform_stream_merger`."""

    def pairwise(a, b):
        va, hia, loa, sza, ca, salts = a
        vb, hib, lob, szb, cb, _ = b
        sa = _distinct.DistinctState(va, hia, loa, sza, ca, salts)
        sb = _distinct.DistinctState(vb, hib, lob, szb, cb, salts)
        m = _distinct.merge(sa, sb)
        return (m.values, m.hash_hi, m.hash_lo, m.size, m.count, salts)

    return _summary_merger(mesh, axis, pairwise, n_leaves=6)


@functools.lru_cache(maxsize=None)
def weighted_stream_merger(mesh: Mesh, axis: str = "stream"):
    """Jitted merger for stacked per-device weighted results
    ``(samples, lkeys, count)``: top-k-of-union, replicated.  Memoized
    per ``(mesh, axis)`` like :func:`uniform_stream_merger`."""

    def pairwise(a, b):
        return _weighted.merge_parts(*a, *b)

    return _summary_merger(mesh, axis, pairwise, n_leaves=3)
