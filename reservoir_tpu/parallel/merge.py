"""Stream-axis parallelism: one logical stream sharded across chips.

The reference handles its "long" dimension — the unbounded stream — in O(k)
memory on one thread (``Sampler.scala:11-12``); the TPU framework adds the
axis the reference cannot: split a logical stream across D devices, sample
each shard independently (zero communication in the hot loop), and combine
with an exact merge that rides ICI collectives (SURVEY §5 long-context row).

Mechanism per mode:

- uniform (Algorithm L): hypergeometric pairwise merge
  (:func:`reservoir_tpu.ops.algorithm_l.merge_samples`), combined across
  the device axis by a log-depth tree after an ``all_gather``;
- distinct: bottom-k union (shared salts across shards);
- weighted: top-k union of ES keys.

The collective is one ``all_gather`` of O(R·k) summary state per result —
amortized over arbitrarily long shard streams; the hot sampling loop stays
collective-free.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import jax.random as jr
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharded import shard_map as _shard_map
from ..ops import algorithm_l as _algl
from ..ops import distinct as _distinct
from ..ops import weighted as _weighted
from ..utils.tracing import trace_span

__all__ = [
    "uniform_stream_merger",
    "distinct_stream_merger",
    "weighted_stream_merger",
    "merge_samples_host",
]

_HOST_PAIRWISE = None  # lazily jitted merge_samples (host tree merges)


def merge_samples_host(
    parts: Sequence[Tuple[np.ndarray, int]],
    key,
    *,
    max_sample_size: int,
) -> Tuple[np.ndarray, int]:
    """Host-side log-depth tree merge of per-shard uniform samples.

    The sharded serving plane (ISSUE 9) routes whole sessions to shards,
    so a cross-shard "one logical sample" query — N sessions, possibly on
    N different shards, read as a single uniform sample of their combined
    streams — merges *host* snapshot arrays, not meshed device state.
    This is the same exact hypergeometric pairwise merge the mesh mergers
    ride (:func:`reservoir_tpu.ops.algorithm_l.merge_samples`, one
    reservoir row per part), combined by the same deterministic log-depth
    node-numbered tree as :func:`uniform_stream_merger` — so for a fixed
    ``key`` and part order the result is bit-reproducible, and a
    single-shard oracle that merges its per-session oracle replays with
    this very function reconciles bit-for-bit (pinned by
    ``tests/test_cluster.py``).

    Args:
      parts: ``(sample, count)`` pairs — each sample a 1-D array already
        truncated to its fill (``ReservoirService.snapshot`` output), each
        count that session's total stream length.
      key: PRNG key or int seed for the merge draws.
      max_sample_size: the configs' ``k`` (merged size is
        ``min(sum(counts), k)``).

    Returns ``(merged_sample, total_count)`` with the merged sample
    truncated to its size.  Uniform (plain) mode only: weighted/distinct
    merges are state-keyed (ES keys / hash planes) and ride the mesh
    mergers below.
    """
    if not parts:
        raise ValueError("merge_samples_host needs at least one part")
    k = int(max_sample_size)
    if isinstance(key, int):
        key = jr.key(key)
    dtype = np.asarray(parts[0][0]).dtype
    global _HOST_PAIRWISE
    if _HOST_PAIRWISE is None:
        # one jitted pairwise merge, shape/dtype-cached by jit itself:
        # the eager k-step scan costs ~100x per pair on the host path
        _HOST_PAIRWISE = jax.jit(_algl.merge_samples)

    def _lift(sample, count):
        arr = np.zeros((1, k), dtype)
        s = np.atleast_1d(np.asarray(sample, dtype))[:k]
        arr[0, : s.shape[0]] = s
        return jnp.asarray(arr), jnp.asarray([int(count)], jnp.uint32)

    with trace_span("reservoir_merge_host"):
        items = [_lift(s, c) for s, c in parts]
        node = 0
        while len(items) > 1:
            nxt = []
            for i in range(0, len(items) - 1, 2):
                node += 1
                s, c = _HOST_PAIRWISE(
                    items[i][0], items[i][1],
                    items[i + 1][0], items[i + 1][1],
                    jr.fold_in(key, node),
                )
                nxt.append((s, c))
            if len(items) % 2:
                nxt.append(items[-1])
            items = nxt
        samples, count = items[0]
    total = int(np.asarray(count)[0])
    return np.asarray(samples)[0, : min(total, k)], total


@functools.lru_cache(maxsize=None)
def uniform_stream_merger(mesh: Mesh, axis: str = "stream"):
    """Jitted ``fn(samples [D, R, k], count [D, R], key) -> (samples [R, k],
    count [R])`` merging per-device Algorithm-L results into one logical
    sample, replicated on every device.

    Memoized per ``(mesh, axis)``: each call used to build a fresh
    ``jax.jit`` wrapper, so repeated construction over the same mesh
    re-traced and re-compiled the whole tree — the jit cache is keyed on
    the wrapper identity, not the HLO.

    Inputs are the stacked per-shard results, sharded ``P(axis)`` on the
    leading device axis; the combine happens after an ``all_gather`` over
    ``axis`` and is identical on every device (same key), so the output is
    replicated by construction.  The combine is a log-depth TREE of
    pairwise merges (depth ``ceil(log2 D)``), not a sequential fold —
    D is static, so the tree unrolls at trace time and XLA runs each
    level's merges in parallel.
    """
    D = mesh.shape[axis]

    def local(samples, count, key):
        # inside shard_map: samples [1, R, k], count [1, R]; key replicated
        g_s = jax.lax.all_gather(samples[0], axis)  # [D, R, k]
        g_c = jax.lax.all_gather(count[0], axis)  # [D, R]

        items = [(g_s[d], g_c[d]) for d in range(D)]
        node = 0
        while len(items) > 1:
            nxt = []
            for i in range(0, len(items) - 1, 2):
                node += 1
                s, c = _algl.merge_samples(
                    items[i][0], items[i][1],
                    items[i + 1][0], items[i + 1][1],
                    jr.fold_in(key, node),
                )
                nxt.append((s, c))
            if len(items) % 2:
                nxt.append(items[-1])
            items = nxt
        return items[0]

    return jax.jit(
        _shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P()),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def _summary_merger(mesh: Mesh, axis: str, pairwise, n_leaves: int):
    """Shared all_gather + log-depth tree combine for key/hash-based merges
    (no RNG).  Depth ``ceil(log2 D)`` pairwise merges, unrolled at trace
    time; each level's merges are independent, so XLA schedules them in
    parallel."""
    D = mesh.shape[axis]

    def local(*leaves):
        gathered = [jax.lax.all_gather(leaf[0], axis) for leaf in leaves]

        items = [tuple(g[d] for g in gathered) for d in range(D)]
        while len(items) > 1:
            nxt = [
                pairwise(items[i], items[i + 1])
                for i in range(0, len(items) - 1, 2)
            ]
            if len(items) % 2:
                nxt.append(items[-1])
            items = nxt
        return items[0]

    return jax.jit(
        _shard_map(
            local,
            mesh=mesh,
            in_specs=tuple(P(axis) for _ in range(n_leaves)),
            out_specs=tuple(P() for _ in range(n_leaves)),
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=None)
def distinct_stream_merger(mesh: Mesh, axis: str = "stream"):
    """Jitted merger for stacked per-device ``DistinctState`` leaves
    ``(values, hash_hi, hash_lo, size, count)`` (salts shared across shards,
    passed separately): returns the replicated merged leaves.  Memoized
    per ``(mesh, axis)`` like :func:`uniform_stream_merger`."""

    def pairwise(a, b):
        va, hia, loa, sza, ca, salts = a
        vb, hib, lob, szb, cb, _ = b
        sa = _distinct.DistinctState(va, hia, loa, sza, ca, salts)
        sb = _distinct.DistinctState(vb, hib, lob, szb, cb, salts)
        m = _distinct.merge(sa, sb)
        return (m.values, m.hash_hi, m.hash_lo, m.size, m.count, salts)

    return _summary_merger(mesh, axis, pairwise, n_leaves=6)


@functools.lru_cache(maxsize=None)
def weighted_stream_merger(mesh: Mesh, axis: str = "stream"):
    """Jitted merger for stacked per-device weighted results
    ``(samples, lkeys, count)``: top-k-of-union, replicated.  Memoized
    per ``(mesh, axis)`` like :func:`uniform_stream_merger`."""

    def pairwise(a, b):
        return _weighted.merge_parts(*a, *b)

    return _summary_merger(mesh, axis, pairwise, n_leaves=3)
