"""reservoir-tpu: a TPU-native reservoir-sampling framework.

A from-scratch rebuild of the capabilities of NthPortal/reservoir
(single-pass uniform sampling via Algorithm L, distinct-value sampling via
salted bottom-k hashing, single-use/reusable lifecycles, and a pass-through
stream operator materializing the final sample) designed for JAX/XLA/Pallas:
reservoir state is a pure pytree, the hot path is a vmapped batched kernel
over tens of thousands of independent reservoirs, RNG is counter-based
(reproducible by construction), and multi-chip scale goes through
``jax.sharding`` meshes.

Layers (bottom-up; compare SURVEY.md §1):

- :mod:`reservoir_tpu.oracle`   — CPU semantic oracles (the reference behavior)
- :mod:`reservoir_tpu.ops`      — device kernels (jit/vmap + Pallas)
- :mod:`reservoir_tpu.api`      — Sampler API with the reference's lifecycle
- :mod:`reservoir_tpu.parallel` — mesh sharding, collectives, reservoir merge
- :mod:`reservoir_tpu.stream`   — pass-through stream operator + host bridge
- :mod:`reservoir_tpu.utils`    — checkpoint, metrics, tracing
"""

from .config import (
    DEFAULT_INITIAL_SIZE,
    MAX_SIZE,
    SamplerConfig,
)
from .errors import (
    AbruptStreamTermination,
    CheckpointCorrupt,
    CheckpointMismatch,
    FencedError,
    FlushTimeout,
    RetryPolicy,
    SamplerClosedError,
    ServiceSaturated,
    SessionIngestError,
    StaleSessionError,
    StreamCancelled,
    TransientDeviceError,
    UnknownSessionError,
)

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy: importing reservoir_tpu must not pull in jax (the oracle/API layer
    # is numpy-only; keeps CPU-only consumers and import time light).
    if name in ("sampler", "distinct", "Sampler"):
        from . import api

        return getattr(api, name)
    if name == "ReservoirEngine":
        from .engine import ReservoirEngine

        return ReservoirEngine
    if name in ("Sample", "DeviceStreamBridge", "DeviceSampler"):
        from . import stream

        return getattr(stream, name)
    if name in (
        "ReservoirService",
        "SessionTable",
        "Session",
        "StandbyReplica",
        "JournalFollower",
        "FailoverController",
        "HeartbeatWriter",
    ):
        from . import serve

        return getattr(serve, name)
    raise AttributeError(f"module 'reservoir_tpu' has no attribute {name!r}")


__all__ = [
    "MAX_SIZE",
    "DEFAULT_INITIAL_SIZE",
    "SamplerConfig",
    "SamplerClosedError",
    "AbruptStreamTermination",
    "StreamCancelled",
    "TransientDeviceError",
    "FlushTimeout",
    "CheckpointCorrupt",
    "CheckpointMismatch",
    "FencedError",
    "RetryPolicy",
    "UnknownSessionError",
    "StaleSessionError",
    "SessionIngestError",
    "ServiceSaturated",
    "Sampler",
    "sampler",
    "distinct",
    "ReservoirEngine",
    "Sample",
    "DeviceStreamBridge",
    "DeviceSampler",
    "ReservoirService",
    "SessionTable",
    "Session",
    "StandbyReplica",
    "JournalFollower",
    "FailoverController",
    "HeartbeatWriter",
    "__version__",
]
