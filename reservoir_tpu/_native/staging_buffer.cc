// Host-side staging buffer for the device stream bridge.
//
// The reference's stream stage touches one element per actor callback
// (SampleImpl.scala:27-31, single-threaded per stage); feeding a TPU takes
// tile-granular flushes instead, and the expensive host-side step is the
// *demux*: an interleaved feed of (stream_id, element) pairs must be
// scattered into per-stream rows of the [S, B] staging tile.  In Python
// that is an interpreter-speed loop; here it is a tight pointer walk.
//
// Concurrency contract: one staging buffer is single-producer/
// single-consumer — push_* and drain may run on different threads (ctypes
// releases the GIL during calls), guarded by a mutex.  Multiple producers
// need their own serialization, matching the sampler thread-safety contract
// of the reference (Sampler.scala:19).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).

#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

namespace {

struct StagingBuffer {
  int32_t num_streams;
  int32_t tile_width;
  int32_t elem_size;   // bytes per element
  int32_t value_arrays;  // 1 (elements only) or 2 (elements + weights)
  // backing store per value array: internally owned by default, or
  // caller-owned after rsv_staging_attach (the zero-copy flush mode — the
  // demux scatters straight into the flush tile, so "drain" degenerates
  // to reading the fill counts)
  uint8_t* base[2];
  uint8_t* owned;      // the internal allocation (kept for destroy)
  int32_t* fill;       // [S]
  int32_t* scratch;    // [S] fill simulation for the parallel pre-pass
  std::mutex mu;

  uint8_t* row(int arr, int32_t s) {
    return base[arr] +
           static_cast<size_t>(s) * tile_width * elem_size;
  }
};

// ----------------------------------------------------------------- demux pool
//
// The single-threaded interleaved scatter tops out around ~9e7 pairs/s
// (DRAM-latency-bound dependent random accesses into a tile that at
// config-5 scale is a ~100 MB working set) — a hard ceiling below the
// 1e9 north star for a per-element feed.  The scatter parallelizes by
// STREAM-ROW RANGE: the tile and the fill array are row-partitioned, so
// T workers each scanning the whole pair batch but scattering only their
// own contiguous row range touch disjoint memory — no locks, no atomics,
// per-stream arrival order preserved (each worker walks pairs in index
// order).  Contiguous ranges (not s % T) keep workers' fill[] entries on
// disjoint cache lines.  The shared scan of the pair array is a cheap
// sequential read; the expensive random writes split T ways.
//
// Worker count: RESERVOIR_STAGING_THREADS (default: hardware_concurrency,
// capped at 16; <=1 disables).  The pool is process-lifetime (detached
// threads, leaked singleton — destroying a condvar with waiters at exit
// is UB).  A forked child (no inherited threads) is detected by pid and
// served by the calling thread running every range itself — same result,
// just serial.
// The worker count the pool WOULD use — readable without constructing
// the pool, so small-batch-only processes never spawn idle threads.
int planned_workers() {
  static const int n = [] {
    const char* env = std::getenv("RESERVOIR_STAGING_THREADS");
    int v;
    if (env) {
      v = std::atoi(env);
      if (v < 1) v = 1;  // explicit 0/negative = force the serial demux
    } else {
      unsigned hc = std::thread::hardware_concurrency();
      v = hc ? static_cast<int>(hc) : 1;
      if (v > 16) v = 16;
    }
    if (v > 64) v = 64;
    return v;
  }();
  return n;
}

class DemuxPool {
 public:
  static DemuxPool& instance() {
    static DemuxPool* p = new DemuxPool;  // leaked: see class comment
    return *p;
  }

  int workers() const { return nworkers_; }

  // False in a forked child (threads not inherited): callers take the
  // plain serial demux instead of run()'s all-ranges fallback, which
  // would scan the batch T times for identical output.
  bool usable() const { return nworkers_ > 1 && getpid() == owner_pid_; }

  // Run fn(t) for t in [0, workers()); blocks until all complete.  The
  // calling thread serves range 0.  Serialized across callers (one
  // task-broadcast slot) — concurrent StagingBuffers queue up here.
  void run(const std::function<void(int)>& fn) {
    if (nworkers_ <= 1 || getpid() != owner_pid_) {
      for (int t = 0; t < nworkers_; ++t) fn(t);
      return;
    }
    std::lock_guard<std::mutex> run_lock(run_mu_);
    {
      std::lock_guard<std::mutex> lk(mu_);
      fn_ = &fn;
      pending_ = nworkers_ - 1;
      ++gen_;
    }
    cv_.notify_all();
    fn(0);
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this] { return pending_ == 0; });
    fn_ = nullptr;
  }

 private:
  DemuxPool() : owner_pid_(getpid()) {
    const int n = planned_workers();
    nworkers_ = n;
    for (int t = 1; t < n; ++t) {
      std::thread([this, t] {
        uint64_t seen = 0;
        std::unique_lock<std::mutex> lk(mu_);
        for (;;) {
          cv_.wait(lk, [&] { return gen_ != seen; });
          seen = gen_;
          const std::function<void(int)>* fn = fn_;
          lk.unlock();
          (*fn)(t);
          lk.lock();
          if (--pending_ == 0) done_cv_.notify_one();
        }
      }).detach();
    }
  }

  std::mutex run_mu_;  // one broadcast at a time
  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
  const std::function<void(int)>* fn_ = nullptr;
  uint64_t gen_ = 0;
  int pending_ = 0;
  int nworkers_ = 1;
  pid_t owner_pid_;
};

// below this batch size the broadcast overhead beats the split's win
constexpr int64_t kParallelMin = 8192;

// Sequential-contract pre-pass: the index of the first pair the serial
// demux would REJECT (bad id, or its row full at processing time), i.e.
// the exact count the caller may treat as consumed.  Runs on a fill-
// simulation scratch so the real counters stay untouched; one dependent
// L2 access per pair — cheap next to the tile scatter it unblocks.
int64_t demux_prefix(StagingBuffer* sb, const int32_t* streams, int64_t n) {
  const uint32_t S = static_cast<uint32_t>(sb->num_streams);
  const int32_t width = sb->tile_width;
  if (n >= sb->num_streams) {
    // batch at least as long as the fill array: the O(S) snapshot
    // amortizes over the walk
    std::memcpy(sb->scratch, sb->fill,
                sizeof(int32_t) * static_cast<size_t>(sb->num_streams));
    for (int64_t i = 0; i < n; ++i) {
      const uint32_t s = static_cast<uint32_t>(streams[i]);
      if (s >= S) return i;
      if (sb->scratch[s] >= width) return i;
      ++sb->scratch[s];
    }
    return n;
  }
  // batch much shorter than the fill array (huge S, near-threshold n):
  // an O(S) copy would rival the scatter itself, so simulate against
  // fill[] directly and rewind by replaying the consumed prefix — the
  // caller holds sb->mu, so the transient mutation is unobservable
  int64_t stop = n;
  for (int64_t i = 0; i < n; ++i) {
    const uint32_t s = static_cast<uint32_t>(streams[i]);
    if (s >= S || sb->fill[s] >= width) {
      stop = i;
      break;
    }
    ++sb->fill[s];
  }
  for (int64_t i = 0; i < stop; ++i) --sb->fill[streams[i]];
  return stop;
}

// One worker's share of the parallel scatter: pairs [0, n) whose stream
// falls in [lo, hi).  Bounds and overflow were resolved by demux_prefix,
// so the walk is branch-light; rows outside the range are untouched —
// the disjointness that makes the split lock-free.
template <typename E>
void demux_range(StagingBuffer* sb, const int32_t* streams, const void* elems,
                 const void* weights, int64_t n, uint32_t lo, uint32_t hi) {
  const auto* esrc = static_cast<const E*>(elems);
  const auto* wsrc = static_cast<const uint32_t*>(weights);
  auto* tile = reinterpret_cast<E*>(sb->base[0]);
  auto* wtile = reinterpret_cast<uint32_t*>(sb->base[1]);
  const int32_t width = sb->tile_width;
  const uint32_t span = hi - lo;
  int32_t* fill = sb->fill;
  constexpr int64_t kPrefetch = 16;
  for (int64_t i = 0; i < n; ++i) {
    if (i + kPrefetch < n) {
      const uint32_t ps = static_cast<uint32_t>(streams[i + kPrefetch]);
      if (ps - lo < span) {
        __builtin_prefetch(&fill[ps], 1, 1);
        __builtin_prefetch(
            &tile[static_cast<size_t>(ps) * width + fill[ps]], 1, 0);
        if (wsrc) {
          __builtin_prefetch(
              &wtile[static_cast<size_t>(ps) * width + fill[ps]], 1, 0);
        }
      }
    }
    const uint32_t s = static_cast<uint32_t>(streams[i]);
    if (s - lo >= span) continue;  // another worker's row
    const int32_t f = fill[s];
    const size_t at = static_cast<size_t>(s) * width + f;
    tile[at] = esrc[i];
    if (wsrc) wtile[at] = wsrc[i];
    fill[s] = f + 1;
  }
}

// Demux inner loop, specialized on the element width: the generic
// per-pair memcpy(elem_size) cannot be inlined (runtime size) and its
// call overhead dominates the walk; typed loads/stores cut the per-pair
// cost to the unavoidable scatter.  Weights, when present, are always
// 4 bytes (the staging layer enforces 4-byte elements for weighted mode).
template <typename E>
int64_t demux_typed(StagingBuffer* sb, const int32_t* streams,
                    const void* elems, const void* weights, int64_t n) {
  const auto* esrc = static_cast<const E*>(elems);
  const auto* wsrc = static_cast<const uint32_t*>(weights);
  auto* tile = reinterpret_cast<E*>(sb->base[0]);
  auto* wtile = reinterpret_cast<uint32_t*>(sb->base[1]);
  const int32_t width = sb->tile_width;
  const uint32_t S = static_cast<uint32_t>(sb->num_streams);
  int32_t* fill = sb->fill;
  // The scatter is DRAM-latency-bound at config-5 scale (the [S, B] tile
  // is a ~100 MB working set; each pair's slot is a dependent random
  // access).  Prefetch the fill counter and the approximate target slot a
  // few pairs ahead — the slot address is exact when the stream does not
  // repeat within the window, and a one-slot miss still pulls the right
  // cache line for B >= 16.
  constexpr int64_t kPrefetch = 16;
  int64_t i = 0;
  for (; i < n; ++i) {
    if (i + kPrefetch < n) {
      const uint32_t ps = static_cast<uint32_t>(streams[i + kPrefetch]);
      if (ps < S) {
        __builtin_prefetch(&fill[ps], 1, 1);
        __builtin_prefetch(
            &tile[static_cast<size_t>(ps) * width + fill[ps]], 1, 0);
        if (wsrc) {
          __builtin_prefetch(
              &wtile[static_cast<size_t>(ps) * width + fill[ps]], 1, 0);
        }
      }
    }
    const uint32_t s = static_cast<uint32_t>(streams[i]);
    if (s >= S) break;  // bad id (incl. negative): stop before it
    const int32_t f = fill[s];
    if (f >= width) break;  // row full: hand control back for a drain
    const size_t at = static_cast<size_t>(s) * width + f;
    tile[at] = esrc[i];
    if (wsrc) wtile[at] = wsrc[i];
    fill[s] = f + 1;
  }
  return i;
}

}  // namespace

extern "C" {

// Create a buffer for S streams x B elements of elem_size bytes each.
// value_arrays=2 keeps a parallel tile (e.g. weights) routed identically.
void* rsv_staging_create(int32_t num_streams, int32_t tile_width,
                         int32_t elem_size, int32_t value_arrays) {
  if (num_streams <= 0 || tile_width <= 0 || elem_size <= 0 ||
      value_arrays < 1 || value_arrays > 2) {
    return nullptr;
  }
  auto* sb = new (std::nothrow) StagingBuffer;
  if (!sb) return nullptr;
  sb->num_streams = num_streams;
  sb->tile_width = tile_width;
  sb->elem_size = elem_size;
  sb->value_arrays = value_arrays;
  size_t plane = static_cast<size_t>(num_streams) * tile_width * elem_size;
  size_t bytes = static_cast<size_t>(value_arrays) * plane;
  // value-initialized: drained rows include never-written slots (whole-row
  // memcpy), and downstream float consumers must never see heap garbage
  // (NaN weight bits would defeat the bridge's positivity clamp)
  sb->owned = new (std::nothrow) uint8_t[bytes]();
  sb->fill = new (std::nothrow) int32_t[num_streams]();
  sb->scratch = new (std::nothrow) int32_t[num_streams]();
  if (!sb->owned || !sb->fill || !sb->scratch) {
    delete[] sb->owned;
    delete[] sb->fill;
    delete[] sb->scratch;
    delete sb;
    return nullptr;
  }
  sb->base[0] = sb->owned;
  sb->base[1] = value_arrays == 2 ? sb->owned + plane : nullptr;
  return sb;
}

void rsv_staging_destroy(void* handle) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb) return;
  delete[] sb->owned;
  delete[] sb->fill;
  delete[] sb->scratch;
  delete sb;
}

// Zero-copy flush mode: scatter future pushes straight into caller-owned
// tile storage ([S][B][elem_size]; weights iff value_arrays == 2).  The
// caller guarantees the buffers outlive the attachment and are not read
// concurrently with pushes (the bridge's single-producer contract).
// Passing null tile re-attaches the internal buffer.
int32_t rsv_staging_attach(void* handle, void* tile, void* weights) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb) return -1;
  std::lock_guard<std::mutex> lock(sb->mu);
  if (!tile) {
    size_t plane =
        static_cast<size_t>(sb->num_streams) * sb->tile_width * sb->elem_size;
    sb->base[0] = sb->owned;
    sb->base[1] = sb->value_arrays == 2 ? sb->owned + plane : nullptr;
    return 0;
  }
  if ((sb->value_arrays == 2) != (weights != nullptr)) return -1;
  sb->base[0] = static_cast<uint8_t*>(tile);
  sb->base[1] = static_cast<uint8_t*>(weights);
  return 0;
}

// The zero-copy "drain": hand back the per-row fill counts and reset them.
// Tile data needs no copy — it is already in the attached buffer.  Returns
// the total staged element count.
int64_t rsv_staging_take(void* handle, int32_t* out_valid) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb || !out_valid) return -1;
  std::lock_guard<std::mutex> lock(sb->mu);
  int64_t total = 0;
  for (int32_t s = 0; s < sb->num_streams; ++s) {
    out_valid[s] = sb->fill[s];
    total += sb->fill[s];
    sb->fill[s] = 0;
  }
  return total;
}

// Append a contiguous chunk to one stream's row.  Returns the number of
// elements consumed (< n iff the row filled mid-chunk; caller drains and
// retries from the returned offset).
int64_t rsv_staging_push_chunk(void* handle, int32_t stream,
                               const void* elems, const void* weights,
                               int64_t n) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb || stream < 0 || stream >= sb->num_streams || n < 0) return -1;
  if ((sb->value_arrays == 2) != (weights != nullptr)) return -1;
  std::lock_guard<std::mutex> lock(sb->mu);
  int32_t fill = sb->fill[stream];
  int64_t take = sb->tile_width - fill;
  if (take > n) take = n;
  if (take > 0) {
    std::memcpy(sb->row(0, stream) + static_cast<size_t>(fill) * sb->elem_size,
                elems, static_cast<size_t>(take) * sb->elem_size);
    if (weights) {
      std::memcpy(
          sb->row(1, stream) + static_cast<size_t>(fill) * sb->elem_size,
          weights, static_cast<size_t>(take) * sb->elem_size);
    }
    sb->fill[stream] = fill + static_cast<int32_t>(take);
  }
  return take;
}

// Demux interleaved (stream_id, element[, weight]) pairs into the staging
// rows — the hot call.  Returns pairs consumed; < n iff some row filled
// (caller drains, then resumes from the offset).  A bad stream id stops
// consumption at that pair and returns the count before it (callers detect
// it by checking streams[consumed] themselves; -1 signals invalid args).
int64_t rsv_staging_push_interleaved(void* handle, const int32_t* streams,
                                     const void* elems, const void* weights,
                                     int64_t n) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb || !streams || !elems || n < 0) return -1;
  if ((sb->value_arrays == 2) != (weights != nullptr)) return -1;
  std::lock_guard<std::mutex> lock(sb->mu);
  const bool typed =
      sb->elem_size == 4 || (sb->elem_size == 8 && !weights);
  // planned_workers() gates WITHOUT constructing the pool: a process
  // that only ever pushes small batches never spawns idle threads, and
  // a forked child (pool not usable) falls through to the serial demux
  // rather than run()'s T-scan fallback.
  if (typed && n >= kParallelMin && planned_workers() > 1 &&
      DemuxPool::instance().usable()) {
    // parallel scatter: resolve the sequential stop point first (the
    // serial contract — consume a prefix, stop at a full row or bad id),
    // then split the guaranteed-safe prefix across row-range workers.
    // A SMALL prefix (hot row nearly full: n pairs requested, few
    // consumable) falls through to the serial scatter — a pool
    // broadcast for a few hundred pairs costs more than it saves.
    const int64_t n_eff = demux_prefix(sb, streams, n);
    if (n_eff >= kParallelMin) {
      DemuxPool& pool = DemuxPool::instance();
      const int T = pool.workers();
      const uint64_t S = static_cast<uint64_t>(sb->num_streams);
      if (sb->elem_size == 4) {
        pool.run([&](int t) {
          demux_range<uint32_t>(sb, streams, elems, weights, n_eff,
                                static_cast<uint32_t>(S * t / T),
                                static_cast<uint32_t>(S * (t + 1) / T));
        });
      } else {
        pool.run([&](int t) {
          demux_range<uint64_t>(sb, streams, elems, weights, n_eff,
                                static_cast<uint32_t>(S * t / T),
                                static_cast<uint32_t>(S * (t + 1) / T));
        });
      }
      return n_eff;
    }
  }
  switch (sb->elem_size) {
    case 4:
      return demux_typed<uint32_t>(sb, streams, elems, weights, n);
    case 8:
      // weighted 8-byte staging keeps the generic path (its parallel
      // array is elem_size-wide by the historical layout; the Python
      // layer only builds weighted staging with 4-byte elements)
      if (!weights) return demux_typed<uint64_t>(sb, streams, elems, weights, n);
      break;
    default:
      break;
  }
  // generic fallback for exotic element widths
  const auto* esrc = static_cast<const uint8_t*>(elems);
  const auto* wsrc = static_cast<const uint8_t*>(weights);
  const int32_t esize = sb->elem_size;
  const int32_t width = sb->tile_width;
  int64_t i = 0;
  for (; i < n; ++i) {
    int32_t s = streams[i];
    if (s < 0 || s >= sb->num_streams) break;
    int32_t fill = sb->fill[s];
    if (fill >= width) break;  // row full: hand control back for a drain
    std::memcpy(sb->row(0, s) + static_cast<size_t>(fill) * esize,
                esrc + static_cast<size_t>(i) * esize, esize);
    if (wsrc) {
      std::memcpy(sb->row(1, s) + static_cast<size_t>(fill) * esize,
                  wsrc + static_cast<size_t>(i) * esize, esize);
    }
    sb->fill[s] = fill + 1;
  }
  return i;
}

// The demux worker count this process would use (env/core-count derived;
// 1 = serial).  Telemetry for the bridge's stage table — a capture on a
// multi-core host records how parallel its demux actually was.
int32_t rsv_staging_threads() { return planned_workers(); }

// Current fill of one row — O(1) flush-due check for single-stream pushes.
int32_t rsv_staging_fill(void* handle, int32_t stream) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb || stream < 0 || stream >= sb->num_streams) return -1;
  std::lock_guard<std::mutex> lock(sb->mu);
  return sb->fill[stream];
}

// True iff any row is at tile width (a flush is due).
int32_t rsv_staging_any_full(void* handle) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb) return 0;
  std::lock_guard<std::mutex> lock(sb->mu);
  for (int32_t s = 0; s < sb->num_streams; ++s) {
    if (sb->fill[s] >= sb->tile_width) return 1;
  }
  return 0;
}

// Copy the staged tile(s) + per-row fill counts out and reset the buffer.
// out_tile is [S][B][elem_size]; out_weights may be null when
// value_arrays == 1.  Returns the total staged element count.
int64_t rsv_staging_drain(void* handle, void* out_tile, void* out_weights,
                          int32_t* out_valid) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb || !out_tile || !out_valid) return -1;
  if ((sb->value_arrays == 2) != (out_weights != nullptr)) return -1;
  std::lock_guard<std::mutex> lock(sb->mu);
  size_t row_bytes = static_cast<size_t>(sb->tile_width) * sb->elem_size;
  int64_t total = 0;
  for (int32_t s = 0; s < sb->num_streams; ++s) {
    int32_t fill = sb->fill[s];
    // copy whole rows: the valid mask excludes stale bytes downstream
    std::memcpy(static_cast<uint8_t*>(out_tile) + s * row_bytes,
                sb->row(0, s), row_bytes);
    if (out_weights) {
      std::memcpy(static_cast<uint8_t*>(out_weights) + s * row_bytes,
                  sb->row(1, s), row_bytes);
    }
    out_valid[s] = fill;
    total += fill;
    sb->fill[s] = 0;
  }
  return total;
}

}  // extern "C"
