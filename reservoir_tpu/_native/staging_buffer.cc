// Host-side staging buffer for the device stream bridge.
//
// The reference's stream stage touches one element per actor callback
// (SampleImpl.scala:27-31, single-threaded per stage); feeding a TPU takes
// tile-granular flushes instead, and the expensive host-side step is the
// *demux*: an interleaved feed of (stream_id, element) pairs must be
// scattered into per-stream rows of the [S, B] staging tile.  In Python
// that is an interpreter-speed loop; here it is a tight pointer walk.
//
// Concurrency contract: one staging buffer is single-producer/
// single-consumer — push_* and drain may run on different threads (ctypes
// releases the GIL during calls), guarded by a mutex.  Multiple producers
// need their own serialization, matching the sampler thread-safety contract
// of the reference (Sampler.scala:19).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).

#include <cstdint>
#include <cstring>
#include <mutex>
#include <new>

namespace {

struct StagingBuffer {
  int32_t num_streams;
  int32_t tile_width;
  int32_t elem_size;   // bytes per element
  int32_t value_arrays;  // 1 (elements only) or 2 (elements + weights)
  // backing store per value array: internally owned by default, or
  // caller-owned after rsv_staging_attach (the zero-copy flush mode — the
  // demux scatters straight into the flush tile, so "drain" degenerates
  // to reading the fill counts)
  uint8_t* base[2];
  uint8_t* owned;      // the internal allocation (kept for destroy)
  int32_t* fill;       // [S]
  std::mutex mu;

  uint8_t* row(int arr, int32_t s) {
    return base[arr] +
           static_cast<size_t>(s) * tile_width * elem_size;
  }
};

// Demux inner loop, specialized on the element width: the generic
// per-pair memcpy(elem_size) cannot be inlined (runtime size) and its
// call overhead dominates the walk; typed loads/stores cut the per-pair
// cost to the unavoidable scatter.  Weights, when present, are always
// 4 bytes (the staging layer enforces 4-byte elements for weighted mode).
template <typename E>
int64_t demux_typed(StagingBuffer* sb, const int32_t* streams,
                    const void* elems, const void* weights, int64_t n) {
  const auto* esrc = static_cast<const E*>(elems);
  const auto* wsrc = static_cast<const uint32_t*>(weights);
  auto* tile = reinterpret_cast<E*>(sb->base[0]);
  auto* wtile = reinterpret_cast<uint32_t*>(sb->base[1]);
  const int32_t width = sb->tile_width;
  const uint32_t S = static_cast<uint32_t>(sb->num_streams);
  int32_t* fill = sb->fill;
  // The scatter is DRAM-latency-bound at config-5 scale (the [S, B] tile
  // is a ~100 MB working set; each pair's slot is a dependent random
  // access).  Prefetch the fill counter and the approximate target slot a
  // few pairs ahead — the slot address is exact when the stream does not
  // repeat within the window, and a one-slot miss still pulls the right
  // cache line for B >= 16.
  constexpr int64_t kPrefetch = 16;
  int64_t i = 0;
  for (; i < n; ++i) {
    if (i + kPrefetch < n) {
      const uint32_t ps = static_cast<uint32_t>(streams[i + kPrefetch]);
      if (ps < S) {
        __builtin_prefetch(&fill[ps], 1, 1);
        __builtin_prefetch(
            &tile[static_cast<size_t>(ps) * width + fill[ps]], 1, 0);
        if (wsrc) {
          __builtin_prefetch(
              &wtile[static_cast<size_t>(ps) * width + fill[ps]], 1, 0);
        }
      }
    }
    const uint32_t s = static_cast<uint32_t>(streams[i]);
    if (s >= S) break;  // bad id (incl. negative): stop before it
    const int32_t f = fill[s];
    if (f >= width) break;  // row full: hand control back for a drain
    const size_t at = static_cast<size_t>(s) * width + f;
    tile[at] = esrc[i];
    if (wsrc) wtile[at] = wsrc[i];
    fill[s] = f + 1;
  }
  return i;
}

}  // namespace

extern "C" {

// Create a buffer for S streams x B elements of elem_size bytes each.
// value_arrays=2 keeps a parallel tile (e.g. weights) routed identically.
void* rsv_staging_create(int32_t num_streams, int32_t tile_width,
                         int32_t elem_size, int32_t value_arrays) {
  if (num_streams <= 0 || tile_width <= 0 || elem_size <= 0 ||
      value_arrays < 1 || value_arrays > 2) {
    return nullptr;
  }
  auto* sb = new (std::nothrow) StagingBuffer;
  if (!sb) return nullptr;
  sb->num_streams = num_streams;
  sb->tile_width = tile_width;
  sb->elem_size = elem_size;
  sb->value_arrays = value_arrays;
  size_t plane = static_cast<size_t>(num_streams) * tile_width * elem_size;
  size_t bytes = static_cast<size_t>(value_arrays) * plane;
  // value-initialized: drained rows include never-written slots (whole-row
  // memcpy), and downstream float consumers must never see heap garbage
  // (NaN weight bits would defeat the bridge's positivity clamp)
  sb->owned = new (std::nothrow) uint8_t[bytes]();
  sb->fill = new (std::nothrow) int32_t[num_streams]();
  if (!sb->owned || !sb->fill) {
    delete[] sb->owned;
    delete[] sb->fill;
    delete sb;
    return nullptr;
  }
  sb->base[0] = sb->owned;
  sb->base[1] = value_arrays == 2 ? sb->owned + plane : nullptr;
  return sb;
}

void rsv_staging_destroy(void* handle) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb) return;
  delete[] sb->owned;
  delete[] sb->fill;
  delete sb;
}

// Zero-copy flush mode: scatter future pushes straight into caller-owned
// tile storage ([S][B][elem_size]; weights iff value_arrays == 2).  The
// caller guarantees the buffers outlive the attachment and are not read
// concurrently with pushes (the bridge's single-producer contract).
// Passing null tile re-attaches the internal buffer.
int32_t rsv_staging_attach(void* handle, void* tile, void* weights) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb) return -1;
  std::lock_guard<std::mutex> lock(sb->mu);
  if (!tile) {
    size_t plane =
        static_cast<size_t>(sb->num_streams) * sb->tile_width * sb->elem_size;
    sb->base[0] = sb->owned;
    sb->base[1] = sb->value_arrays == 2 ? sb->owned + plane : nullptr;
    return 0;
  }
  if ((sb->value_arrays == 2) != (weights != nullptr)) return -1;
  sb->base[0] = static_cast<uint8_t*>(tile);
  sb->base[1] = static_cast<uint8_t*>(weights);
  return 0;
}

// The zero-copy "drain": hand back the per-row fill counts and reset them.
// Tile data needs no copy — it is already in the attached buffer.  Returns
// the total staged element count.
int64_t rsv_staging_take(void* handle, int32_t* out_valid) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb || !out_valid) return -1;
  std::lock_guard<std::mutex> lock(sb->mu);
  int64_t total = 0;
  for (int32_t s = 0; s < sb->num_streams; ++s) {
    out_valid[s] = sb->fill[s];
    total += sb->fill[s];
    sb->fill[s] = 0;
  }
  return total;
}

// Append a contiguous chunk to one stream's row.  Returns the number of
// elements consumed (< n iff the row filled mid-chunk; caller drains and
// retries from the returned offset).
int64_t rsv_staging_push_chunk(void* handle, int32_t stream,
                               const void* elems, const void* weights,
                               int64_t n) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb || stream < 0 || stream >= sb->num_streams || n < 0) return -1;
  if ((sb->value_arrays == 2) != (weights != nullptr)) return -1;
  std::lock_guard<std::mutex> lock(sb->mu);
  int32_t fill = sb->fill[stream];
  int64_t take = sb->tile_width - fill;
  if (take > n) take = n;
  if (take > 0) {
    std::memcpy(sb->row(0, stream) + static_cast<size_t>(fill) * sb->elem_size,
                elems, static_cast<size_t>(take) * sb->elem_size);
    if (weights) {
      std::memcpy(
          sb->row(1, stream) + static_cast<size_t>(fill) * sb->elem_size,
          weights, static_cast<size_t>(take) * sb->elem_size);
    }
    sb->fill[stream] = fill + static_cast<int32_t>(take);
  }
  return take;
}

// Demux interleaved (stream_id, element[, weight]) pairs into the staging
// rows — the hot call.  Returns pairs consumed; < n iff some row filled
// (caller drains, then resumes from the offset).  A bad stream id stops
// consumption at that pair and returns the count before it (callers detect
// it by checking streams[consumed] themselves; -1 signals invalid args).
int64_t rsv_staging_push_interleaved(void* handle, const int32_t* streams,
                                     const void* elems, const void* weights,
                                     int64_t n) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb || !streams || !elems || n < 0) return -1;
  if ((sb->value_arrays == 2) != (weights != nullptr)) return -1;
  std::lock_guard<std::mutex> lock(sb->mu);
  switch (sb->elem_size) {
    case 4:
      return demux_typed<uint32_t>(sb, streams, elems, weights, n);
    case 8:
      // weighted 8-byte staging keeps the generic path (its parallel
      // array is elem_size-wide by the historical layout; the Python
      // layer only builds weighted staging with 4-byte elements)
      if (!weights) return demux_typed<uint64_t>(sb, streams, elems, weights, n);
      break;
    default:
      break;
  }
  // generic fallback for exotic element widths
  const auto* esrc = static_cast<const uint8_t*>(elems);
  const auto* wsrc = static_cast<const uint8_t*>(weights);
  const int32_t esize = sb->elem_size;
  const int32_t width = sb->tile_width;
  int64_t i = 0;
  for (; i < n; ++i) {
    int32_t s = streams[i];
    if (s < 0 || s >= sb->num_streams) break;
    int32_t fill = sb->fill[s];
    if (fill >= width) break;  // row full: hand control back for a drain
    std::memcpy(sb->row(0, s) + static_cast<size_t>(fill) * esize,
                esrc + static_cast<size_t>(i) * esize, esize);
    if (wsrc) {
      std::memcpy(sb->row(1, s) + static_cast<size_t>(fill) * esize,
                  wsrc + static_cast<size_t>(i) * esize, esize);
    }
    sb->fill[s] = fill + 1;
  }
  return i;
}

// Current fill of one row — O(1) flush-due check for single-stream pushes.
int32_t rsv_staging_fill(void* handle, int32_t stream) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb || stream < 0 || stream >= sb->num_streams) return -1;
  std::lock_guard<std::mutex> lock(sb->mu);
  return sb->fill[stream];
}

// True iff any row is at tile width (a flush is due).
int32_t rsv_staging_any_full(void* handle) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb) return 0;
  std::lock_guard<std::mutex> lock(sb->mu);
  for (int32_t s = 0; s < sb->num_streams; ++s) {
    if (sb->fill[s] >= sb->tile_width) return 1;
  }
  return 0;
}

// Copy the staged tile(s) + per-row fill counts out and reset the buffer.
// out_tile is [S][B][elem_size]; out_weights may be null when
// value_arrays == 1.  Returns the total staged element count.
int64_t rsv_staging_drain(void* handle, void* out_tile, void* out_weights,
                          int32_t* out_valid) {
  auto* sb = static_cast<StagingBuffer*>(handle);
  if (!sb || !out_tile || !out_valid) return -1;
  if ((sb->value_arrays == 2) != (out_weights != nullptr)) return -1;
  std::lock_guard<std::mutex> lock(sb->mu);
  size_t row_bytes = static_cast<size_t>(sb->tile_width) * sb->elem_size;
  int64_t total = 0;
  for (int32_t s = 0; s < sb->num_streams; ++s) {
    int32_t fill = sb->fill[s];
    // copy whole rows: the valid mask excludes stale bytes downstream
    std::memcpy(static_cast<uint8_t*>(out_tile) + s * row_bytes,
                sb->row(0, s), row_bytes);
    if (out_weights) {
      std::memcpy(static_cast<uint8_t*>(out_weights) + s * row_bytes,
                  sb->row(1, s), row_bytes);
    }
    out_valid[s] = fill;
    total += fill;
    sb->fill[s] = 0;
  }
  return total;
}

}  // extern "C"
