// Native bulk path for host distinct-value (bottom-k) sampling.
//
// The per-element host path pays interpreter cost for the scramble +
// threshold compare on EVERY element even though almost none are accepted
// once the reservoir is warm (the same observation the reference exploits
// in its hot loop, Sampler.scala:403-408).  Here the whole scan is a tight
// C loop: scramble (the exact Feistel/fmix32 permutation of
// ops/hashing.py::scramble64, integer-identical), one compare against the
// current threshold, and — only for the rare below-threshold candidates —
// a binary search + insert into the sorted bottom-k kept inline.
//
// Semantics match BottomKOracle per-element processing exactly, except
// ordering among *distinct values with identical 64-bit scrambled hashes*
// (probability ~2^-64 per pair; the documented shared bias source), where
// eviction tie-breaking differs.  Dedup is by (hash, value-bits), same as
// the device kernel.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).

#include <cstdint>
#include <cstring>

namespace {

inline uint32_t fmix32(uint32_t x) {
  x ^= x >> 16;
  x *= 0x85EBCA6Bu;
  x ^= x >> 13;
  x *= 0xC2B2AE35u;
  x ^= x >> 16;
  return x;
}

// ops/hashing.py::_ROUND_CONSTS
constexpr uint32_t kRound[6] = {0x9E3779B9u, 0x85EBCA6Bu, 0xC2B2AE35u,
                                0x27D4EB2Fu, 0x165667B1u, 0x9E3779B1u};

inline uint64_t scramble64(uint64_t v, uint64_t r0, uint64_t r1) {
  uint32_t hi = static_cast<uint32_t>(v >> 32) ^ static_cast<uint32_t>(r0 >> 32);
  uint32_t lo = static_cast<uint32_t>(v) ^ static_cast<uint32_t>(r0);
  for (int i = 0; i < 3; ++i) {
    uint32_t t = hi ^ fmix32(lo + kRound[i]);
    hi = lo;
    lo = t;
  }
  hi ^= static_cast<uint32_t>(r1 >> 32);
  lo ^= static_cast<uint32_t>(r1);
  for (int i = 3; i < 6; ++i) {
    uint32_t t = hi ^ fmix32(lo + kRound[i]);
    hi = lo;
    lo = t;
  }
  return (static_cast<uint64_t>(hi) << 32) | lo;
}

// First index in entry_hash[0..size) with hash >= h (lower bound).
inline int32_t lower_bound_hash(const uint64_t* entry_hash, int32_t size,
                                uint64_t h) {
  int32_t lo = 0, hi = size;
  while (lo < hi) {
    int32_t mid = lo + (hi - lo) / 2;
    if (entry_hash[mid] < h) {
      lo = mid + 1;
    } else {
      hi = mid;
    }
  }
  return lo;
}

// Is (h, v) already present?  Scan the equal-hash run from its lower bound.
inline bool contains(const uint64_t* entry_hash, const int64_t* entry_val,
                     int32_t size, int32_t pos, uint64_t h, int64_t v) {
  for (int32_t i = pos; i < size && entry_hash[i] == h; ++i) {
    if (entry_val[i] == v) return true;
  }
  return false;
}

}  // namespace

extern "C" {

// Scan n 64-bit values through the salted bottom-k.  entry_hash/entry_val
// hold the current entries sorted by hash ascending (size_io entries);
// updated in place.  Returns the number of insertions/evictions performed
// (>= 0), or -1 on invalid arguments.
int64_t rsv_bottomk_scan(const int64_t* values, int64_t n, uint64_t r0,
                         uint64_t r1, uint64_t* entry_hash,
                         int64_t* entry_val, int32_t* size_io, int32_t k) {
  if (!values || !entry_hash || !entry_val || !size_io || k <= 0 || n < 0 ||
      *size_io < 0 || *size_io > k) {
    return -1;
  }
  int32_t size = *size_io;
  uint64_t threshold =
      size == k ? entry_hash[k - 1] : ~static_cast<uint64_t>(0);
  int64_t edits = 0;
  // Block-wise two-pass structure: the scramble loop has no cross-lane
  // dependencies or branches, so the compiler vectorizes it (VPU-style);
  // the candidate pass is a predictable almost-never-taken branch.
  constexpr int64_t kBlock = 4096;
  uint64_t hbuf[kBlock];
  for (int64_t base = 0; base < n; base += kBlock) {
    const int64_t m = (n - base < kBlock) ? n - base : kBlock;
    const int64_t* vblk = values + base;
    for (int64_t j = 0; j < m; ++j) {
      hbuf[j] = scramble64(static_cast<uint64_t>(vblk[j]), r0, r1);
    }
    for (int64_t j = 0; j < m; ++j) {
      const uint64_t h = hbuf[j];
      if (h >= threshold) continue;  // the hot path: one compare
      const int64_t v = vblk[j];
      int32_t pos = lower_bound_hash(entry_hash, size, h);
      if (contains(entry_hash, entry_val, size, pos, h, v)) continue;
      if (size == k) {
        // insert at pos, evict the max (last) entry
        std::memmove(entry_hash + pos + 1, entry_hash + pos,
                     sizeof(uint64_t) * (k - pos - 1));
        std::memmove(entry_val + pos + 1, entry_val + pos,
                     sizeof(int64_t) * (k - pos - 1));
        entry_hash[pos] = h;
        entry_val[pos] = v;
        threshold = entry_hash[k - 1];
      } else {
        std::memmove(entry_hash + pos + 1, entry_hash + pos,
                     sizeof(uint64_t) * (size - pos));
        std::memmove(entry_val + pos + 1, entry_val + pos,
                     sizeof(int64_t) * (size - pos));
        entry_hash[pos] = h;
        entry_val[pos] = v;
        ++size;
        if (size == k) threshold = entry_hash[k - 1];
      }
      ++edits;
    }
  }
  *size_io = size;
  return edits;
}

}  // extern "C"
