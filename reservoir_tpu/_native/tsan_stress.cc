// ThreadSanitizer stress for the staging buffer's concurrency contract
// (SURVEY §5 race-detection row — the one "partial" in VERDICT r2's
// component table: the mutex contract had no TSAN-style exercise).
//
// The documented contract: push_* and drain may run on different threads
// (the bridge's _FlushPipeline worker drains while the producer demuxes),
// all calls guarded by the internal mutex.  This harness runs producers,
// a draining consumer, and a polling monitor concurrently under
// -fsanitize=thread, and checks element conservation: every element
// consumed by a push is eventually drained exactly once.
//
// Build + run:  make -C reservoir_tpu/_native tsan   (CI `sanitizers` job)

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* rsv_staging_create(int32_t, int32_t, int32_t, int32_t);
void rsv_staging_destroy(void*);
int64_t rsv_staging_push_chunk(void*, int32_t, const void*, const void*,
                               int64_t);
int64_t rsv_staging_push_interleaved(void*, const int32_t*, const void*,
                                     const void*, int64_t);
int32_t rsv_staging_fill(void*, int32_t);
int32_t rsv_staging_any_full(void*);
int64_t rsv_staging_drain(void*, void*, void*, int32_t*);
int32_t rsv_staging_attach(void*, void*, void*);
int64_t rsv_staging_take(void*, int32_t*);
}

namespace {

constexpr int32_t kStreams = 16;
constexpr int32_t kWidth = 64;
constexpr int64_t kPairsPerProducer = 200000;

std::atomic<int64_t> pushed{0};
std::atomic<int64_t> drained{0};
std::atomic<bool> producers_done{false};

void producer(void* sb, unsigned seed) {
  std::vector<int32_t> streams(256);
  std::vector<int32_t> elems(256);
  unsigned state = seed;
  int64_t remaining = kPairsPerProducer;
  while (remaining > 0) {
    int64_t n = static_cast<int64_t>(streams.size());
    if (n > remaining) n = remaining;
    for (int64_t i = 0; i < n; ++i) {
      state = state * 1664525u + 1013904223u;
      streams[i] = static_cast<int32_t>(state % kStreams);
      elems[i] = static_cast<int32_t>(state >> 8);
    }
    int64_t off = 0;
    while (off < n) {
      int64_t took = rsv_staging_push_interleaved(
          sb, streams.data() + off, elems.data() + off, nullptr, n - off);
      if (took < 0) {
        std::fprintf(stderr, "push_interleaved failed\n");
        std::abort();
      }
      pushed.fetch_add(took);
      off += took;
      if (off < n) std::this_thread::yield();  // a row is full: consumer's turn
    }
    remaining -= n;
  }
}

void consumer(void* sb) {
  std::vector<int32_t> tile(static_cast<size_t>(kStreams) * kWidth);
  std::vector<int32_t> valid(kStreams);
  while (true) {
    // snapshot before the drain (same exit race as the parallel-demux
    // phase's consumer): a push landing between a zero drain and the flag
    // read must not let the loop exit with elements staged
    const bool done = producers_done.load();
    int64_t got = rsv_staging_drain(sb, tile.data(), nullptr, valid.data());
    if (got < 0) {
      std::fprintf(stderr, "drain failed\n");
      std::abort();
    }
    drained.fetch_add(got);
    // exit only once producers are finished AND the buffer drained empty
    if (done && got == 0) break;
    std::this_thread::yield();
  }
}

void monitor(void* sb) {
  while (!producers_done.load()) {
    (void)rsv_staging_any_full(sb);
    (void)rsv_staging_fill(sb, kStreams - 1);
    std::this_thread::yield();
  }
}

}  // namespace

// Phase 2: the zero-copy (attach/take) handoff contract — ONE producer
// demuxes into the attached tile and swaps buffers at each "flush"
// (take + attach-other), while a reader thread scans the tile the
// producer just handed off.  Mirrors the bridge's depth-1 pipeline: the
// producer never re-attaches a tile before the reader signalled done
// (the semaphore role is played by an atomic generation counter).
namespace {

std::atomic<int64_t> zc_handed{0};   // generation handed to the reader
std::atomic<int64_t> zc_read{0};     // generation the reader finished
std::atomic<int64_t> zc_sum_w{0};    // element checksum written
std::atomic<int64_t> zc_sum_r{0};    // element checksum read
constexpr int kZcFlushes = 200;

void zc_producer(void* sb, std::vector<int32_t>* tiles,
                 std::vector<int32_t>* valids) {
  unsigned state = 7u;
  std::vector<int32_t> streams(kStreams * kWidth / 2);
  std::vector<int32_t> elems(streams.size());
  int active = 0;
  for (int flush = 0; flush < kZcFlushes; ++flush) {
    const int64_t n = static_cast<int64_t>(streams.size());
    for (int64_t i = 0; i < n; ++i) {
      state = state * 1664525u + 1013904223u;
      streams[i] = static_cast<int32_t>(state % kStreams);
      elems[i] = static_cast<int32_t>(state >> 8) & 0xffff;
    }
    int64_t off = 0;
    while (off < n) {
      int64_t took = rsv_staging_push_interleaved(
          sb, streams.data() + off, elems.data() + off, nullptr, n - off);
      if (took < 0) std::abort();
      for (int64_t i = off; i < off + took; ++i) zc_sum_w.fetch_add(elems[i]);
      off += took;
      if (off < n) {
        // row full mid-batch: flush (take + swap) exactly like the bridge
        int64_t total =
            rsv_staging_take(sb, valids[active].data());
        if (total < 0) std::abort();
        // wait until the reader is done with the OTHER tile (depth-1)
        while (zc_handed.load() - zc_read.load() >= 1)
          std::this_thread::yield();
        zc_handed.fetch_add(1);
        int next = 1 - active;
        if (rsv_staging_attach(sb, tiles[next].data(), nullptr) != 0)
          std::abort();
        active = next;
      }
    }
  }
  // final flush of the remainder
  int64_t total = rsv_staging_take(sb, valids[active].data());
  if (total < 0) std::abort();
  while (zc_handed.load() - zc_read.load() >= 1) std::this_thread::yield();
  zc_handed.fetch_add(1);
}

void zc_reader(std::vector<int32_t>* tiles, std::vector<int32_t>* valids,
               std::atomic<bool>* done) {
  int active = 0;
  while (true) {
    if (zc_read.load() == zc_handed.load()) {
      if (done->load() && zc_read.load() == zc_handed.load()) break;
      std::this_thread::yield();
      continue;
    }
    // the tile at `active` was handed off; sum its valid elements
    for (int32_t s = 0; s < kStreams; ++s) {
      const int32_t f = valids[active][s];
      for (int32_t j = 0; j < f; ++j) {
        zc_sum_r.fetch_add(tiles[active][static_cast<size_t>(s) * kWidth + j]);
      }
    }
    zc_read.fetch_add(1);
    active = 1 - active;
  }
}

}  // namespace

static int run_zero_copy_phase() {
  void* sb = rsv_staging_create(kStreams, kWidth, sizeof(int32_t), 1);
  if (!sb) return 1;
  std::vector<int32_t> tiles[2] = {
      std::vector<int32_t>(static_cast<size_t>(kStreams) * kWidth),
      std::vector<int32_t>(static_cast<size_t>(kStreams) * kWidth)};
  std::vector<int32_t> valids[2] = {std::vector<int32_t>(kStreams),
                                    std::vector<int32_t>(kStreams)};
  if (rsv_staging_attach(sb, tiles[0].data(), nullptr) != 0) return 1;
  std::atomic<bool> done{false};
  std::thread r(zc_reader, tiles, valids, &done);
  std::thread p(zc_producer, sb, tiles, valids);
  p.join();
  done.store(true);
  r.join();
  rsv_staging_destroy(sb);
  if (zc_sum_w.load() != zc_sum_r.load()) {
    std::fprintf(stderr, "zero-copy checksum mismatch: wrote=%lld read=%lld\n",
                 static_cast<long long>(zc_sum_w.load()),
                 static_cast<long long>(zc_sum_r.load()));
    return 1;
  }
  std::printf("tsan_stress zero-copy OK: %lld handoffs, checksum %lld\n",
              static_cast<long long>(zc_read.load()),
              static_cast<long long>(zc_sum_r.load()));
  return 0;
}

// Phase 3: the RANGE-PARALLEL demux (pool workers scattering disjoint
// row ranges) under TSAN, plus its sequential-contract equivalence: the
// consumed-prefix count and the per-row contents must match a serial
// simulation exactly, while a consumer drains concurrently (SPSC).
namespace {

constexpr int32_t kParStreams = 64;
constexpr int32_t kParWidth = 256;
constexpr int64_t kParBatch = 1 << 15;  // >= the parallel threshold

int run_parallel_demux_phase() {
  void* sb = rsv_staging_create(kParStreams, kParWidth, sizeof(int32_t), 1);
  if (!sb) return 1;

  // deterministic stop-point + content equivalence vs a serial simulation
  std::vector<int32_t> streams(kParBatch), elems(kParBatch);
  unsigned state = 99u;
  for (int64_t i = 0; i < kParBatch; ++i) {
    state = state * 1664525u + 1013904223u;
    streams[i] = static_cast<int32_t>(state % kParStreams);
    elems[i] = static_cast<int32_t>(state >> 8);
  }
  std::vector<std::vector<int32_t>> expect(kParStreams);
  int64_t stop = kParBatch;
  for (int64_t i = 0; i < kParBatch; ++i) {
    auto& row = expect[streams[i]];
    if (static_cast<int32_t>(row.size()) >= kParWidth) {
      stop = i;
      break;
    }
    row.push_back(elems[i]);
  }
  int64_t took = rsv_staging_push_interleaved(sb, streams.data(),
                                              elems.data(), nullptr,
                                              kParBatch);
  if (took != stop) {
    std::fprintf(stderr, "parallel stop mismatch: got=%lld want=%lld\n",
                 static_cast<long long>(took), static_cast<long long>(stop));
    return 1;
  }
  std::vector<int32_t> tile(static_cast<size_t>(kParStreams) * kParWidth);
  std::vector<int32_t> valid(kParStreams);
  if (rsv_staging_drain(sb, tile.data(), nullptr, valid.data()) != took)
    return 1;
  for (int32_t s = 0; s < kParStreams; ++s) {
    if (valid[s] != static_cast<int32_t>(expect[s].size()) ||
        std::memcmp(tile.data() + static_cast<size_t>(s) * kParWidth,
                    expect[s].data(), expect[s].size() * sizeof(int32_t))) {
      std::fprintf(stderr, "parallel row %d mismatch\n", s);
      return 1;
    }
  }

  // SPSC stress at parallel batch sizes: pool workers + concurrent drain
  std::atomic<int64_t> p_pushed{0}, p_drained{0};
  std::atomic<bool> p_done{false};
  std::thread cons([&] {
    while (true) {
      // snapshot the flag BEFORE draining: if the producer pushes its
      // final batch and sets p_done between a zero-result drain and the
      // flag check, breaking on the stale got==0 would strand elements
      // and fail the conservation gate below.  done-before-drain means
      // "done && got == 0" proves the buffer was empty after the last
      // push.
      const bool done = p_done.load();
      int64_t got = rsv_staging_drain(sb, tile.data(), nullptr, valid.data());
      if (got < 0) std::abort();
      p_drained.fetch_add(got);
      if (done && got == 0) break;
      std::this_thread::yield();
    }
  });
  int64_t remaining = 20 * kParBatch;
  while (remaining > 0) {
    int64_t off = 0;
    while (off < kParBatch) {
      int64_t t = rsv_staging_push_interleaved(
          sb, streams.data() + off, elems.data() + off, nullptr,
          kParBatch - off);
      if (t < 0) std::abort();
      p_pushed.fetch_add(t);
      off += t;
      if (off < kParBatch) std::this_thread::yield();
    }
    remaining -= kParBatch;
  }
  p_done.store(true);
  cons.join();
  rsv_staging_destroy(sb);
  if (p_pushed.load() != 20 * kParBatch ||
      p_drained.load() != p_pushed.load()) {
    std::fprintf(stderr, "parallel conservation violated\n");
    return 1;
  }
  std::printf("tsan_stress parallel demux OK: stop=%lld, %lld through pool\n",
              static_cast<long long>(stop),
              static_cast<long long>(p_pushed.load()));
  return 0;
}

}  // namespace

int main() {
  // force the pool on before its lazy init (phases 1/2 stay below the
  // parallel threshold, so the first big push in phase 3 constructs it)
  setenv("RESERVOIR_STAGING_THREADS", "4", 1);
  void* sb = rsv_staging_create(kStreams, kWidth, sizeof(int32_t), 1);
  if (!sb) {
    std::fprintf(stderr, "create failed\n");
    return 1;
  }
  std::thread c(consumer, sb);
  std::thread m(monitor, sb);
  std::thread p1(producer, sb, 1u);
  std::thread p2(producer, sb, 2u);
  p1.join();
  p2.join();
  producers_done.store(true);
  c.join();
  m.join();
  const int64_t expect = 2 * kPairsPerProducer;
  if (pushed.load() != expect || drained.load() != expect) {
    std::fprintf(stderr, "conservation violated: pushed=%lld drained=%lld\n",
                 static_cast<long long>(pushed.load()),
                 static_cast<long long>(drained.load()));
    rsv_staging_destroy(sb);
    return 1;
  }
  rsv_staging_destroy(sb);
  std::printf("tsan_stress OK: %lld elements through %d streams\n",
              static_cast<long long>(expect), kStreams);
  int rc = run_zero_copy_phase();
  if (rc != 0) return rc;
  return run_parallel_demux_phase();
}
