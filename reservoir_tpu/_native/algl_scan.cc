// Native bulk path for the host Algorithm-L oracle (duplicates mode).
//
// The Python skip-jump path (oracle/algorithm_l.py::_sample_indexed)
// already touches only accepted elements, but each acceptance costs ~4us
// of interpreter overhead (three Generator calls + float math) — ~1.1k
// acceptances for a 1M-element k=128 stream caps the host row at ~2.3e8
// elem/s.  This scan is the identical loop in C, drawing from the SAME
// numpy bit stream: the caller passes the BitGenerator's next_double
// function pointer + state (numpy's documented ctypes interface), so
// native and Python paths produce bit-identical reservoirs under one seed.
//
// Draw order per acceptance (the oracle's documented contract):
//   slot = floor(next_double * k); u1 = 1 - next_double; u2 = 1 - next_double
// matching AlgorithmLOracle._evict / _advance exactly.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).

#include <cmath>
#include <cstdint>

extern "C" {

typedef double (*next_double_fn)(void*);

// Scan elems[0..n) in steady state (reservoir full, count >= k).  Returns
// the new count; samples/log_w/next_acc are updated in place.
int64_t reservoir_algl_scan(void* next_double_ptr, void* rng_state,
                            const int64_t* elems, int64_t n, int64_t k,
                            int64_t* samples, int64_t count, int64_t next_acc,
                            double log_w, double* log_w_out,
                            int64_t* next_out) {
  next_double_fn next_double =
      reinterpret_cast<next_double_fn>(next_double_ptr);
  int64_t i = 0;
  while (true) {
    // absolute stream index of elems[i] is count + i + 1; the next
    // acceptance (absolute index next_acc) sits at offset:
    int64_t target = i + (next_acc - count) - 1;
    if (target >= n) {
      count += n - i;
      break;
    }
    count += target - i + 1;
    i = target + 1;
    // evict: overwrite a uniform slot, then redraw W / next (Algorithm L,
    // Sampler.scala:243-246 / :228-236 semantics)
    int64_t slot = static_cast<int64_t>(next_double(rng_state) * (double)k);
    samples[slot] = elems[target];
    double u1 = 1.0 - next_double(rng_state);
    double u2 = 1.0 - next_double(rng_state);
    log_w += std::log(u1) / static_cast<double>(k);
    double w = std::exp(log_w);
    int64_t skip;
    if (w < 1.0) {
      skip = static_cast<int64_t>(std::floor(std::log(u2) / std::log1p(-w)));
    } else {
      skip = 0;  // log1p(-1) = -inf -> immediate re-accept
    }
    next_acc += skip + 1;
  }
  *log_w_out = log_w;
  *next_out = next_acc;
  return count;
}

}  // extern "C"
