"""Configuration and parameter validation.

The reference's entire configuration surface is constructor parameters,
validated eagerly (``Sampler.scala:70-95``):

- ``MaxSize = Int.MaxValue - 2``            (``Sampler.scala:71``)
- ``DefaultInitialSize = 16``               (``Sampler.scala:72``)
- ``validateSharedParams``: ``0 < maxSampleSize <= MaxSize`` else
  ``IllegalArgumentException``; non-null ``map`` else NPE (``Sampler.scala:79-86``)
- ``validateDistinctParams`` additionally requires a ``hash`` (``Sampler.scala:92-95``)

We keep the same philosophy — no global flag registry, no config files.  A
frozen :class:`SamplerConfig` carries the device-engine parameters (reservoir
count, tile size, dtypes, mesh axes); plain keyword arguments configure the
host :class:`~reservoir_tpu.api.Sampler`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

#: Maximum sample size, identical to the reference (``Sampler.scala:71``).
MAX_SIZE: int = 2**31 - 3  # Int.MaxValue - 2 == 2147483645

#: Initial capacity of a non-pre-allocated growable reservoir
#: (``Sampler.scala:72``).  The host oracle grows a Python list (already
#: geometric); device reservoirs are always statically shaped at ``k`` —
#: XLA requires static shapes, so ``pre_allocate`` is the natural mode there.
DEFAULT_INITIAL_SIZE: int = 16


def validate_max_sample_size(max_sample_size: Any) -> int:
    """``0 < maxSampleSize <= MaxSize`` (``Sampler.scala:79-84``)."""
    if not isinstance(max_sample_size, int) or isinstance(max_sample_size, bool):
        raise ValueError(
            f"max_sample_size must be an int, got {type(max_sample_size).__name__}"
        )
    if max_sample_size <= 0:
        raise ValueError(f"max_sample_size must be positive, got {max_sample_size}")
    if max_sample_size > MAX_SIZE:
        raise ValueError(
            f"max_sample_size must be <= {MAX_SIZE}, got {max_sample_size}"
        )
    return max_sample_size


def validate_map(map_fn: Any) -> Callable:
    """Non-null, callable ``map`` (``Sampler.scala:85`` — NPE -> TypeError)."""
    if map_fn is None or not callable(map_fn):
        raise TypeError("map function must be callable (got %r)" % (map_fn,))
    return map_fn


def validate_hash(hash_fn: Any) -> Callable:
    """Non-null, callable ``hash`` (``Sampler.scala:92-95``)."""
    if hash_fn is None or not callable(hash_fn):
        raise TypeError("hash function must be callable (got %r)" % (hash_fn,))
    return hash_fn


def validate_shared_params(max_sample_size: Any, map_fn: Any) -> None:
    """Mirror of ``validateSharedParams`` (``Sampler.scala:79-86``)."""
    validate_max_sample_size(max_sample_size)
    validate_map(map_fn)


def validate_non_distinct_params(max_sample_size: Any, map_fn: Any) -> None:
    """Mirror of ``validateNonDistinctParams`` (``Sampler.scala:87-90``)."""
    validate_shared_params(max_sample_size, map_fn)


def validate_distinct_params(max_sample_size: Any, map_fn: Any, hash_fn: Any) -> None:
    """Mirror of ``validateDistinctParams`` (``Sampler.scala:92-95``)."""
    validate_shared_params(max_sample_size, map_fn)
    validate_hash(hash_fn)


@dataclasses.dataclass(frozen=True)
class SamplerConfig:
    """Frozen device-engine configuration.

    One logical "sampler" on device is ``num_reservoirs`` independent
    reservoirs updated in lockstep (the reference's single mutable sampler,
    ``Sampler.scala:196-207``, becomes a pytree of ``[R, ...]`` arrays).

    Attributes:
      max_sample_size: ``k`` — reservoir capacity per stream.
      num_reservoirs: ``R`` — independent reservoirs (vmapped axis).
      tile_size: ``B`` — elements consumed per reservoir per device step.
      element_dtype: dtype of stream elements on device.
      sample_dtype: dtype of stored samples (post-``map``); defaults to
        ``element_dtype``.
      count_dtype: dtype of the per-reservoir element counter.  ``int32``
        supports 2^31-1 elements *per reservoir* (ample for sharded streams);
        ``"wide"`` carries counters as emulated-uint64 uint32 planes —
        streams past 2^31 per reservoir with x64 OFF (duplicates mode only;
        the reference's ``count: Long``, ``Sampler.scala:203``); ``int64``
        with x64 enabled also works.
      distinct: bottom-k distinct-value mode (``Sampler.scala:383-412``).
      weighted: A-ExpJ weighted mode (capability beyond the reference).
      mesh_axis: mesh axis name the reservoir dimension is sharded over
        (None = single device).
      impl: hot-path kernel selection.  ``"auto"`` (default) dispatches
        eligible updates (full tiles, identity map, supported dtypes, R
        divisible by the row block; duplicates mode additionally requires
        steady state — the weighted kernel is fill-capable) to the Pallas
        TPU kernels on TPU backends and the XLA path everywhere else;
        ``"xla"`` never uses Pallas; ``"pallas"`` forces the Pallas kernel
        for eligible updates (Mosaic interpreter on CPU) and fails
        construction if the config can never be eligible.  All three modes
        have kernels (Algorithm L steady-state, A-ExpJ fill-capable,
        distinct threshold-scan); user ``map_fn``/``hash_fn`` hooks always
        take the XLA path.
    """

    max_sample_size: int
    num_reservoirs: int = 1
    tile_size: int = 1024
    element_dtype: Any = "int32"
    sample_dtype: Optional[Any] = None
    count_dtype: Any = "int32"
    distinct: bool = False
    weighted: bool = False
    mesh_axis: Optional[str] = None
    impl: str = "auto"

    def __post_init__(self) -> None:
        validate_max_sample_size(self.max_sample_size)
        if self.num_reservoirs <= 0:
            raise ValueError("num_reservoirs must be positive")
        if self.tile_size <= 0:
            raise ValueError("tile_size must be positive")
        if self.impl not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"impl must be 'auto', 'xla' or 'pallas', got {self.impl!r}"
            )
        if self.count_dtype == "wide" and (self.distinct or self.weighted):
            raise ValueError(
                "count_dtype='wide' is only supported in duplicates mode "
                "(distinct/weighted counters stay int32)"
            )

    @property
    def k(self) -> int:
        return self.max_sample_size

    def resolved_sample_dtype(self) -> Any:
        return self.sample_dtype if self.sample_dtype is not None else self.element_dtype
