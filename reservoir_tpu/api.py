"""Host Sampler API — the reference's public trait and factories.

Mirrors ``trait Sampler[A,B]`` (``Sampler.scala:26-68``): ``sample``,
``sample_all`` (default per-element loop, ``:50``), ``result``, ``is_open`` —
plus the factory/validation surface of ``object Sampler``
(``Sampler.scala:70-180``) and its lifecycle matrix:

====================  =========================================  ==========================
factory               single-use (default)                       reusable
====================  =========================================  ==========================
:func:`sampler`       ``SingleUseRandomElements`` (:334-351)     ``MultiResultRandomElements`` (:353-381)
:func:`distinct`      ``SingleUseRandomValues`` (:414-428)       ``MultiResultRandomValues`` (:430-433)
====================  =========================================  ==========================

Single-use semantics: ``result()`` closes the sampler and frees its buffers
(GC-nulling, ``:345-350``); any later ``sample``/``sample_all``/``result``
raises :class:`~reservoir_tpu.errors.SamplerClosedError`
(``SingleUse.checkOpen``, ``:185-186``); ``is_open`` stays callable (``:193``).
Reusable semantics: ``result()`` returns a stable snapshot and sampling may
continue; earlier snapshots are never clobbered.  As in the reference
(zero-copy ``ArraySeq`` over the live array with copy-on-write,
``:353-381``), the snapshot is an immutable zero-copy view
(:class:`SampleView`) of the live buffer; the engine copies before its next
mutation, so the view never changes underneath the caller.

These host samplers run the CPU oracles — they are the semantic baseline
(BASELINE.md config 1).  The batch/device counterpart with the same lifecycle
is :class:`reservoir_tpu.engine.ReservoirEngine`.

Samplers are NOT thread-safe, matching the reference's documented contract
(``Sampler.scala:19, 105, 143``).
"""

from __future__ import annotations

import abc
from collections.abc import Sequence as _SequenceABC
from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from .config import validate_non_distinct_params
from .errors import SamplerClosedError
from .oracle.algorithm_l import AlgorithmLOracle
from .oracle.bottom_k import BottomKOracle

__all__ = [
    "Sampler",
    "SampleView",
    "sampler",
    "distinct",
    "weighted",
    "WeightedSampler",
]

_identity = lambda x: x  # noqa: E731


class SampleView(_SequenceABC):
    """Immutable zero-copy view of a reusable sampler's current sample —
    the ``ArraySeq.unsafeWrapArray`` analog (``Sampler.scala:375-379``).

    O(1) to create: wraps the engine's live buffer without copying.  The
    engine's copy-on-write guard copies *its* side before the next mutation,
    so a view is a stable snapshot; immutability here keeps the caller from
    mutating engine state through the alias (the reference returns an
    immutable ``IndexedSeq`` for exactly this reason).
    """

    __slots__ = ("_data",)

    def __init__(self, data: List[Any]) -> None:
        self._data = data

    def __getitem__(self, index):
        if isinstance(index, slice):
            return list(self._data[index])
        return self._data[index]

    def __len__(self) -> int:
        return len(self._data)

    def __eq__(self, other) -> bool:
        if isinstance(other, (SampleView, list, tuple)):
            return list(self) == list(other)
        return NotImplemented

    def __hash__(self):
        return hash(tuple(self._data))

    def __repr__(self) -> str:
        return f"SampleView({self._data!r})"


class Sampler(abc.ABC):
    """Public sampler trait (``Sampler.scala:26-68``).

    Not reusable unless stated otherwise; not thread-safe (doc contract,
    ``Sampler.scala:14-19``).
    """

    @abc.abstractmethod
    def sample(self, element: Any) -> None:
        """Sample a single element (``Sampler.scala:38``)."""

    def sample_all(self, elements: Iterable[Any]) -> None:
        """Sample every element; default per-element loop (``Sampler.scala:50``).
        Implementations override with skip-jump bulk paths that must produce
        identical results under identical RNG state (invariant 4)."""
        for element in elements:
            self.sample(element)

    @abc.abstractmethod
    def result(self) -> Sequence[Any]:
        """The sampled elements (``Sampler.scala:60``).  Single-use samplers
        close and return a fresh list; reusable samplers return a stable
        snapshot (possibly an immutable zero-copy :class:`SampleView`)."""

    @property
    @abc.abstractmethod
    def is_open(self) -> bool:
        """Whether this sampler can still sample (``Sampler.scala:67``)."""


class _SingleUseMixin:
    """Lifecycle state machine (``SingleUse``, ``Sampler.scala:182-194``)."""

    _open = True

    def _check_open(self) -> None:
        if not self._open:
            raise SamplerClosedError(
                "this sampler is single-use, and no longer open"
            )

    def _close(self) -> None:
        self._open = False

    @property
    def is_open(self) -> bool:
        return self._open


class _SingleUseSampler(_SingleUseMixin, Sampler):
    """Single-use wrapper over an oracle engine (``Sampler.scala:334-351,
    414-428``)."""

    def __init__(self, engine) -> None:
        self._engine = engine

    def sample(self, element: Any) -> None:
        self._check_open()
        self._engine.sample(element)

    def sample_all(self, elements: Iterable[Any]) -> None:
        self._check_open()
        self._engine.sample_all(elements)

    def result(self) -> List[Any]:
        self._check_open()
        res = self._engine.result()
        self._close()
        self._engine = None  # free for GC (Sampler.scala:345-350)
        return res


class _ReusableSampler(Sampler):
    """Reusable wrapper (``Sampler.scala:353-381, 430-433``): ``result()``
    snapshots without closing; ``is_open`` is always True (``:380``)."""

    def __init__(self, engine) -> None:
        self._engine = engine

    def sample(self, element: Any) -> None:
        self._engine.sample(element)

    def sample_all(self, elements: Iterable[Any]) -> None:
        self._engine.sample_all(elements)

    def result(self) -> Sequence[Any]:
        # zero-copy with copy-on-write when the engine supports it (the
        # reusable aliasing optimization, Sampler.scala:353-381); the
        # immutable view is a stable snapshot
        view = getattr(self._engine, "result_view", None)
        if view is not None:
            return SampleView(view())
        return self._engine.result()

    @property
    def is_open(self) -> bool:
        return True


def _resolve_rng(rng: Union[None, int, np.random.Generator]) -> np.random.Generator:
    """Explicit RNG in, reproducibility out — the constructor-input design the
    reference's reflection-based tests argue for (``SamplerTest.scala:16-54``)."""
    if isinstance(rng, np.random.Generator):
        return rng
    return np.random.default_rng(rng)


def sampler(
    max_sample_size: int,
    *,
    pre_allocate: bool = False,
    reusable: bool = False,
    map_fn: Optional[Callable[[Any], Any]] = None,
    rng: Union[None, int, np.random.Generator] = None,
) -> Sampler:
    """Uniform reservoir sampler, duplicates allowed (``Sampler.apply``,
    ``Sampler.scala:130-136``).

    Each element of the stream has ``k/n`` inclusion probability.  ``map_fn``
    is applied on accept and may be called more than ``k`` times
    (``Sampler.scala:116``).  ``rng`` may be a seed or a ``numpy`` Generator.
    """
    # validate with an explicit identity but hand the oracle the user's
    # map_fn as given: None tells it the map is identity, unlocking the
    # native bulk scan (oracle/algorithm_l.py module docs)
    validate_non_distinct_params(
        max_sample_size, map_fn if map_fn is not None else _identity
    )
    engine = AlgorithmLOracle(
        max_sample_size, _resolve_rng(rng), map_fn=map_fn, pre_allocate=pre_allocate
    )
    return _ReusableSampler(engine) if reusable else _SingleUseSampler(engine)


def distinct(
    max_sample_size: int,
    *,
    reusable: bool = False,
    map_fn: Optional[Callable[[Any], Any]] = None,
    hash_fn: Optional[Callable[[Any], int]] = None,
    rng: Union[None, int, np.random.Generator] = None,
    salts: Optional[Tuple[int, int]] = None,
) -> Sampler:
    """Distinct-value sampler (``Sampler.distinct``, ``Sampler.scala:173-180``).

    Each *distinct value* of the stream has uniform inclusion probability.
    ``map_fn`` is applied to every element (it feeds the hash,
    ``Sampler.scala:155``); ``hash_fn`` defaults to a stable 64-bit hash
    covering every stable hashable — ints (identity embedding), floats,
    str/bytes, None, tuples, frozensets (canonical-serialization FNV;
    ``Sampler.scala:75`` analog).  Only objects with process-salted or
    id-based hashes need an explicit ``hash_fn``.
    """
    # keep the user's map_fn as given (None = identity): the oracle's
    # vectorized bulk path only engages without a per-element map hook
    validate_non_distinct_params(
        max_sample_size, map_fn if map_fn is not None else _identity
    )
    if hash_fn is not None:
        from .config import validate_hash

        validate_hash(hash_fn)  # explicit hash must be callable (:92-95)
    engine = BottomKOracle(
        max_sample_size,
        _resolve_rng(rng),
        map_fn=map_fn,
        hash_fn=hash_fn,  # None -> oracle's stable default (Sampler.scala:75)
        salts=salts,
    )
    return _ReusableSampler(engine) if reusable else _SingleUseSampler(engine)


class WeightedSampler:
    """Host weighted sampler (A-ExpJ) behind the reference lifecycle.

    Capability beyond the reference (it has no weighted mode — SURVEY §6);
    the surface mirrors :class:`Sampler` except ``sample`` takes
    ``(element, weight)``.  Zero-weight contract: ``w == 0`` is counted but
    never sampled; ``w < 0`` raises — identical to the device engine
    (:mod:`reservoir_tpu.ops.weighted` module docs).
    """

    def __init__(self, engine, reusable: bool) -> None:
        self._engine = engine
        self._reusable = reusable
        self._open = True

    def _check_open(self) -> None:
        if not self._reusable and not self._open:
            raise SamplerClosedError(
                "this sampler is single-use, and no longer open"
            )

    @property
    def is_open(self) -> bool:
        return True if self._reusable else self._open

    def sample(self, element: Any, weight: float) -> None:
        self._check_open()
        self._engine.sample(element, weight)

    def sample_all(
        self,
        pairs: Iterable[Tuple[Any, float]],
        weights: Optional[Any] = None,
    ) -> None:
        """Bulk path: ``sample_all(pairs)`` over ``(element, weight)`` pairs,
        or ``sample_all(elements, weights)`` over parallel arrays — the
        array form takes the vectorized exponential-jump route (identical
        results, C-speed skips) when the engine provides it."""
        self._check_open()
        if weights is not None:
            bulk = getattr(self._engine, "sample_all_arrays", None)
            if bulk is not None:
                bulk(pairs, weights)
            else:
                elems_arr = np.asarray(pairs)
                weights_arr = np.asarray(weights)
                if elems_arr.shape != weights_arr.shape or elems_arr.ndim != 1:
                    # zip() would silently truncate the longer side, and
                    # 2-D rows would fail deep in the oracle instead
                    raise ValueError(
                        "elements and weights must be matching 1-D arrays"
                    )
                self._engine.sample_all(zip(elems_arr, weights_arr))
        else:
            self._engine.sample_all(pairs)

    def result(self) -> List[Any]:
        self._check_open()
        res = self._engine.result()
        if not self._reusable:
            self._open = False
            self._engine = None  # free for GC (Sampler.scala:345-350)
        return res


def weighted(
    max_sample_size: int,
    *,
    reusable: bool = False,
    rng: Union[None, int, np.random.Generator] = None,
    naive: bool = False,
) -> WeightedSampler:
    """Weighted reservoir sampler: k items with inclusion biased by weight
    (Efraimidis-Spirakis keys; A-ExpJ jumps by default, ``naive=True`` for
    the exact A-ES construction used as distributional ground truth)."""
    from .oracle.weighted import AExpJOracle, NaiveWeightedOracle

    cls = NaiveWeightedOracle if naive else AExpJOracle
    engine = cls(max_sample_size, _resolve_rng(rng))
    return WeightedSampler(engine, reusable)
