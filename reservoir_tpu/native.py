"""ctypes loader for the native host helpers (``_native/staging_buffer.cc``).

The runtime around the TPU compute path is native where it matters: the
stream bridge's interleaved demux — scattering (stream_id, element) pairs
into per-stream staging rows — is an interpreter-speed loop in Python and a
pointer walk in C++ (SURVEY §7.3: the host feed, not the kernel, is the
likely bottleneck at 1e9 elem/s).

Loading is best-effort with a build attempt (``make`` in
``reservoir_tpu/_native/``) and a pure-numpy fallback: the framework never
*requires* the .so — it only gets faster with it.  ``NativeStaging.available()``
reports which path is in use, :func:`load_error` why loading failed (the
build is no longer *silently* best-effort); ``RESERVOIR_TPU_NO_NATIVE=1``
forces the fallback (used by tests to cover both).  Loading is guarded by a
lock so concurrent first use cannot race into duplicate builds.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from .utils import faults as _faults

__all__ = ["NativeStaging", "load_library", "load_error", "algl_scan"]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libreservoir_host.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
_load_error: Optional[str] = None
_load_lock = threading.Lock()


def load_error() -> Optional[str]:
    """Why the last :func:`load_library` attempt failed (None = no failure)."""
    return _load_error


def _so_stale() -> bool:
    """True when the .so is missing or older than any .cc/Makefile source."""
    try:
        so_mtime = os.path.getmtime(_SO_PATH)
    except OSError:
        return True
    for name in os.listdir(_NATIVE_DIR):
        if name.endswith(".cc") or name == "Makefile":
            try:
                if os.path.getmtime(os.path.join(_NATIVE_DIR, name)) > so_mtime:
                    return True
            except OSError:
                return True
    return False


def load_library(rebuild: bool = False) -> Optional[ctypes.CDLL]:
    """Load (building on first use if needed) the native library; None if
    unavailable — callers fall back to numpy, and :func:`load_error` says why."""
    global _lib, _load_attempted, _load_error
    if os.environ.get("RESERVOIR_TPU_NO_NATIVE") == "1":
        return None
    with _load_lock:
        if _lib is not None and not rebuild:
            return _lib
        if _load_attempted and not rebuild:
            return _lib
        _load_attempted = True
        # invoke make only when the .so is missing or older than a source
        # file — a stat comparison in-process, so the common warm path never
        # forks a subprocess (and concurrent fresh processes rarely race;
        # the Makefile builds to a temp name and mv's for atomicity)
        if rebuild or _so_stale():
            try:
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except (OSError, subprocess.SubprocessError) as e:
                _load_error = f"native build failed: {e}"
                if not os.path.exists(_SO_PATH):
                    return None  # no stale .so to fall back on either
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            _load_error = f"dlopen failed: {e}"
            return None
        if _load_error and _load_error.startswith("native build failed"):
            # a stale-but-working .so loaded: the native path IS live, but
            # it may not match the sources — keep the failure visible (the
            # module contract: the build is never *silently* best-effort)
            _load_error = f"running STALE .so ({_load_error})"
        return _finish_load(lib)


def _finish_load(lib: ctypes.CDLL) -> ctypes.CDLL:
    global _lib
    lib.rsv_staging_create.restype = ctypes.c_void_p
    lib.rsv_staging_create.argtypes = [ctypes.c_int32] * 4
    lib.rsv_staging_destroy.argtypes = [ctypes.c_void_p]
    lib.rsv_staging_push_chunk.restype = ctypes.c_int64
    lib.rsv_staging_push_chunk.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
    ]
    lib.rsv_staging_push_interleaved.restype = ctypes.c_int64
    lib.rsv_staging_push_interleaved.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
    ]
    lib.rsv_staging_any_full.restype = ctypes.c_int32
    lib.rsv_staging_any_full.argtypes = [ctypes.c_void_p]
    lib.rsv_staging_fill.restype = ctypes.c_int32
    lib.rsv_staging_fill.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.rsv_staging_drain.restype = ctypes.c_int64
    lib.rsv_staging_drain.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
    ]
    if hasattr(lib, "rsv_staging_threads"):  # absent in a stale pre-r5 .so
        lib.rsv_staging_threads.restype = ctypes.c_int32
        lib.rsv_staging_threads.argtypes = []
    if hasattr(lib, "rsv_staging_attach"):  # absent in a stale pre-r4 .so
        lib.rsv_staging_attach.restype = ctypes.c_int32
        lib.rsv_staging_attach.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.rsv_staging_take.restype = ctypes.c_int64
        lib.rsv_staging_take.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    if hasattr(lib, "rsv_bottomk_scan"):  # absent only in a stale pre-r2 .so
        lib.rsv_bottomk_scan.restype = ctypes.c_int64
        lib.rsv_bottomk_scan.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
    if hasattr(lib, "reservoir_algl_scan"):  # absent only in a stale .so
        lib.reservoir_algl_scan.restype = ctypes.c_int64
        lib.reservoir_algl_scan.argtypes = [
            ctypes.c_void_p,  # next_double function pointer
            ctypes.c_void_p,  # bit-generator state
            ctypes.c_void_p,  # elems
            ctypes.c_int64,  # n
            ctypes.c_int64,  # k
            ctypes.c_void_p,  # samples (in/out)
            ctypes.c_int64,  # count
            ctypes.c_int64,  # next acceptance (absolute, 1-based)
            ctypes.c_double,  # log_w
            ctypes.POINTER(ctypes.c_double),  # log_w out
            ctypes.POINTER(ctypes.c_int64),  # next out
        ]
    _lib = lib
    return _lib


def algl_scan(rng, elems: np.ndarray, k: int, samples: np.ndarray,
              count: int, next_acc: int, log_w: float):
    """Steady-state Algorithm-L skip-jump scan in C, drawing from ``rng``'s
    own bit stream (numpy's documented BitGenerator ctypes interface) so the
    result is bit-identical to the Python path under one seed.

    Mutates ``samples`` (int64[k]) in place; returns
    ``(count, next_acc, log_w)`` after the scan, or None when the native
    library (or the generator's ctypes interface) is unavailable — callers
    fall back to the Python loop.
    """
    lib = load_library()
    if lib is None or not hasattr(lib, "reservoir_algl_scan"):
        return None
    try:
        iface = rng.bit_generator.ctypes
        fn_ptr = ctypes.cast(iface.next_double, ctypes.c_void_p)
        state = iface.state_address
    except AttributeError:
        return None
    log_w_out = ctypes.c_double()
    next_out = ctypes.c_int64()
    new_count = lib.reservoir_algl_scan(
        fn_ptr,
        ctypes.c_void_p(state),
        elems.ctypes.data_as(ctypes.c_void_p),
        elems.size,
        k,
        samples.ctypes.data_as(ctypes.c_void_p),
        count,
        next_acc,
        log_w,
        ctypes.byref(log_w_out),
        ctypes.byref(next_out),
    )
    return int(new_count), int(next_out.value), float(log_w_out.value)


class NativeStaging:
    """[S, B] staging tile with C-speed interleaved demux and a numpy
    fallback.  Single-producer/single-consumer (the bridge's contract)."""

    def __init__(self, num_streams: int, tile_width: int, dtype,
                 weighted: bool = False) -> None:
        self._S = int(num_streams)
        self._B = int(tile_width)
        self._dtype = np.dtype(dtype)
        self._weighted = weighted
        if weighted and self._dtype.itemsize != 4:
            raise ValueError("weighted staging requires a 4-byte element dtype")
        self._lib = load_library()
        if self._lib is not None:
            self._handle = self._lib.rsv_staging_create(
                self._S, self._B, self._dtype.itemsize, 2 if weighted else 1
            )
            if not self._handle:
                self._lib = None
        if self._lib is None:
            self._handle = None
            self._buf = np.zeros((self._S, self._B), self._dtype)
            self._wbuf = np.zeros((self._S, self._B), np.float32) if weighted else None
            self._fill = np.zeros(self._S, np.int32)

    def available(self) -> bool:
        """True when the C++ path is live (False: numpy fallback)."""
        return self._lib is not None

    def threads(self) -> int:
        """Demux worker count the native pool would use (1 = serial; the
        numpy fallback is always 1).  Telemetry for the bridge stage
        table — a multi-core capture records its own parallelism."""
        if self._lib is not None and hasattr(self._lib, "rsv_staging_threads"):
            return int(self._lib.rsv_staging_threads())
        return 1

    # --------------------------------------------------------- zero-copy mode

    def supports_attach(self) -> bool:
        """True when the zero-copy flush mode is available (native lib with
        the attach/take ABI, or the numpy fallback which emulates it)."""
        return self._lib is None or hasattr(self._lib, "rsv_staging_attach")

    def attach(self, tile: np.ndarray,
               weights: Optional[np.ndarray] = None) -> None:
        """Scatter future pushes straight into caller-owned buffers (the
        bridge's zero-copy flush mode): ``tile`` is ``[S, B]`` of the
        staging dtype, ``weights`` the parallel float32 tile iff weighted.
        The caller must keep the arrays alive while attached and must not
        read them concurrently with pushes (single-producer contract)."""
        if tile.shape != (self._S, self._B) or tile.dtype != self._dtype:
            raise ValueError(
                f"tile must be [{self._S}, {self._B}] {self._dtype}"
            )
        if not tile.flags["C_CONTIGUOUS"]:
            raise ValueError("attached tile must be C-contiguous")
        if self._weighted != (weights is not None):
            raise ValueError("weights tile required iff staging is weighted")
        if weights is not None and not (
            weights.flags["C_CONTIGUOUS"]
            and weights.shape == (self._S, self._B)
            and weights.dtype == np.float32
        ):
            raise ValueError(
                f"weights must be C-contiguous [{self._S}, {self._B}] float32"
            )
        if self._lib is not None:
            if not hasattr(self._lib, "rsv_staging_attach"):
                raise RuntimeError(
                    "stale native library without the attach ABI; "
                    "load_library(rebuild=True)"
                )
            rc = self._lib.rsv_staging_attach(
                self._handle,
                tile.ctypes.data_as(ctypes.c_void_p),
                weights.ctypes.data_as(ctypes.c_void_p)
                if weights is not None
                else None,
            )
            if rc != 0:
                raise ValueError("invalid attach arguments")
            # keep the arrays alive while the C side holds raw pointers
            self._attached = (tile, weights)
        else:
            self._buf = tile
            self._wbuf = weights

    def take(self, out_valid: np.ndarray) -> int:
        """The zero-copy drain: copy per-row fill counts into ``out_valid``
        and reset them.  Tile data is already in the attached buffers."""
        _faults.fire("native.staging")
        if out_valid.shape != (self._S,) or out_valid.dtype != np.int32:
            raise ValueError(f"out_valid must be [{self._S}] int32")
        if not out_valid.flags["C_CONTIGUOUS"]:
            raise ValueError("out_valid must be C-contiguous")
        if self._lib is not None:
            if not hasattr(self._lib, "rsv_staging_take"):
                raise RuntimeError(
                    "stale native library without the attach ABI; "
                    "load_library(rebuild=True)"
                )
            total = self._lib.rsv_staging_take(
                self._handle, out_valid.ctypes.data_as(ctypes.c_void_p)
            )
            if total < 0:
                raise ValueError("invalid take arguments")
            return int(total)
        out_valid[...] = self._fill
        total = int(self._fill.sum())
        self._fill[:] = 0
        return total

    # ------------------------------------------------------------------ push

    def push_chunk(self, stream: int, elems: np.ndarray,
                   weights: Optional[np.ndarray] = None) -> int:
        """Append a contiguous chunk to one row; returns elements consumed
        (less than ``len(elems)`` when the row filled — drain and resume)."""
        _faults.fire("native.staging")
        elems = np.ascontiguousarray(elems, self._dtype)
        if self._weighted != (weights is not None):
            raise ValueError("weights required iff staging is weighted")
        if weights is not None:
            weights = np.ascontiguousarray(weights, np.float32)
            if weights.shape != elems.shape:
                raise ValueError("weights must match elements shape")
        if self._lib is not None:
            took = self._lib.rsv_staging_push_chunk(
                self._handle,
                int(stream),
                elems.ctypes.data_as(ctypes.c_void_p),
                weights.ctypes.data_as(ctypes.c_void_p) if weights is not None else None,
                elems.size,
            )
            if took < 0:
                raise ValueError("invalid push_chunk arguments")
            return int(took)
        fill = int(self._fill[stream])
        take = min(self._B - fill, elems.size)
        self._buf[stream, fill : fill + take] = elems[:take]
        if weights is not None:
            self._wbuf[stream, fill : fill + take] = weights[:take]
        self._fill[stream] += take
        return take

    def push_interleaved(self, streams: np.ndarray, elems: np.ndarray,
                         weights: Optional[np.ndarray] = None) -> int:
        """Demux (stream_id, element) pairs; returns pairs consumed (less
        than ``len(streams)`` when a target row filled mid-batch).  Raises on
        out-of-range stream ids."""
        _faults.fire("native.staging")
        streams = np.ascontiguousarray(streams, np.int32)
        elems = np.ascontiguousarray(elems, self._dtype)
        if streams.shape != elems.shape or streams.ndim != 1:
            raise ValueError("streams and elems must be equal-length 1-D")
        if self._weighted != (weights is not None):
            raise ValueError("weights required iff staging is weighted")
        if weights is not None:
            weights = np.ascontiguousarray(weights, np.float32)
            if weights.shape != elems.shape:
                raise ValueError("weights must match elements shape")
        if streams.size and (
            int(streams.min()) < 0 or int(streams.max()) >= self._S
        ):
            # name the offending pair: "out of range" alone is unusable in
            # a 65k-stream interleaved feed
            bad = int(np.argmax((streams < 0) | (streams >= self._S)))
            raise ValueError(
                f"stream id {int(streams[bad])} out of range [0, {self._S}) "
                f"at position {bad} of the interleaved batch"
            )
        if self._lib is not None:
            took = self._lib.rsv_staging_push_interleaved(
                self._handle,
                streams.ctypes.data_as(ctypes.c_void_p),
                elems.ctypes.data_as(ctypes.c_void_p),
                weights.ctypes.data_as(ctypes.c_void_p) if weights is not None else None,
                streams.size,
            )
            if took < 0:
                raise ValueError("invalid push_interleaved arguments")
            return int(took)
        # numpy fallback: stable-sort by stream, then per-present-stream
        # bulk copies (capacity-limited; stop at the first full row to match
        # the native consume-prefix contract)
        n = streams.size
        i = 0
        while i < n:
            s = int(streams[i])
            fill = int(self._fill[s])
            if fill >= self._B:
                break
            j = i
            while j < n and int(streams[j]) == s and fill + (j - i) < self._B:
                j += 1
            take = j - i
            self._buf[s, fill : fill + take] = elems[i:j]
            if weights is not None:
                self._wbuf[s, fill : fill + take] = weights[i:j]
            self._fill[s] += take
            i = j
        return i

    # ----------------------------------------------------------------- drain

    def any_full(self) -> bool:
        if self._lib is not None:
            return bool(self._lib.rsv_staging_any_full(self._handle))
        return bool(np.any(self._fill >= self._B))

    def row_full(self, stream: int) -> bool:
        """O(1) flush-due check for one row (the single-stream push path —
        ``any_full`` is an O(S) scan)."""
        if self._lib is not None:
            return self._lib.rsv_staging_fill(self._handle, int(stream)) >= self._B
        return int(self._fill[stream]) >= self._B

    def fill(self, stream: int) -> int:
        """O(1) staged-element count of one row.  The skip gate's push
        fast path (ISSUE 8) requires an EMPTY row — staged residue would
        put the host replica behind the row's true stream position."""
        if self._lib is not None:
            return int(self._lib.rsv_staging_fill(self._handle, int(stream)))
        return int(self._fill[stream])

    def drain(self, out_tile: np.ndarray, out_valid: np.ndarray,
              out_weights: Optional[np.ndarray] = None) -> int:
        """Copy staged rows + fill counts into caller buffers and reset;
        returns total staged elements."""
        _faults.fire("native.staging")
        # explicit raises, not asserts: these guard raw C memcpys and must
        # survive python -O
        if out_tile.shape != (self._S, self._B) or out_tile.dtype != self._dtype:
            raise ValueError(
                f"out_tile must be [{self._S}, {self._B}] {self._dtype}"
            )
        if out_valid.shape != (self._S,) or out_valid.dtype != np.int32:
            raise ValueError(f"out_valid must be [{self._S}] int32")
        if not (out_tile.flags["C_CONTIGUOUS"] and out_valid.flags["C_CONTIGUOUS"]):
            raise ValueError("drain buffers must be C-contiguous")
        if out_weights is not None and not (
            out_weights.flags["C_CONTIGUOUS"]
            and out_weights.shape == (self._S, self._B)
            and out_weights.dtype == np.float32
        ):
            raise ValueError(
                f"out_weights must be C-contiguous [{self._S}, {self._B}] float32"
            )
        if self._weighted != (out_weights is not None):
            raise ValueError("out_weights required iff staging is weighted")
        if self._lib is not None:
            total = self._lib.rsv_staging_drain(
                self._handle,
                out_tile.ctypes.data_as(ctypes.c_void_p),
                out_weights.ctypes.data_as(ctypes.c_void_p)
                if out_weights is not None
                else None,
                out_valid.ctypes.data_as(ctypes.c_void_p),
            )
            if total < 0:
                raise ValueError("invalid drain arguments")
            return int(total)
        out_tile[...] = self._buf
        if out_weights is not None:
            out_weights[...] = self._wbuf
        out_valid[...] = self._fill
        total = int(self._fill.sum())
        self._fill[:] = 0
        return total

    def __del__(self) -> None:
        lib, handle = getattr(self, "_lib", None), getattr(self, "_handle", None)
        if lib is not None and handle:
            lib.rsv_staging_destroy(handle)
