"""CPU semantic oracle for weighted reservoir sampling (A-ES / A-ExpJ).

Capability beyond the reference (SURVEY §6, BASELINE config 4): single-pass
sampling of k items where each item's inclusion is biased by a positive
weight, per Efraimidis & Spirakis — item i gets key ``u_i^(1/w_i)``; the
sample is the k largest keys ("A-ES").  The exponential-jumps variant
("A-ExpJ") skips over items whose cumulative weight is below a drawn
threshold, touching only O(k log(n/k)) items in expectation — the weighted
analog of Algorithm L's skip structure.

Two oracles:

- :class:`NaiveWeightedOracle` — materializes every key, exact by
  construction; the distributional ground truth.
- :class:`AExpJOracle` — the streaming jump algorithm whose behavior the
  device kernel (:mod:`reservoir_tpu.ops.weighted`) reproduces.

Keys are kept in log-space (``lkey = log(u)/w``) so huge streams don't
underflow — same design as the Algorithm-L ``W`` (SURVEY §7.3).
"""

from __future__ import annotations

import heapq
import math
from typing import Any, Iterable, List, Optional, Tuple

import numpy as np

from ..config import validate_max_sample_size

__all__ = ["NaiveWeightedOracle", "AExpJOracle"]


class NaiveWeightedOracle:
    """Exact A-ES: assign every item ``lkey = log(u)/w``, keep top k."""

    def __init__(self, k: int, rng: np.random.Generator) -> None:
        self._k = validate_max_sample_size(int(k))
        self._rng = rng
        self._heap: List[Tuple[float, int, Any]] = []  # (lkey, tie, value)
        self._tie = 0
        self._count = 0

    def sample(self, element: Any, weight: float) -> None:
        if weight < 0:
            raise ValueError(f"weights must be >= 0, got {weight}")
        self._count += 1
        if weight == 0:
            return  # zero-weight items are never sampled
        u = 1.0 - self._rng.random()
        lkey = math.log(u) / weight
        self._tie += 1
        if len(self._heap) < self._k:
            heapq.heappush(self._heap, (lkey, self._tie, element))
        elif lkey > self._heap[0][0]:
            heapq.heapreplace(self._heap, (lkey, self._tie, element))

    def sample_all(self, pairs: Iterable[Tuple[Any, float]]) -> None:
        for element, weight in pairs:
            self.sample(element, weight)

    def result(self) -> List[Any]:
        return [v for (_lk, _t, v) in sorted(self._heap, reverse=True)]


class AExpJOracle:
    """Streaming A-ExpJ with exponential jumps.

    Distributionally identical to :class:`NaiveWeightedOracle` (same key
    construction), but only draws RNG on accepted items: between acceptances
    it skips items until their cumulative weight exceeds a drawn amount
    ``Xw = log(r)/log(T)`` (T = current threshold key), then gives the
    crossing item a key conditioned to beat the threshold:
    ``key = r2^(1/w)`` with ``r2 ~ U(T^w, 1)``.
    """

    def __init__(self, k: int, rng: np.random.Generator) -> None:
        self._k = validate_max_sample_size(int(k))
        self._rng = rng
        self._heap: List[Tuple[float, int, Any]] = []
        self._tie = 0
        self._count = 0
        self._xw: Optional[float] = None  # remaining weight to skip

    def _draw_xw(self) -> float:
        # log(r)/log(T) in log-space: lT = heap min lkey (negative)
        r = 1.0 - self._rng.random()
        lt = self._heap[0][0]
        if lt == 0.0:  # threshold key is 1: nothing can beat it via U(t,1)
            return math.inf
        return math.log(r) / lt

    def _accept(self, element: Any, weight: float) -> None:
        """Accept the jump-crossing item: key conditioned into (T^w, 1),
        then redraw the jump."""
        lt = self._heap[0][0]
        t_w = math.exp(weight * lt)
        r2 = t_w + (1.0 - self._rng.random()) * (1.0 - t_w)
        lkey = math.log(r2) / weight
        self._tie += 1
        heapq.heapreplace(self._heap, (lkey, self._tie, element))
        self._xw = self._draw_xw()

    def _fill(self, element: Any, weight: float) -> None:
        u = 1.0 - self._rng.random()
        self._tie += 1
        heapq.heappush(self._heap, (math.log(u) / weight, self._tie, element))
        if len(self._heap) == self._k:
            self._xw = self._draw_xw()

    def sample(self, element: Any, weight: float) -> None:
        if weight < 0:
            raise ValueError(f"weights must be >= 0, got {weight}")
        self._count += 1
        if weight == 0:
            return
        if len(self._heap) < self._k:
            self._fill(element, weight)
            return
        self._xw -= weight
        if self._xw <= 0:
            self._accept(element, weight)

    def sample_all(self, pairs: Iterable[Tuple[Any, float]]) -> None:
        for element, weight in pairs:
            self.sample(element, weight)

    def sample_all_arrays(self, elements: np.ndarray, weights: np.ndarray) -> None:
        """Bulk path over parallel arrays — identical results to per-element
        calls by construction: ``np.subtract.accumulate`` (float64) replays
        the exact sequential ``xw -= w`` chain, so jump crossings land on
        the same items and RNG draws happen in the same order; the segments
        between accepts are traversed once at C speed (the weighted analog
        of the skip-jump bulk path, ``Sampler.scala:261-287``)."""
        elements = np.asarray(elements)
        weights = np.asarray(weights, np.float64)
        if weights.shape != elements.shape or elements.ndim != 1:
            raise ValueError("elements and weights must be matching 1-D arrays")
        if not np.all(weights >= 0):  # also rejects NaN (min() would not)
            raise ValueError(
                "weights must be >= 0 (and not NaN); got "
                f"min {float(weights.min()) if weights.size else 0}"
            )
        n = elements.shape[0]
        off = 0
        # fill phase: per-element until the heap holds k positive items
        while len(self._heap) < self._k and off < n:
            self._count += 1
            w = float(weights[off])
            if w > 0:
                self._fill(elements[off], w)
            off += 1
        chunk = 8192  # bounds per-accept re-accumulation to O(chunk)
        while off < n:
            end = min(off + chunk, n)
            # replay xw - w[off] - w[off+1] - ... exactly (sequential
            # float64 accumulate); crossing = first partial <= 0
            acc = np.subtract.accumulate(
                np.concatenate(([self._xw], weights[off:end]))
            )[1:]
            crossed = np.nonzero(acc <= 0.0)[0]
            if crossed.size == 0:
                self._count += end - off
                self._xw = float(acc[-1])
                off = end
                continue
            j = off + int(crossed[0])
            self._count += j - off + 1
            self._accept(elements[j], float(weights[j]))
            off = j + 1

    @property
    def count(self) -> int:
        return self._count

    def result(self) -> List[Any]:
        return [v for (_lk, _t, v) in sorted(self._heap, reverse=True)]
