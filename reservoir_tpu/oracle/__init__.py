"""CPU semantic oracles (SURVEY §7.2 M0).

Plain-Python re-derivations of the reference's two sampling engines — the
statistical ground truth for the device kernels and the CPU baseline of
BASELINE.md config 1.
"""

from .algorithm_l import AlgorithmLOracle
from .bottom_k import BottomKOracle

__all__ = ["AlgorithmLOracle", "BottomKOracle"]
