"""CPU semantic oracle for Algorithm-L reservoir sampling (duplicates mode).

This is a from-scratch re-derivation of the *behavior* of the reference's
``RandomElements`` engine (``Sampler.scala:196-332``) — per-element Algorithm L
with geometric skip counts — used as the statistical oracle for the device
kernels and as the CPU baseline (BASELINE.md config 1).  The semantics live
in plain Python (clarity over speed); int64-array and modest-range feeds
additionally ride a bit-identical native C scan (see the draw-order notes
below), so being the oracle costs nothing at benchmark scale.

Algorithm L ("An optimal algorithm", Li 1994; referenced by the reference at
``Sampler.scala:227``):

- fill the reservoir with the first ``k`` elements in arrival order
  (invariant 1, ``Sampler.scala:253-255``);
- afterwards keep a running weight ``W`` and an absolute index ``next`` of the
  next accepted element; each acceptance overwrites a uniformly random slot
  (invariant 2, ``Sampler.scala:243-246``) and re-draws ``W``/``next``:
  ``W *= u1**(1/k)``; ``next += floor(log(u2)/log(1-W)) + 1``
  (``Sampler.scala:228-236``).

Elements between acceptances cost one counter bump and one compare — the bulk
paths (:meth:`AlgorithmLOracle.sample_all`) skip them without touching them at
all (no ``map``, no RNG), mirroring ``sampleIndexed``/``sampleIterator``
(``Sampler.scala:261-287``).

RNG is an explicit constructor input (``numpy.random.Generator``), which is the
lesson the reference's own tests teach by counterexample: they must reach into
private fields by reflection to force RNG state (``SamplerTest.scala:16-54``).
Draw-order contract (shared by the per-element and bulk paths, so the
``sample == sample_all`` invariant 4 of SURVEY §2.2 holds by construction):

1. at construction: ``u1, u2`` for the initial ``W``/``next``;
2. at each acceptance: ``slot = floor(next_double * k)``, then ``u1, u2``.

The slot draw is a scaled ``next_double`` rather than ``Generator.integers``
so the native bulk scan (``_native/algl_scan.cc``) can replay the identical
stream through the BitGenerator's ``next_double`` pointer alone; the
truncation bias is ~2^-53 per draw, far below the 64-bit-hash bias class the
distinct mode already documents.  Int64-array inputs to :meth:`sample_all`
take that C scan when the native library is available (bit-identical
results, ~30x the throughput); everything else runs the plain-Python loop.

``W`` is tracked in log-space so that ``n ~ 1e12``-scale streams do not
underflow (SURVEY §7.3 "Float W in log-space").
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from ..config import validate_max_sample_size

__all__ = ["AlgorithmLOracle"]


class AlgorithmLOracle:
    """Single-stream Algorithm-L reservoir sampler (duplicates allowed).

    Semantics match the reference engine ``RandomElements``
    (``Sampler.scala:196-332``); lifecycle (single-use/reusable) is layered on
    top by :mod:`reservoir_tpu.api`.

    Args:
      k: reservoir capacity (``maxSampleSize``).
      rng: explicit RNG (``numpy.random.Generator``).
      map_fn: ``A => B`` applied on *accept* — it may be called more than ``k``
        times because accepted elements can later be evicted (doc contract at
        ``Sampler.scala:116``; invariant 5).
      pre_allocate: allocate the full ``k``-slot buffer up front instead of
        growing geometrically from 16 (``Sampler.scala:200-202, 210-222``).
        Behaviorally invisible; exposed for API parity.
    """

    def __init__(
        self,
        k: int,
        rng: np.random.Generator,
        map_fn: Optional[Callable[[Any], Any]] = None,
        pre_allocate: bool = False,
    ) -> None:
        self._k = validate_max_sample_size(int(k))
        self._rng = rng
        self._identity_map = map_fn is None
        self._map = map_fn if map_fn is not None else lambda x: x
        # Growable buffer semantics (Sampler.scala:200-222).  A Python list
        # already grows geometrically, so `pre_allocate` is accepted for API
        # parity but is behaviorally invisible (as in the reference — it only
        # trades allocation pattern, never results).  We deliberately do NOT
        # allocate k slots eagerly: k = MAX_SIZE is legal at construction
        # (Sampler.scala:71) and must not commit ~17GB before any element
        # arrives.  Device engines always pre-allocate (XLA static shapes).
        self._samples: List[Any] = []
        self._pre_allocate = pre_allocate
        self._aliased = False  # a result_view() holds our live list
        self._count: int = 0
        self._log_w: float = 0.0
        self._next: int = self._k  # absolute 1-based index of next acceptance
        self._advance()

    # -- Algorithm L skip computation (Sampler.scala:228-236) ----------------

    def _advance(self) -> None:
        """Redraw ``W`` and the absolute index of the next acceptance."""
        u1 = 1.0 - self._rng.random()  # (0, 1]
        u2 = 1.0 - self._rng.random()
        self._log_w += math.log(u1) / self._k
        w = math.exp(self._log_w)
        # log1p(-w) is exact for tiny w; w==1 gives -inf -> skip 0.
        denom = math.log1p(-w) if w < 1.0 else -math.inf
        if denom == -math.inf:
            skip = 0
        else:
            skip = math.floor(math.log(u2) / denom)
        self._next += skip + 1

    def _evict(self, element: Any) -> None:
        """Overwrite a uniformly random slot (``Sampler.scala:243-246``).

        Scaled ``random()`` rather than ``integers()`` so the draw is one
        ``next_double`` — replayable by the native scan (module docs)."""
        if self._aliased:
            self._ensure_unaliased()
        slot = int(self._rng.random() * self._k)
        self._samples[slot] = self._map(element)
        self._advance()

    def _append(self, element: Any) -> None:
        if self._aliased:
            self._ensure_unaliased()
        self._samples.append(self._map(element))

    def _ensure_unaliased(self) -> None:
        """Copy-on-write (``ensureUnaliased``, ``Sampler.scala:357-365``):
        an outstanding :meth:`result_view` holds the live list — copy before
        the first mutation so the view stays a stable snapshot."""
        self._samples = list(self._samples)
        self._aliased = False

    # -- public per-element / bulk API ---------------------------------------

    @property
    def count(self) -> int:
        return self._count

    @property
    def k(self) -> int:
        return self._k

    def sample(self, element: Any) -> None:
        """Per-element hot path (``Sampler.scala:248-259``)."""
        self._count += 1
        if self._count <= self._k:
            self._append(element)
        elif self._count >= self._next:
            self._evict(element)

    def sample_all(self, elements: Iterable[Any]) -> None:
        """Bulk path: skipped elements are never touched.

        Mirrors ``sampleAllImpl`` dispatch (``Sampler.scala:289-316``):
        random-access sequences use index jumping (``sampleIndexed``,
        ``:261-273``); other iterables use iterator-dropping
        (``sampleIterator``, ``:275-287``).  Produces results identical to a
        per-element loop under the same RNG state (invariant 4; tested).
        """
        if isinstance(elements, range) and self._sample_range(elements):
            return
        if isinstance(elements, (Sequence, np.ndarray)) and not isinstance(
            elements, (str, bytes)
        ):
            self._sample_indexed(elements)
        else:
            self._sample_iterator(iter(elements))

    # Materializing a range only beats the lazy skip-jump while the O(n)
    # arange cost stays under the O(k log n) Python acceptance cost; past
    # ~8M elements the lazy path is faster AND stays O(k) memory (a
    # range(10**10) must never allocate 80 GB).
    _RANGE_MATERIALIZE_CAP = 1 << 23

    def _coerce_samples_int64(self) -> Optional[np.ndarray]:
        """The resident samples as an int64 array, or None when they are
        not exactly int64-typed (floats/bools/strings must never be
        coerced — the shared gate for both native-scan entry points)."""
        try:
            s = np.asarray(self._samples)
        except (TypeError, ValueError, OverflowError):
            return None
        return s if s.dtype == np.int64 else None

    def _sample_range(self, r: range) -> bool:
        """Materialize a modest range as int64 and ride the native scan —
        the BASELINE config-1 "1M-element Iterator" shape.  Results stay
        plain Python ints.  False -> caller runs the ordinary (lazy) path;
        every precondition is checked *before* any state mutation so the
        fallback replays from an untouched sampler."""
        # gate on the POST-FILL remainder: elements the fill phase will
        # consume cannot reach the C scan, and a mostly-fill range would
        # materialize for nothing
        remainder = len(r) - max(0, self._k - self._count)
        if not (512 < remainder and len(r) <= self._RANGE_MATERIALIZE_CAP):
            return False
        if not self._identity_map:
            return False  # map_fn expects the range's plain ints
        from .. import native as _native

        if _native.load_library() is None:
            # no C scan: the lazy range path is strictly better (and keeps
            # storing plain ints, which the ndarray loop would not)
            return False
        # cheap pre-gate so a refusal never pays the arange; the scan
        # itself re-derives the array post-fill (_try_native_scan), which
        # is unavoidable — fill appends between these two points
        if self._samples and self._coerce_samples_int64() is None:
            return False
        try:
            arr = np.arange(r.start, r.stop, r.step, dtype=np.int64)
        except (OverflowError, MemoryError):
            return False  # out-of-int64 bounds or no memory: stay lazy
        if arr.size != len(r):
            return False
        self._sample_indexed(arr, as_python_int=True)
        return True

    def _sample_indexed(
        self, seq: Sequence[Any], as_python_int: bool = False
    ) -> None:
        n = len(seq)
        i = 0
        # fill phase
        while self._count < self._k and i < n:
            self._count += 1
            elem = seq[i]
            self._append(int(elem) if as_python_int else elem)
            i += 1
        # native fast path: the same skip-jump loop in C, drawing from the
        # same numpy bit stream — bit-identical results (module docs)
        if (
            n - i > 512
            and self._identity_map
            # exact-type gate: ndarray *subclasses* (np.ma.MaskedArray,
            # np.matrix) override __getitem__ semantics the raw-buffer C
            # scan would ignore — they keep the Python path (ADVICE r2)
            and type(seq) is np.ndarray
            and seq.ndim == 1
            and seq.dtype == np.int64
            and self._try_native_scan(seq, i, n, as_python_int)
        ):
            return
        # skip-jump phase: land directly on acceptance indices.
        # seq[i] has absolute stream index count+1, so the next acceptance
        # (absolute index `next`) sits at offset i + (next - count) - 1.
        while True:
            target = i + (self._next - self._count) - 1
            if target >= n:
                self._count += n - i
                return
            self._count += target - i + 1
            i = target + 1
            elem = seq[target]
            self._evict(int(elem) if as_python_int else elem)

    def _try_native_scan(
        self, seq: np.ndarray, i: int, n: int, as_python_int: bool = False
    ) -> bool:
        """Run the C scan over ``seq[i:]``; False -> caller uses the Python
        loop (native unavailable, or samples not int64-coercible)."""
        from .. import native as _native

        if self._aliased:
            self._ensure_unaliased()
        # int64-exact resident samples only: coercion would silently
        # truncate float/bool/str samples held from earlier calls
        samples = self._coerce_samples_int64()
        if samples is None or samples.shape != (self._k,):
            return False
        res = _native.algl_scan(
            self._rng,
            np.ascontiguousarray(seq[i:]),
            self._k,
            samples,
            self._count,
            self._next,
            self._log_w,
        )
        if res is None:
            return False
        self._count, self._next, self._log_w = res
        # range inputs deliver plain ints (what the Python path stores)
        self._samples = (
            [int(v) for v in samples] if as_python_int else list(samples)
        )
        return True

    def _sample_iterator(self, it: Iterator[Any]) -> None:
        while True:
            skip = self._next - self._count - 1
            if self._count < self._k:
                # fill phase consumes elements one by one
                try:
                    elem = next(it)
                except StopIteration:
                    return
                self._count += 1
                self._append(elem)
                continue
            # drop `skip` elements without touching them
            consumed = _drop(it, skip)
            self._count += consumed
            if consumed < skip:
                return
            try:
                elem = next(it)
            except StopIteration:
                return
            self._count += 1
            self._evict(elem)

    def result(self) -> List[Any]:
        """Current sample; fewer than ``k`` seen -> all of them, in arrival
        order (truncation, ``Sampler.scala:318-331``).  Always a fresh list."""
        size = min(self._count, self._k)
        return list(self._samples[:size])

    def result_view(self) -> List[Any]:
        """Zero-copy result with copy-on-write protection — the reusable
        aliasing optimization of ``MultiResultRandomElements``
        (``Sampler.scala:353-381``): when the buffer holds exactly the sample
        (the steady-state common case), return the *live* list and mark it
        aliased; the next mutation copies first, so the view is a stable
        snapshot.  Callers must treat the returned list as immutable (the
        reference returns an immutable wrapper over the live array)."""
        size = min(self._count, self._k)
        if size == len(self._samples):
            self._aliased = True
            return self._samples
        return list(self._samples[:size])


def _drop(it: Iterator[Any], n: int) -> int:
    """Advance ``it`` by up to ``n`` elements; return how many were consumed."""
    count = 0
    for _ in itertools.islice(it, n):
        count += 1
    return count
