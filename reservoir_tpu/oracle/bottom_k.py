"""CPU semantic oracle for distinct-value sampling (salted bottom-k hashing).

Re-derivation of the reference's ``RandomValues`` engine
(``Sampler.scala:383-412``): keep the ``k`` *distinct* values whose salted
64-bit scrambled hashes are smallest.  Every distinct value then has uniform
inclusion probability k/D (D = number of distinct values), because the
scramble induces an independent uniform random order on values
(``Sampler.scala:16-17`` doc contract; bias only from 64-bit collisions).

Structure mirrors the reference hot path (``Sampler.scala:394-408``):

- a max-heap of (hash, value) keyed on hash — the current bottom-k, with the
  *largest* retained hash on top;
- a membership set of values for O(1) dedup;
- a cached ``max_hash`` threshold so the common case (hash above threshold) is
  one compare + one set lookup.

Unlike duplicates mode, ``map`` is applied to *every* element (it feeds the
hash; ``Sampler.scala:155, 395``).  The hash/scramble is the shared
integer-only spec in :mod:`reservoir_tpu.ops.hashing`, so this oracle is
bit-compatible with the device kernel.
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, Iterable, List, Optional, Set, Tuple

import numpy as np

from ..config import validate_max_sample_size
from ..ops.hashing import draw_salts, scramble64_array, scramble64_int

__all__ = ["BottomKOracle"]

_U64 = (1 << 64) - 1


_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3


def _fnv(data: bytes, h: int = _FNV_OFFSET) -> int:
    """FNV-1a 64-bit over ``data``, continuing from state ``h``."""
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _U64
    return h


def _default_hash(value: Any) -> int:
    """Default user hash as a stable 64-bit pattern.

    The reference's default is ``_.hashCode().toLong`` — defined for EVERY
    object (``Sampler.scala:75``).  This mirrors that contract for every
    *stable* Python hashable (VERDICT r2 item 6): identity embedding for
    ints (device-kernel parity), canonical-serialization FNV-1a for the
    rest, recursing through containers.  Deliberately *not* Python's
    builtin ``hash()``, which is salted per process and would break
    cross-process reproducibility.

    Consistency with equality (the membership set dedups by ``==``):
    numerically equal ints/bools/floats hash identically (``True == 1 ==
    1.0`` all take the integer embedding), and equal tuples/frozensets
    hash identically by recursion.  Only types with no canonical stable
    serialization (arbitrary objects, whose ``hash()`` is id-based or
    process-salted) are refused — pass ``hash_fn=`` for those.
    """
    # bool is an int subclass, and np.bool_ is neither np.integer nor
    # np.floating — all must share the int embedding (True == 1 == 1.0
    # == np.True_ and == values must hash equal)
    if isinstance(value, (int, np.integer, np.bool_)):
        return int(value) & _U64
    if isinstance(value, (float, np.floating)):
        f = float(value)
        if f.is_integer():
            return int(f) & _U64  # 1.0 == 1: same embedding as the int
        import struct

        return _fnv(b"f" + struct.pack(">d", f))
    if value is None:
        return _fnv(b"N")
    if isinstance(value, str):
        # domain-separated from bytes: 'a' != b'a' must not collide
        # (ADVICE r3 #2), matching the b"f"/b"N"/b"T"/b"S" prefixes
        return _fnv(b"s" + value.encode("utf-8"))
    if isinstance(value, (bytes, bytearray)):
        return _fnv(b"b" + bytes(value))
    if isinstance(value, tuple):
        h = _fnv(b"T")
        for item in value:
            h = _fnv(_default_hash(item).to_bytes(8, "big"), h)
        return h
    if isinstance(value, frozenset):
        # order-independent canonical form: sort the element hashes
        h = _fnv(b"S")
        for eh in sorted(_default_hash(item) for item in value):
            h = _fnv(eh.to_bytes(8, "big"), h)
        return h
    raise TypeError(
        f"no stable default hash for {type(value).__name__} (its hash() is "
        "process-salted or id-based, which would break reproducibility); "
        "pass hash_fn="
    )


class BottomKOracle:
    """Single-stream distinct-value sampler (bottom-k min-hashing)."""

    def __init__(
        self,
        k: int,
        rng: np.random.Generator,
        map_fn: Optional[Callable[[Any], Any]] = None,
        hash_fn: Optional[Callable[[Any], int]] = None,
        salts: Optional[Tuple[int, int]] = None,
    ) -> None:
        self._k = validate_max_sample_size(int(k))
        self._mapped = map_fn is not None  # gates the vectorized bulk path
        self._map = map_fn if map_fn is not None else lambda x: x
        self._hash = hash_fn if hash_fn is not None else _default_hash
        # Per-instance salts drawn once (Sampler.scala:385-388); injectable
        # for determinism tests (no reflection needed).
        self._salts = salts if salts is not None else draw_salts(rng)
        # Max-heap via negated hash (heapq is a min-heap).
        self._heap: List[Tuple[int, int, Any]] = []  # (-hash, tiebreak, value)
        self._members: Set[Any] = set()
        self._max_hash: int = -1  # threshold; -1 while not full
        self._tie = 0  # monotonic tiebreaker so values never get compared
        self._count = 0

    @property
    def count(self) -> int:
        return self._count

    def _scrambled(self, element: Any) -> Tuple[Any, int]:
        value = self._map(element)  # applied to EVERY element (Sampler.scala:395)
        return value, scramble64_int(self._hash(value), self._salts)

    def sample(self, element: Any) -> None:
        """Per-element hot path (``Sampler.scala:394-408``)."""
        self._count += 1
        value, h = self._scrambled(element)
        self._insert(value, h)

    def sample_all(self, elements: Iterable[Any]) -> None:
        """Bulk path.  Integer arrays with the default map/hash take a
        vectorized route (the ``sampleAll`` fast-path analog,
        ``Sampler.scala:261-287``): hashes are scrambled array-at-once and
        the Python loop touches only fill-phase and below-threshold
        candidates — identical results to per-element calls by construction
        (same hashes, same arrival order)."""
        if (
            # exact type: ndarray subclasses (MaskedArray) keep the loop
            type(elements) is np.ndarray
            and elements.ndim == 1
            and elements.dtype.kind in "iu"
            and elements.dtype.itemsize <= 8
            and self._hash is _default_hash
            and not self._mapped
            # mixed-type streams (per-element str calls interleaved with int
            # arrays) can't round-trip members through a numpy array
            and all(
                isinstance(v, (int, np.integer)) for v in self._members
            )
        ):
            self._sample_all_fast(elements)
        else:
            for element in elements:
                self.sample(element)

    def _as_bits64(self, arr: np.ndarray) -> np.ndarray:
        """The stream as int64 bit patterns — sign-extended for signed
        dtypes, zero-extended for unsigned (the ``int(v) & 2^64-1``
        embedding of :func:`_default_hash`)."""
        if arr.dtype == np.uint64:
            return arr.view(np.int64)
        return arr.astype(np.int64, copy=False)

    def _native_scan(self, arr: np.ndarray) -> bool:
        """Full-stream scan in the C helper (scramble + threshold compare
        per element, binary-search insert on the rare accepts).  Returns
        False when unavailable — caller falls back to the numpy path.
        Selection is identical to per-element processing (dedup by
        (hash, value-bits)); only hash-tie ordering between distinct values
        (~2^-64 per pair) can differ."""
        import ctypes

        from ..native import load_library

        lib = load_library()
        if lib is None or not hasattr(lib, "rsv_bottomk_scan"):
            return False
        member_dtype = np.uint64 if arr.dtype.kind == "u" else np.int64
        members = self._member_array(member_dtype)
        if members is None:
            return False  # some member doesn't fit this dtype's bit view
        # serialize (hash, value) sorted by hash ascending
        entries = sorted((-nh, v) for (nh, _t, v) in self._heap)
        entry_hash = np.full(self._k, np.iinfo(np.uint64).max, np.uint64)
        entry_val = np.zeros(self._k, np.int64)
        size = len(entries)
        for i, (h, v) in enumerate(entries):
            entry_hash[i] = h
            entry_val[i] = np.asarray(v, member_dtype).view(np.int64)
        bits = np.ascontiguousarray(self._as_bits64(arr))
        size_c = ctypes.c_int32(size)
        rc = lib.rsv_bottomk_scan(
            bits.ctypes.data_as(ctypes.c_void_p),
            bits.shape[0],
            ctypes.c_uint64(self._salts[0]),
            ctypes.c_uint64(self._salts[1]),
            entry_hash.ctypes.data_as(ctypes.c_void_p),
            entry_val.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(size_c),
            self._k,
        )
        if rc < 0:
            return False
        self._count += int(bits.shape[0])
        new_size = int(size_c.value)
        vals = entry_val[:new_size].view(member_dtype)
        self._heap = []
        self._members = set()
        for i in range(new_size):
            v = int(vals[i])
            self._tie += 1
            self._heap.append((-int(entry_hash[i]), self._tie, v))
            self._members.add(v)
        heapq.heapify(self._heap)
        # sorted ascending: the last entry is the max retained hash
        self._max_hash = int(entry_hash[new_size - 1]) if new_size else -1
        return True

    def _sample_all_fast(self, arr: np.ndarray) -> None:
        """Chunked vectorized scan.  Exactness rests on two properties of
        bottom-k: the threshold only ever *tightens*, so a vectorized
        below-threshold prefilter against the chunk-entry threshold is a
        complete candidate superset; and the retained set is insertion-order
        independent (it is "the k smallest distinct scrambled hashes so
        far"), so candidates may be processed hash-ascending rather than in
        arrival order.  Each chunk: prefilter, dedup (a value determines its
        hash, so ``np.unique`` on values dedups hash-consistently), drop
        existing members, then insert hash-ascending with an early break at
        the live threshold.  Chunks grow geometrically: as the threshold
        tightens, ever-larger spans are disposed of by one array compare.

        The native C scan (when available) subsumes this whole routine at
        pointer-walk speed; it is tried first."""
        if self._native_scan(arr):
            return
        hashes = scramble64_array(arr, self._salts)
        n = arr.shape[0]
        off = 0
        # fill phase: per-element until the heap holds k distinct values
        while len(self._heap) < self._k and off < n:
            self._count += 1
            self._insert(int(arr[off]), int(hashes[off]))
            off += 1
        chunk = 4 * self._k
        member_arr: Optional[np.ndarray] = None
        while off < n:
            end = min(off + chunk, n)
            self._count += end - off
            cand = np.nonzero(
                hashes[off:end] < np.uint64(self._max_hash)
            )[0]
            if cand.size:
                uvals, first = np.unique(arr[off:end][cand], return_index=True)
                uhash = hashes[off:end][cand][first]
                if member_arr is None:
                    member_arr = self._member_array(arr.dtype)
                    if member_arr is None:
                        # a member doesn't fit arr.dtype (e.g. a negative
                        # int sampled before a uint64 stream): finish this
                        # call on the exact per-element route
                        self._count -= end - off  # sample() re-counts
                        for j in range(off, n):
                            self.sample(int(arr[j]))
                        return
                fresh = ~np.isin(uvals, member_arr)
                uvals, uhash = uvals[fresh], uhash[fresh]
                order = np.argsort(uhash)
                changed = False
                for i in order:
                    h = int(uhash[i])
                    if h >= self._max_hash:
                        break  # hash-ascending: the rest can't be accepted
                    self._insert(int(uvals[i]), h)
                    changed = True
                if changed:
                    member_arr = self._member_array(arr.dtype)
            off = end
            chunk = min(chunk * 2, 1 << 20)

    def _member_array(self, dtype: np.dtype) -> Optional[np.ndarray]:
        """The membership set as a ``dtype`` array, or None when some member
        is not representable in ``dtype`` (caller must take the per-element
        route — ``np.isin`` against a lossy conversion would be wrong).

        Range-checks explicitly: ``np.fromiter`` raises for out-of-range
        Python ints but silently *wraps* numpy scalars (e.g. ``np.int64(-5)``
        into uint64), which would corrupt the dedup."""
        info = np.iinfo(dtype)
        out = np.empty(len(self._members), dtype=dtype)
        for i, v in enumerate(self._members):
            iv = int(v)
            if iv < info.min or iv > info.max:
                return None
            out[i] = iv
        return out

    def _insert(self, value: Any, h: int) -> None:
        """Heap/membership insert of a pre-scrambled (value, hash) pair —
        the tail of :meth:`sample` after the threshold compare."""
        if len(self._heap) < self._k:
            if value not in self._members:
                self._tie += 1
                heapq.heappush(self._heap, (-h, self._tie, value))
                self._members.add(value)
                self._max_hash = max(self._max_hash, h)
        elif h < self._max_hash and value not in self._members:
            _, _, evicted = heapq.heapreplace(
                self._heap, (-h, self._tie + 1, value)
            )
            self._tie += 1
            self._members.discard(evicted)
            self._members.add(value)
            self._max_hash = -self._heap[0][0]

    def result(self) -> List[Any]:
        """The sampled distinct values.  Order is not specified by the
        contract (``Sampler.scala:411``); we return them sorted by scrambled
        hash so the output is deterministic and directly comparable with the
        device kernel's sorted bottom-k."""
        return [v for (_nh, _t, v) in sorted(self._heap, key=lambda e: -e[0])]

    def threshold(self) -> int:
        """Current max retained hash (testing hook)."""
        return self._max_hash
