"""Structured JSON-lines event log with correlation fields.

The scattered one-shot ``logging`` warnings this plane replaces (ISSUE 6
satellite, via :mod:`reservoir_tpu.utils.log`) could never be correlated:
a demotion on the primary, a fence refusal on the zombie, and a replica
re-bootstrap are one causal chain, but three unstructured strings.  Every
record here is one JSON object per line carrying ``ts``, ``event``, and
whatever correlation fields the site knows — ``flush_seq``, ``session``,
``epoch``, ``site`` — so the chain can be joined offline, exactly the way
``sessions.jsonl`` records are.

Write discipline matches the session journal: append + flush per record
(a process crash loses nothing already written; an OS crash may tear the
final line, which :func:`read_events` tolerates), single ``write()`` call
per record so concurrent emitters interleave at line granularity.

Rate limiting is built in (token bucket, default 200 events/s with an
equal burst): a hot loop cannot turn the event log into the bottleneck it
is meant to observe.  Dropped records are counted per event name and a
``telemetry.dropped`` summary record is written when the storm passes, so
the tail of the log always says what it is missing.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from .registry import get as _registry_get

__all__ = ["EventLog", "read_events"]


class EventLog:
    """Append-only, rate-limited JSON-lines event writer (thread-safe)."""

    def __init__(
        self,
        path: str,
        *,
        rate_limit_hz: float = 200.0,
        burst: Optional[float] = None,
        clock=time.time,
    ) -> None:
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        self.path = path
        self._clock = clock
        self._rate = float(rate_limit_hz)
        self._burst = float(burst) if burst is not None else max(
            1.0, self._rate
        )
        self._tokens = self._burst
        self._last_refill = clock()
        self._dropped: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._fh = open(path, "a", encoding="utf-8")

    @property
    def dropped(self) -> Dict[str, int]:
        """Per-event-name drop counts since the last summary record."""
        with self._lock:
            return dict(self._dropped)

    def _admit_locked(self, event: str, now: float) -> bool:
        """Token-bucket admission (caller holds the lock)."""
        if self._rate <= 0:
            return True
        self._tokens = min(
            self._burst, self._tokens + (now - self._last_refill) * self._rate
        )
        self._last_refill = now
        if self._tokens < 1.0:
            self._dropped[event] = self._dropped.get(event, 0) + 1
            # the live half of the drop accounting (ISSUE 11 satellite):
            # the in-stream telemetry.dropped summary only lands when the
            # storm passes, but an SLO dashboard must see the log lying by
            # omission WHILE it lies — so every drop also increments a
            # registry counter (Counter holds its own lock and never takes
            # this one, so the ordering is cycle-free)
            reg = _registry_get()
            if reg is not None:
                reg.counter("telemetry.dropped").inc()
            return False
        self._tokens -= 1.0
        return True

    def emit(self, event: str, **fields) -> bool:
        """Write one record; returns False when rate-limited (the drop is
        counted and summarized on the next admitted record)."""
        now = self._clock()
        with self._lock:
            if self._fh.closed:
                return False
            if not self._admit_locked(event, now):
                return False
            lines = ""
            if self._dropped:
                lines += json.dumps(
                    {
                        "ts": now,
                        "event": "telemetry.dropped",
                        "counts": self._dropped,
                    },
                    sort_keys=True,
                ) + "\n"
                self._dropped = {}
            record = {"ts": now, "event": event}
            record.update(fields)
            lines += json.dumps(record, sort_keys=True, default=str) + "\n"
            self._fh.write(lines)
            self._fh.flush()
        return True

    def close(self) -> None:
        with self._lock:
            if self._fh.closed:
                return
            if self._dropped:
                # a storm that never subsided before shutdown would lose
                # its drop counts: flush the summary the next admitted
                # record would have carried
                self._fh.write(
                    json.dumps(
                        {
                            "ts": self._clock(),
                            "event": "telemetry.dropped",
                            "counts": self._dropped,
                        },
                        sort_keys=True,
                    )
                    + "\n"
                )
                self._dropped = {}
            self._fh.close()

    def __del__(self) -> None:
        fh = getattr(self, "_fh", None)
        if fh is not None and not fh.closed:
            try:
                fh.close()
            except OSError:
                pass


def read_events(path: str) -> List[dict]:
    """Parse an event log.  A torn FINAL line (crash mid-append) is
    dropped — the same tolerance the session journal extends to its tail;
    corruption anywhere earlier raises (the file did not get that way by
    crashing, and silently skipping records would hide it) naming the
    line number AND the byte offset of the bad record, so ``dd``/``tail
    -c`` can jump straight to it in a multi-gigabyte log."""
    with open(path, "rb") as fh:
        data = fh.read()
    raw_lines = data.split(b"\n")
    if raw_lines and raw_lines[-1] == b"":
        raw_lines.pop()  # the trailing newline of a clean final record
    records: List[dict] = []
    offset = 0
    for i, raw in enumerate(raw_lines):
        line_offset = offset
        offset += len(raw) + 1
        line = raw.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line.decode("utf-8")))
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            if i == len(raw_lines) - 1:
                break  # torn tail: the event it described never landed
            raise ValueError(
                f"{path!r}: corrupt event log at line {i + 1} "
                f"(byte offset {line_offset}): {e}"
            ) from None
    return records
