"""Unified telemetry plane (ISSUE 6): metrics registry, latency
histograms, structured event log, exporters.

The stack below this package measures itself three different ways —
snapshot-only counter dataclasses (:mod:`reservoir_tpu.utils.metrics`),
Perfetto trace spans (:mod:`reservoir_tpu.utils.tracing`), and ad-hoc
bench quantile lists.  This package is the one place they meet:

- :mod:`.registry` — thread-safe named counters/gauges/**log-spaced
  latency histograms** (exact p50/p99/p99.9 readout), module-global
  :func:`enable`/:func:`disable` with the fault plane's zero-overhead-
  when-disabled discipline, and block registration that absorbs the
  released metric dataclasses into every export;
- :mod:`.events` — a rate-limited JSON-lines event log with correlation
  fields (``flush_seq``/``session``/``epoch``/``site``), torn-tail
  tolerant like ``sessions.jsonl``;
- :mod:`.export` — Prometheus text format and an atomic JSON snapshot
  (embedded into ``heartbeat.json`` by the HA plane's
  :class:`~reservoir_tpu.serve.ha.HeartbeatWriter`, tailed live by
  ``tools/reservoir_top.py``);
- :mod:`.slo` — declarative :class:`~reservoir_tpu.obs.slo.SLOSpec`
  objectives (latency quantile, staleness, error rate, sample quality)
  judged by an :class:`~reservoir_tpu.obs.slo.SLOPlane` with
  Google-SRE-style multi-window burn rates — ``ok``/``warn``/``page``
  verdicts riding every export (ISSUE 7);
- :mod:`.audit` — the online
  :class:`~reservoir_tpu.obs.audit.SampleQualityAuditor`: rolling pooled
  KS against the uniform law plus per-stratum inclusion-rate counters,
  feeding the ``sample_quality`` objective so statistical drift pages
  like a latency regression.

Telemetry is **off by default**: every instrumented hot path costs one
module-global load and an ``is None`` test until :func:`enable` is called
(pinned by the trip-wire in ``tests/test_obs.py``)::

    from reservoir_tpu import obs

    reg = obs.enable(event_log_path="/tmp/events.jsonl")
    ...  # run traffic
    print(obs.prometheus_text(reg))
    p50, p99, p999 = reg.histogram("serve.ingest_s").percentiles()
    obs.disable()
"""

from . import flight, trace
from .events import EventLog, read_events
from .export import json_snapshot, prometheus_text, write_json_snapshot
from .flight import FlightRecorder, read_bundle
from .trace import Span, Tracer, attribution
from .registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    active,
    blocks,
    disable,
    emit,
    enable,
    register_block,
)
from .registry import get as get_registry
from .audit import SampleQualityAuditor
from .slo import SLOPlane, SLOSpec, SLOVerdict, default_slos

__all__ = [
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "Registry",
    "EventLog",
    "SLOPlane",
    "SLOSpec",
    "SLOVerdict",
    "SampleQualityAuditor",
    "Span",
    "Tracer",
    "active",
    "attribution",
    "blocks",
    "default_slos",
    "disable",
    "emit",
    "enable",
    "flight",
    "get_registry",
    "json_snapshot",
    "prometheus_text",
    "read_bundle",
    "read_events",
    "register_block",
    "trace",
    "write_json_snapshot",
]
