"""Online sample-quality auditing: the statistical invariant as telemetry.

CI gates sampling correctness once, offline, with big pools
(``tests/test_ks_gate.py`` at the BASELINE 1% KS bound; the reference
gates with 5-sigma frequency tests, ``SamplerTest.scala:144-240``).
Nothing watched production: a biased RNG fold, a demoted kernel with a
subtle acceptance bug, or a recycled row leaking its predecessor's
elements would serve confidently wrong samples while every latency SLO
stayed green.  :class:`SampleQualityAuditor` closes that gap (ISSUE 7
tentpole; "Parallel Streaming Random Sampling", arXiv:1906.04120,
motivates inclusion probability as *the* invariant to watch): a
low-overhead monitor hooked into the service's ingest/snapshot paths that
feeds ``audit.*`` instruments, which the ``sample_quality``
:class:`~reservoir_tpu.obs.slo.SLOSpec` turns into ``ok``/``warn``/``page``
— statistical drift pages exactly like a latency regression.

Two complementary detectors:

- **Rolling pooled KS** — uniform reservoir sampling over a stream of
  *known positions* must yield sample positions uniform on ``[0, n)``.
  Sessions whose elements encode their stream position (the load
  generator's canary traffic does exactly this; any value outside
  ``[0, n)`` is excluded, so opaque production values simply don't feed
  this detector) have their snapshots normalized by their own stream
  length and pooled across sessions; once ``min_pool`` observations
  accumulate, one KS distance against U[0,1) is computed — reusing
  ``ks_one_sample_uniform`` (``utils/stats.py``) with ``n=1``, the exact
  CI formula on the unit interval — and gated at
  ``max(KS_GATE, ks_crit / sqrt(pool))``: the literal 1% BASELINE bound
  whenever the pool is large enough to support it, else the
  finite-sample critical value (``ks_crit`` = 1.95 ~ alpha 0.001, the
  CI analogue of the reference's 5-sigma posture).
- **Per-stratum inclusion-rate counters** — works on *opaque* values:
  every ingested element is bucketed (default ``|value| % strata``) and
  counted; every snapshot's elements are bucketed and counted too.
  Unbiased sampling includes every stratum at the same rate, so the
  maximum relative deviation of per-stratum inclusion rates from their
  pooled mean flags value-correlated bias (a sampler that favors small
  keys, a demoted path dropping a lane) long before the CI gate would
  see it.  Counters decay by half at each check, keeping the window
  rolling.

Overhead discipline: both hooks gate on the telemetry plane's
module-global — with ``obs`` disabled they cost one global load and an
``is None`` test, nothing else (the trip-wire in ``tests/test_obs.py``
pins it, same as the fault plane).  Single-writer, like the service that
owns it.
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional

import numpy as np

from . import registry as _obs

__all__ = ["SampleQualityAuditor"]


class SampleQualityAuditor:
    """Rolling KS + stratum inclusion monitor for a serving plane.

    Attach via ``ReservoirService(..., auditor=SampleQualityAuditor())``;
    the service calls :meth:`record_ingest` after each accepted ingest and
    :meth:`observe_snapshot` after each snapshot read.

    Args:
      min_pool: pooled (position-encoded) observations per KS check.
      ks_crit: finite-sample critical coefficient — the gate is
        ``max(KS_GATE, ks_crit / sqrt(pool))``.
      strata: number of value-hash buckets for the inclusion counters.
      stratum_of: optional ``array -> int array`` bucketing override
        (default ``|value| % strata``).
      min_stratum_count: minimum ingested elements per stratum before a
        stratum check can flag anything (deviation on ten elements is
        noise, not bias).
      stratum_gate: maximum relative deviation of a stratum's inclusion
        rate from the pooled mean before it counts as a breach.
      obs_scope: per-shard instrument label (ISSUE 9): when set, the
        ``audit.*`` instruments are recorded under scoped names
        (``audit.ks_checks@<scope>``) so each shard's auditor feeds its
        own ``sample_quality`` objective (``default_slos(scope=...)``).
    """

    def __init__(
        self,
        *,
        min_pool: int = 512,
        ks_crit: float = 1.95,
        strata: int = 8,
        stratum_of: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        min_stratum_count: int = 256,
        stratum_gate: float = 0.5,
        obs_scope: Optional[str] = None,
    ) -> None:
        if min_pool < 8:
            raise ValueError("min_pool must be at least 8")
        if strata < 2:
            raise ValueError("need at least 2 strata")
        self._min_pool = int(min_pool)
        self._ks_crit = float(ks_crit)
        self._strata = int(strata)
        self._stratum_of = stratum_of
        self._min_stratum = int(min_stratum_count)
        self._stratum_gate = float(stratum_gate)
        self._obs_scope = obs_scope
        self._pool: List[np.ndarray] = []
        self._pool_n = 0
        self._pool_sessions = 0
        self._ingested = np.zeros(strata, dtype=np.int64)
        self._included = np.zeros(strata, dtype=np.int64)
        self.last_ks: Optional[float] = None
        self.last_stratum_dev: Optional[float] = None

    # ----------------------------------------------------------- gated hooks

    def record_ingest(self, key: str, values) -> None:
        """Count one accepted ingest into the stratum ledger.  No-op (one
        global load, one ``is None`` test) while telemetry is disabled."""
        if _obs.get() is None:
            return
        self._record(key, values)

    def observe_snapshot(self, key: str, sample, n: int) -> None:
        """Feed one session snapshot (``n`` = that session's stream
        length so far).  No-op while telemetry is disabled."""
        if _obs.get() is None:
            return
        self._observe(key, sample, int(n))

    # -------------------------------------------------------------- internals

    def _buckets(self, arr: np.ndarray) -> np.ndarray:
        if self._stratum_of is not None:
            return np.asarray(self._stratum_of(arr), dtype=np.int64)
        return np.abs(arr.astype(np.int64, copy=False)) % self._strata

    def _record(self, key: str, values) -> None:
        arr = np.atleast_1d(np.asarray(values))
        if not arr.size:
            return
        self._ingested += np.bincount(
            self._buckets(arr), minlength=self._strata
        )[: self._strata]

    def _observe(self, key: str, sample, n: int) -> None:
        arr = np.atleast_1d(np.asarray(sample))
        if not arr.size or n <= 0:
            return
        self._included += np.bincount(
            self._buckets(arr), minlength=self._strata
        )[: self._strata]
        # position-encoded canary values: normalize by this session's own
        # stream length; anything outside [0, n) is an opaque value and
        # simply does not feed the KS pool
        u = arr.astype(np.float64, copy=False) / float(n)
        u = u[(u >= 0.0) & (u < 1.0)]
        if u.size:
            self._pool.append(u)
            self._pool_n += int(u.size)
            self._pool_sessions += 1
        if self._pool_n >= self._min_pool:
            self._check()

    def _check(self) -> None:
        reg = _obs.get()
        if reg is None:  # disabled mid-stream: drop the pending pool
            self._pool, self._pool_n, self._pool_sessions = [], 0, 0
            return
        from ..utils.stats import KS_GATE, ks_one_sample_uniform

        pooled = np.concatenate(self._pool)
        m = int(pooled.size)
        # n=1: the pool is already on the unit interval, so the shared CI
        # formula computes sup|ECDF - x| against U[0,1) directly
        ks = ks_one_sample_uniform(pooled, 1)
        gate = max(KS_GATE, self._ks_crit / math.sqrt(m))
        self.last_ks = ks
        reg.gauge(_obs.scoped("audit.ks_statistic", self._obs_scope)).set(ks)
        reg.gauge(_obs.scoped("audit.ks_gate", self._obs_scope)).set(gate)
        reg.gauge(_obs.scoped("audit.pool_size", self._obs_scope)).set(m)
        reg.counter(_obs.scoped("audit.ks_checks", self._obs_scope)).inc()
        if ks > gate:
            reg.counter(_obs.scoped("audit.ks_breaches", self._obs_scope)).inc()
            _obs.emit(
                "audit.ks_breach",
                site="obs.audit",
                ks=round(ks, 6),
                gate=round(gate, 6),
                pool=m,
                sessions=self._pool_sessions,
            )
        self._pool, self._pool_n, self._pool_sessions = [], 0, 0
        self._check_strata(reg)

    def _check_strata(self, reg) -> None:
        eligible = self._ingested >= self._min_stratum
        if eligible.sum() < 2 or self._included[eligible].sum() == 0:
            return
        rates = self._included[eligible] / self._ingested[eligible]
        mean = self._included[eligible].sum() / self._ingested[eligible].sum()
        dev = float(np.abs(rates / mean - 1.0).max())
        self.last_stratum_dev = dev
        reg.gauge(_obs.scoped("audit.stratum_dev", self._obs_scope)).set(dev)
        reg.counter(_obs.scoped("audit.stratum_checks", self._obs_scope)).inc()
        if dev > self._stratum_gate:
            worst = int(np.argmax(np.abs(rates / mean - 1.0)))
            reg.counter(_obs.scoped("audit.stratum_breaches", self._obs_scope)).inc()
            _obs.emit(
                "audit.stratum_breach",
                site="obs.audit",
                dev=round(dev, 4),
                gate=self._stratum_gate,
                stratum=int(np.flatnonzero(eligible)[worst]),
            )
        # decay: keep the ledger a rolling window, not an all-time average
        self._ingested //= 2
        self._included //= 2
