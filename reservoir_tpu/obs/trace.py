"""Causal ingest tracing: lightweight spans with head-based sampling.

The telemetry plane (ISSUE 6) aggregates; the SLO plane (ISSUE 7) judges;
neither can say *which stage* of one session's ingest ate a p99.9.  The
existing :mod:`reservoir_tpu.utils.tracing` spans need an attached JAX
profiler capture — exactly what is never running when the interesting
failure happens.  This module is the always-available half (ISSUE 11): a
Dapper-style span record small enough to keep on at production rates.

A :class:`Span` is trace_id/span_id/parent plus a monotonic start, a
duration, a stage tag, and the correlation fields the event log already
standardizes (``shard``/``session``/``flush_seq``/``epoch``) — so a span
tree joins against journal frames and event records offline, with no new
wire format.  Spans follow an ingest end to end: cluster route →
admission → coalesce → gate eval → flush queue → dispatch → journal
append → (via the flush_seq already in journal frames) replica apply and
promote on the standby.

**Head-based sampling**: the keep/drop decision is made once, at the root
(1-in-``sample_every`` by a stable hash of the root key — a session key on
the serve path, the flush seq on the bridge/replica path, so both sides
of a journal frame sample the *same* seqs), and every nested span
inherits it through a per-thread stack.  Error, fence, promotion, and
SLO-page paths force sampling (``force=True``) — the traces worth having
are never the ones the sampler happened to keep.

Activation follows the fault plane's discipline exactly
(:mod:`reservoir_tpu.utils.faults`, :mod:`reservoir_tpu.obs.registry`): a
module-global :func:`enable`/:func:`disable` pair, every instrumented hot
path gating on ``get() is None`` — zero overhead when disabled (one
module-global load, one ``is None`` test; pinned by the trip-wire in
``tests/test_obs.py``).  Tracing is purely observational: journals and
snapshots are byte-identical with tracing on or off.

:func:`attribution` turns the retained spans into the latency report the
ISSUE asks for: per-stage p50/p99 and share of end-to-end ingest wait,
plus the critical path of the worst traces.  ``bench.py``'s ``trace``
stage asserts that report reconciles with the measured end-to-end wait.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

__all__ = [
    "Span",
    "Tracer",
    "enable",
    "disable",
    "active",
    "get",
    "attribution",
]


class Span:
    """One causal span: identity, timing, stage tag, correlation fields.

    ``trace_id``/``span_id``/``parent_id`` are small process-local ints
    (a root span's trace_id is its own span_id); ``start_s`` is the
    tracer's monotonic clock, ``ts`` the wall clock at start (bundles are
    read by humans), ``duration_s`` is filled at end.  ``fields`` carries
    the correlation keys (``session``/``shard``/``flush_seq``/``epoch``/
    ``error``) the site knows."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name",
        "ts", "start_s", "duration_s", "forced", "fields",
    )

    def __init__(
        self,
        trace_id: int,
        span_id: int,
        parent_id: Optional[int],
        name: str,
        ts: float,
        start_s: float,
        *,
        forced: bool = False,
        fields: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.ts = ts
        self.start_s = start_s
        self.duration_s = 0.0
        self.forced = forced
        self.fields = fields if fields is not None else {}

    def to_dict(self) -> dict:
        """The JSON form bundles and the postmortem viewer consume."""
        out = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "ts": self.ts,
            "start_s": self.start_s,
            "duration_s": self.duration_s,
        }
        if self.forced:
            out["forced"] = True
        if self.fields:
            out["fields"] = dict(self.fields)
        return out

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Span({self.name!r}, trace={self.trace_id}, "
            f"dur={self.duration_s:.6f}, {self.fields})"
        )


#: Stack sentinel: an *unsampled* root still pushes this, so nested span
#: sites skip in O(1) without re-deciding (head-based sampling: one
#: decision at the root, inherited everywhere below it on this thread).
_SKIP = object()


class Tracer:
    """Bounded retention of causal spans with head-based sampling.

    Finished spans land in a fixed-size ring (``capacity`` most recent;
    the flight recorder's bounded-memory contract extends here), appended
    under the GIL's deque atomicity — no lock on the hot path.  The
    per-thread span stack makes nesting free at call sites: a nested
    ``span()`` needs no parent argument, and a span opened on the bridge's
    dispatch worker is automatically a root there.

    Args:
      sample_every: keep 1-in-N roots (stable ``crc32`` hash of the root
        key, NOT a counter — the same session/seq samples the same way at
        every site, which is what makes cross-site correlation work).
        ``1`` keeps everything (bench/tests).
      capacity: ring size (spans retained for bundles/attribution).
      clock: monotonic duration clock (injectable for tests).
      wall: wall clock stamped on each span start.
    """

    def __init__(
        self,
        *,
        sample_every: int = 8,
        capacity: int = 4096,
        clock=time.perf_counter,
        wall=time.time,
    ) -> None:
        self._sample_every = max(1, int(sample_every))
        self._spans: deque = deque(maxlen=int(capacity))
        self._ids = itertools.count(1)
        self._clock = clock
        self._wall = wall
        self._local = threading.local()
        self.sampled = 0
        self.skipped = 0
        self.forced = 0

    # ------------------------------------------------------------- sampling

    @property
    def sample_every(self) -> int:
        return self._sample_every

    def sample(self, key: Any) -> bool:
        """The head-based keep/drop decision for root key ``key`` — a
        pure function of the key, so every site agrees on it."""
        n = self._sample_every
        if n <= 1:
            return True
        return zlib.crc32(str(key).encode("utf-8")) % n == 0

    # ---------------------------------------------------------------- spans

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = []
            self._local.stack = st
        return st

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        *,
        key: Any = None,
        force: bool = False,
        **fields: Any,
    ) -> Iterator[Optional[Span]]:
        """Record one stage.  At a root (no enclosing span on this
        thread), ``key`` drives the sampling decision and ``force=True``
        bypasses it (error/fence/promotion paths).  Nested, the decision
        is inherited: under a sampled root this records a child; under an
        unsampled root it skips in O(1).  Yields the live :class:`Span`
        (``None`` when skipping) so the site can attach late fields."""
        st = self._stack()
        parent: Optional[Span] = None
        if st:
            top = st[-1]
            if top is _SKIP and not force:
                st.append(_SKIP)
                try:
                    yield None
                finally:
                    st.pop()
                return
            parent = top if isinstance(top, Span) else None
        if parent is None and not force and not (
            key is not None and self.sample(key)
        ):
            self.skipped += 1
            st.append(_SKIP)
            try:
                yield None
            finally:
                st.pop()
            return
        span_id = next(self._ids)
        span = Span(
            parent.trace_id if parent is not None else span_id,
            span_id,
            parent.span_id if parent is not None else None,
            name,
            self._wall(),
            self._clock(),
            forced=force,
            fields=dict(fields) if fields else {},
        )
        if force:
            self.forced += 1
        else:
            self.sampled += 1
        st.append(span)
        try:
            yield span
        finally:
            st.pop()
            span.duration_s = self._clock() - span.start_s
            self._spans.append(span)

    def point(
        self,
        name: str,
        *,
        force: bool = True,
        detached: bool = False,
        **fields: Any,
    ) -> Span:
        """A zero-duration marker span (reject/fence/kill markers on the
        failover critical path).  Forced by default — markers exist
        precisely because something went wrong.  ``detached=True`` starts
        its own trace even under an open span (markers whose duration
        spans many calls, like the coalesce wait)."""
        st = self._stack()
        parent = (
            None
            if detached
            else (st[-1] if st and isinstance(st[-1], Span) else None)
        )
        span_id = next(self._ids)
        span = Span(
            parent.trace_id if parent is not None else span_id,
            span_id,
            parent.span_id if parent is not None else None,
            name,
            self._wall(),
            self._clock(),
            forced=force,
            fields=dict(fields) if fields else {},
        )
        self.forced += 1
        self._spans.append(span)
        return span

    # -------------------------------------------------------------- readout

    def spans(self) -> List[Span]:
        """The retained spans, oldest first (bounded by ``capacity``)."""
        return list(self._spans)

    def clear(self) -> None:
        self._spans.clear()

    def snapshot(self) -> dict:
        return {
            "sample_every": self._sample_every,
            "capacity": self._spans.maxlen,
            "retained": len(self._spans),
            "sampled": self.sampled,
            "skipped": self.skipped,
            "forced": self.forced,
        }


# ---------------------------------------------------------------- activation

_TRACER: Optional[Tracer] = None


def get() -> Optional[Tracer]:
    """The active tracer, or ``None`` (tracing disabled — the default).
    Hot paths gate on this: one global load, one ``is None`` test."""
    return _TRACER


def enable(tracer: Optional[Tracer] = None, **kwargs: Any) -> Tracer:
    """Activate causal tracing process-wide; returns the active tracer.
    Keyword arguments construct one (``sample_every=``, ``capacity=``)."""
    global _TRACER
    if tracer is None:
        tracer = Tracer(**kwargs)
    _TRACER = tracer
    return tracer


def disable() -> None:
    """Deactivate tracing: every span site reverts to the zero-overhead
    no-op path."""
    global _TRACER
    _TRACER = None


@contextlib.contextmanager
def active(tracer: Optional[Tracer] = None, **kwargs: Any) -> Iterator[Tracer]:
    """``with trace.active(sample_every=1) as tr: ...`` — scoped (tests)."""
    global _TRACER
    prev = _TRACER
    tr = enable(tracer, **kwargs)
    try:
        yield tr
    finally:
        _TRACER = prev


# -------------------------------------------------------------- attribution


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[i]


def attribution(
    spans: Optional[List[Span]] = None,
    *,
    root: str = "serve.ingest",
    worst: int = 3,
) -> dict:
    """Per-stage latency attribution over retained spans.

    Groups spans by trace, keeps traces rooted at a ``root``-named span,
    and attributes each span's **self time** (duration minus its direct
    children's durations — spans nest on one thread, so children tile
    their parent) to its stage tag: total time, p50/p99, and share of
    the summed end-to-end wait.  The root's own self time is reported as
    ``other``.  Self times of a trace partition its end-to-end wait, so
    the stage sums plus ``other`` reconcile with the e2e sum *by
    construction* — exactly what ``bench.py trace`` asserts against its
    independent wall-clock measurement.  ``critical_path`` lists the
    ``worst`` traces by end-to-end wait with their ordered stages and
    correlation fields.
    """
    if spans is None:
        tr = get()
        spans = tr.spans() if tr is not None else []
    by_trace: Dict[int, List[Span]] = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)
    e2e: List[float] = []
    stage_durs: Dict[str, List[float]] = {}
    other_total = 0.0
    traces: List[tuple] = []  # (e2e_s, root_span, children)
    for tid, group in by_trace.items():
        root_span = next((s for s in group if s.name == root), None)
        if root_span is None:
            continue
        children = sorted(
            (s for s in group if s.span_id != root_span.span_id),
            key=lambda s: s.start_s,
        )
        e2e.append(root_span.duration_s)
        child_sum: Dict[int, float] = {}
        for c in children:
            if c.parent_id is not None:
                child_sum[c.parent_id] = (
                    child_sum.get(c.parent_id, 0.0) + c.duration_s
                )
        for c in children:
            self_s = max(
                0.0, c.duration_s - child_sum.get(c.span_id, 0.0)
            )
            stage_durs.setdefault(c.name, []).append(self_s)
        other_total += max(
            0.0,
            root_span.duration_s - child_sum.get(root_span.span_id, 0.0),
        )
        traces.append((root_span.duration_s, root_span, children))
    e2e_sorted = sorted(e2e)
    e2e_sum = sum(e2e)
    stages: Dict[str, dict] = {}
    for name in sorted(stage_durs):
        durs = sorted(stage_durs[name])
        total = sum(durs)
        stages[name] = {
            "count": len(durs),
            "sum_s": total,
            "p50_s": _quantile(durs, 0.5),
            "p99_s": _quantile(durs, 0.99),
            "share": (total / e2e_sum) if e2e_sum else 0.0,
        }
    traces.sort(key=lambda t: t[0], reverse=True)
    critical = []
    for dur, root_span, children in traces[: max(0, int(worst))]:
        critical.append({
            "trace_id": root_span.trace_id,
            "e2e_s": dur,
            "fields": dict(root_span.fields),
            "stages": [
                {
                    "name": c.name,
                    "duration_s": c.duration_s,
                    **{
                        k: v
                        for k, v in c.fields.items()
                        if k in ("session", "shard", "flush_seq", "epoch")
                    },
                }
                for c in children
            ],
        })
    return {
        "root": root,
        "traces": len(e2e),
        "spans": len(spans),
        "e2e_s": {
            "count": len(e2e),
            "sum": e2e_sum,
            "mean": (e2e_sum / len(e2e)) if e2e else 0.0,
            "p50": _quantile(e2e_sorted, 0.5),
            "p99": _quantile(e2e_sorted, 0.99),
        },
        "stages": stages,
        "other": {
            "sum_s": other_total,
            "share": (other_total / e2e_sum) if e2e_sum else 0.0,
        },
        "critical_path": critical,
    }
