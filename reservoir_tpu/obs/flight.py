"""Always-on flight recorder and atomic postmortem bundles.

Aggregate telemetry answers "how bad"; the causal spans
(:mod:`reservoir_tpu.obs.trace`) answer "where"; this module answers the
question every 3am page actually starts with: *what was the cluster doing
in the seconds before it went wrong?*  A :class:`FlightRecorder` is a
fixed-size ring of the most recent structured events and notes — always
on once installed, at bounded memory, appended under the GIL's deque
atomicity (no lock on the record path) — plus :meth:`dump`: one atomic
JSON **postmortem bundle** carrying the span tree, the event tail, the
live instrument snapshot + SLO verdicts, the heartbeat/epoch state, the
journal watermarks, and the recorder's config.

Bundles are auto-triggered by the failure paths that matter
(:class:`~reservoir_tpu.serve.ha.FailoverController` promotions and
degraded-transition verdicts, ``FencedError``, flush-watchdog trips, SLO
``page`` transitions) through :meth:`trigger`, which rate-limits per
reason so a flapping health check cannot turn the postmortem plane into
a disk-filling incident of its own.  ``tools/postmortem.py`` renders a
bundle with no jax import.

Installation follows the plane's zero-overhead discipline: a
module-global :func:`install`/:func:`uninstall` pair; every trigger site
gates on ``get() is None`` (one global load, one test — pinned by the
trip-wire in ``tests/test_obs.py``).  Installing also taps
:func:`reservoir_tpu.obs.registry.emit` so every structured event lands
in the ring even when no event log is attached.  Recording is purely
observational: journals and snapshots are byte-identical with the
recorder installed or not.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

from . import registry as _registry

__all__ = [
    "FlightRecorder",
    "install",
    "uninstall",
    "get",
    "recording",
    "read_bundle",
]

_BUNDLE_PREFIX = "postmortem-"


def _sanitize(reason: str) -> str:
    return "".join(
        c if (c.isalnum() or c in "-_") else "_" for c in reason
    )[:48] or "manual"


class FlightRecorder:
    """Bounded ring of recent events + postmortem bundle writer.

    Args:
      dir: where bundles land (created if missing).
      capacity: ring size (most recent events/notes retained).
      keep: bundles retained on disk — older ones are pruned after each
        dump, so a chaos soak cannot fill the volume.
      min_interval_s: per-reason trigger rate limit; a suppressed trigger
        is counted (:attr:`suppressed`), never an error.
      clock: wall-clock source (injectable for tests).
      config: deployment facts worth having in every bundle
        (``checkpoint_dir`` additionally lets :meth:`dump` read the
        heartbeat and fence epoch at dump time).
    """

    def __init__(
        self,
        dir: str,
        *,
        capacity: int = 2048,
        keep: int = 8,
        min_interval_s: float = 5.0,
        clock=time.time,
        config: Optional[dict] = None,
    ) -> None:
        os.makedirs(dir, exist_ok=True)
        self.dir = dir
        self._ring: deque = deque(maxlen=int(capacity))
        self._keep = max(1, int(keep))
        self._min_interval = float(min_interval_s)
        self._clock = clock
        self.config = dict(config or {})
        self._seq = itertools.count(1)
        self._last_trigger: Dict[str, float] = {}
        self._dump_lock = threading.Lock()
        self._dumping = False
        self.dumps = 0
        self.suppressed = 0

    # ------------------------------------------------------------ recording

    def record(self, kind: str, payload: dict) -> None:
        """Append one ring record (``deque.append`` is atomic — no lock)."""
        self._ring.append((self._clock(), kind, payload))

    def _tap_event(self, event: str, fields: dict) -> None:
        """The :func:`registry.emit` tap — every structured event, even
        ones the rate-limited event log drops, lands in the ring."""
        record = {"event": event}
        record.update(fields)
        self._ring.append((self._clock(), "event", record))

    def note(self, name: str, **fields: Any) -> None:
        """A free-form breadcrumb (instrument snapshots, chaos actions)."""
        record = {"note": name}
        record.update(fields)
        self._ring.append((self._clock(), "note", record))

    def tail(self) -> List[dict]:
        """The ring contents, oldest first, as JSON-able dicts."""
        return [
            {"ts": ts, "kind": kind, **payload}
            for ts, kind, payload in list(self._ring)
        ]

    # -------------------------------------------------------------- dumping

    def trigger(self, reason: str, **context: Any) -> Optional[str]:
        """Rate-limited auto-dump: at most one bundle per ``reason`` per
        ``min_interval_s``.  Returns the bundle path, or ``None`` when
        suppressed.  Never raises on the caller's (failure) path — a
        postmortem writer that can crash the patient is worse than none."""
        if self._dumping:
            # re-entrant trigger: assembling a bundle can itself evaluate
            # the SLO plane (json_snapshot), whose page transition must
            # not recurse into a second dump under the dump lock
            self.suppressed += 1
            return None
        now = self._clock()
        last = self._last_trigger.get(reason)
        if last is not None and (now - last) < self._min_interval:
            self.suppressed += 1
            return None
        self._last_trigger[reason] = now
        try:
            return self.dump(reason=reason, **context)
        except Exception:
            return None

    def dump(
        self,
        reason: str = "manual",
        path: Optional[str] = None,
        **context: Any,
    ) -> str:
        """Write one postmortem bundle atomically (temp file + rename);
        returns its path.  The bundle carries everything the viewer needs
        with no live process: span list (tree-reconstructable), event
        tail, telemetry snapshot + SLO verdicts + latency attribution,
        heartbeat/epoch state, and the recorder's config + context."""
        with self._dump_lock:
            self._dumping = True
            try:
                return self._dump_locked(reason, path, context)
            finally:
                self._dumping = False

    def _dump_locked(
        self, reason: str, path: Optional[str], context: dict
    ) -> str:
        seq = next(self._seq)
        bundle: dict = {
            "ts": self._clock(),
            "reason": reason,
            "seq": seq,
            "context": {k: v for k, v in context.items()},
            "config": dict(self.config),
            "events": self.tail(),
        }
        from . import trace as _trace

        tr = _trace.get()
        if tr is not None:
            bundle["tracer"] = tr.snapshot()
            bundle["spans"] = [s.to_dict() for s in tr.spans()]
            bundle["attribution"] = _trace.attribution(
                tr.spans(),
                root=str(self.config.get("root_span", "serve.ingest")),
            )
        reg = _registry.get()
        if reg is not None:
            from .export import json_snapshot

            bundle["telemetry"] = json_snapshot(reg)
        ckpt = context.get("checkpoint_dir") or self.config.get(
            "checkpoint_dir"
        )
        if ckpt:
            bundle["heartbeat"] = _read_json(
                os.path.join(str(ckpt), "heartbeat.json")
            )
            try:
                from ..utils.checkpoint import read_epoch

                bundle["epoch"] = read_epoch(str(ckpt))
            except Exception:
                pass
        if path is None:
            path = os.path.join(
                self.dir,
                f"{_BUNDLE_PREFIX}{seq:04d}-{_sanitize(reason)}.json",
            )
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp.pm")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(bundle, fh, default=str)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        self.dumps += 1
        self._prune()
        _registry.emit("flight.dump", site="flight", reason=reason, path=path)
        return path

    def bundles(self) -> List[str]:
        """Bundle paths in this recorder's dir, oldest first."""
        try:
            names = sorted(
                n
                for n in os.listdir(self.dir)
                if n.startswith(_BUNDLE_PREFIX) and n.endswith(".json")
            )
        except OSError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    def _prune(self) -> None:
        paths = self.bundles()
        for p in paths[: max(0, len(paths) - self._keep)]:
            with contextlib.suppress(OSError):
                os.unlink(p)


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError, ValueError):
        return None


def read_bundle(path: str) -> dict:
    """Parse one postmortem bundle (plain JSON; the viewer's loader)."""
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


# ---------------------------------------------------------------- activation

_FLIGHT: Optional[FlightRecorder] = None


def get() -> Optional[FlightRecorder]:
    """The installed recorder, or ``None`` (the default).  Trigger sites
    gate on this: one global load, one ``is None`` test."""
    return _FLIGHT


def install(
    recorder: Optional[FlightRecorder] = None, *, dir: Optional[str] = None,
    **kwargs: Any,
) -> FlightRecorder:
    """Install a recorder process-wide (constructing one at ``dir`` when
    not given) and tap :func:`registry.emit` into its ring."""
    global _FLIGHT
    if recorder is None:
        if dir is None:
            raise ValueError("install() needs a recorder or a dir")
        recorder = FlightRecorder(dir, **kwargs)
    _FLIGHT = recorder
    _registry._set_event_tap(recorder._tap_event)
    return recorder


def uninstall() -> None:
    """Remove the recorder and its event tap: every trigger site reverts
    to the zero-overhead no-op path."""
    global _FLIGHT
    _FLIGHT = None
    _registry._set_event_tap(None)


@contextlib.contextmanager
def recording(
    recorder: Optional[FlightRecorder] = None, **kwargs: Any
) -> Iterator[FlightRecorder]:
    """``with flight.recording(dir=...) as fr: ...`` — scoped (tests)."""
    global _FLIGHT
    prev = _FLIGHT
    fr = install(recorder, **kwargs)
    try:
        yield fr
    finally:
        _FLIGHT = prev
        _registry._set_event_tap(
            prev._tap_event if prev is not None else None
        )
