"""Declarative SLOs with Google-SRE-style multi-window burn-rate verdicts.

PR 6's registry measures everything and judges nothing: the quantiles are
there, but nothing says whether 12 ms at p99 is fine or a page.  This
module is the judgment layer (ISSUE 7, ROADMAP item 5): a set of
:class:`SLOSpec` objectives evaluated over **rolling windows** of the
existing :class:`~reservoir_tpu.obs.registry.Registry` instruments, each
yielding an ``ok`` / ``warn`` / ``page`` verdict with the burn rates that
justify it.

The evaluation model is the multi-window burn rate from the Google SRE
workbook (ch. 5): an objective grants an **error budget** — the fraction
of events allowed to be bad (``1 - quantile`` for a latency objective;
an explicit ``budget`` for error-rate objectives).  The *burn rate* over
a window is ``observed_bad_fraction / budget``: burn 1.0 spends the
budget exactly at the sustainable pace, 14.4 spends a 30-day budget in
~2 days.  A verdict escalates only when **both** the short window (fast
signal, noisy) and the long window (slow signal, stable) agree — the
standard trick that pages quickly on real regressions without paging on
a single slow request:

- ``page``: both windows burn at >= ``page_burn`` (default 14.4);
- ``warn``: both windows burn at >= ``warn_burn`` (default 3.0);
- ``ok``: anything less.

Four objective kinds, all reading instruments the stack already feeds:

- ``latency_quantile`` — a registry histogram of seconds; a "bad event"
  is an observation above ``threshold``.  Budget is ``1 - quantile``:
  "p99 of ingest under 50 ms" = at most 1% of ingests over 50 ms.
- ``staleness`` — identical math over a staleness histogram
  (``serve.snapshot_staleness_s``): snapshots served from a cache older
  than ``threshold`` are the bad events.
- ``error_rate`` — two counters, bad over total, with an explicit
  ``budget`` fraction (``serve.ingest_errors`` / ``serve.ingest_total``).
- ``sample_quality`` — the statistical objective (ISSUE 7 tentpole /
  arXiv:1906.04120's inclusion-probability invariant): counters fed by
  :class:`~reservoir_tpu.obs.audit.SampleQualityAuditor`
  (``audit.ks_breaches`` / ``audit.ks_checks``) judged exactly like an
  error rate, so statistical drift pages exactly like a latency
  regression.  ``value_instrument`` (default ``audit.ks_statistic``)
  carries the live KS distance into the verdict for display.

An :class:`SLOPlane` holds the specs and a bounded history of instrument
frames; every :meth:`~SLOPlane.evaluate` call records one frame and diffs
against the newest frame at least one window old (or the oldest frame —
a young plane judges everything since construction).  The plane attaches
itself to its registry, so :func:`~reservoir_tpu.obs.export.json_snapshot`
(and therefore ``heartbeat.json`` and ``tools/reservoir_top.py``'s
verdict panel) and the Prometheus exporter pick the verdicts up with no
extra wiring.  Zero overhead with telemetry disabled: nothing here sits
on a hot path — evaluation happens at export/heartbeat cadence.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
from typing import Deque, Dict, Iterable, Optional, Tuple

from . import registry as _obs
from .registry import Counter, Histogram, Registry

__all__ = ["SLOSpec", "SLOVerdict", "SLOPlane", "default_slos", "KINDS"]

#: The objective kinds :class:`SLOSpec` accepts.
KINDS: Tuple[str, ...] = (
    "latency_quantile",
    "staleness",
    "error_rate",
    "sample_quality",
)

#: Verdict severity order (worst() folds with this).
_SEVERITY = {"ok": 0, "warn": 1, "page": 2}


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective over registry instruments.

    Attributes:
      name: verdict key (stable across exports — dashboards join on it).
      kind: one of :data:`KINDS`.
      instrument: the histogram (latency/staleness kinds) or the
        bad-event counter (error kinds) to read.
      threshold: the objective bound — seconds for latency/staleness
        (an observation above it is a bad event); for error kinds it is
        display-only context (the gate the bad counter already applied,
        e.g. the auditor's KS gate).
      quantile: latency/staleness only — the objective's quantile; the
        error budget is ``1 - quantile``.
      total_instrument: error kinds only — the total-events counter.
      budget: error kinds only — allowed bad fraction (0..1).
      short_window_s / long_window_s: the two burn-rate windows.
      warn_burn / page_burn: burn-rate escalation thresholds (both
        windows must agree).
      value_instrument: optional gauge whose live value rides the
        verdict (``sample_quality`` defaults it to the auditor's
        ``audit.ks_statistic``).
      description: human objective line for status panels.
    """

    name: str
    kind: str
    instrument: str
    threshold: float = 0.0
    quantile: float = 0.99
    total_instrument: str = ""
    budget: float = 0.01
    short_window_s: float = 300.0
    long_window_s: float = 3600.0
    warn_burn: float = 3.0
    page_burn: float = 14.4
    value_instrument: str = ""
    description: str = ""

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(
                f"SLOSpec {self.name!r}: kind must be one of {KINDS}, "
                f"got {self.kind!r}"
            )
        if self.kind in ("latency_quantile", "staleness"):
            if not (0.0 < self.quantile < 1.0):
                raise ValueError(
                    f"SLOSpec {self.name!r}: quantile must be in (0, 1)"
                )
            if self.threshold <= 0.0:
                raise ValueError(
                    f"SLOSpec {self.name!r}: latency/staleness objectives "
                    "need a positive threshold (seconds)"
                )
        else:
            if not self.total_instrument:
                raise ValueError(
                    f"SLOSpec {self.name!r}: error-rate objectives need "
                    "total_instrument"
                )
            if not (0.0 < self.budget < 1.0):
                raise ValueError(
                    f"SLOSpec {self.name!r}: budget must be in (0, 1)"
                )
        if not (0 < self.short_window_s <= self.long_window_s):
            raise ValueError(
                f"SLOSpec {self.name!r}: need 0 < short_window_s <= "
                "long_window_s"
            )
        if not (0 < self.warn_burn <= self.page_burn):
            raise ValueError(
                f"SLOSpec {self.name!r}: need 0 < warn_burn <= page_burn"
            )

    def error_budget(self) -> float:
        """The allowed bad-event fraction this objective grants."""
        if self.kind in ("latency_quantile", "staleness"):
            return 1.0 - self.quantile
        return self.budget

    def objective(self) -> str:
        """One-line human rendering for status panels."""
        if self.description:
            return self.description
        if self.kind in ("latency_quantile", "staleness"):
            return (
                f"p{self.quantile * 100:g} {self.instrument} "
                f"<= {self.threshold * 1e3:g}ms"
            )
        return (
            f"{self.instrument}/{self.total_instrument} "
            f"<= {self.budget:g}"
        )


@dataclasses.dataclass
class SLOVerdict:
    """One evaluated objective: the actionable ``verdict`` plus the burn
    rates and window deltas that justify it (``bad``/``total`` are the
    short-window event deltas)."""

    name: str
    kind: str
    verdict: str
    burn_short: float
    burn_long: float
    bad: float
    total: float
    budget: float
    threshold: float
    value: float
    objective: str

    def as_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


def default_slos(
    *,
    ingest_p99_s: float = 0.050,
    snapshot_p99_s: float = 0.050,
    staleness_s: float = 2.0,
    error_budget: float = 0.01,
    quality_budget: float = 0.05,
    short_window_s: float = 300.0,
    long_window_s: float = 3600.0,
    scope: Optional[str] = None,
) -> Tuple[SLOSpec, ...]:
    """The serving plane's standard objective set: ingest/snapshot latency,
    snapshot staleness, admission error rate, and sample quality — the
    four axes ``bench.py traffic`` reports and ``reservoir_top`` panels.

    ``scope`` labels every instrument name with a per-shard scope
    (ISSUE 9, :func:`~reservoir_tpu.obs.registry.scoped`): a cluster runs
    one :class:`SLOPlane` per shard over ``serve.*@shardN`` instruments,
    so one saturated shard pages alone while its neighbors stay ``ok``.
    Spec names are unchanged — planes are per-shard objects, so dashboards
    join on the same objective names across shards."""
    common = dict(
        short_window_s=short_window_s, long_window_s=long_window_s
    )

    def _n(name: str) -> str:
        return _obs.scoped(name, scope)

    return (
        SLOSpec(
            "ingest_latency_p99",
            "latency_quantile",
            _n("serve.ingest_s"),
            threshold=ingest_p99_s,
            quantile=0.99,
            **common,
        ),
        SLOSpec(
            "snapshot_latency_p99",
            "latency_quantile",
            _n("serve.snapshot_s"),
            threshold=snapshot_p99_s,
            quantile=0.99,
            **common,
        ),
        SLOSpec(
            "snapshot_staleness_p99",
            "staleness",
            _n("serve.snapshot_staleness_s"),
            threshold=staleness_s,
            quantile=0.99,
            **common,
        ),
        SLOSpec(
            "ingest_error_rate",
            "error_rate",
            _n("serve.ingest_errors"),
            total_instrument=_n("serve.ingest_total"),
            budget=error_budget,
            **common,
        ),
        SLOSpec(
            "sample_quality",
            "sample_quality",
            _n("audit.ks_breaches"),
            total_instrument=_n("audit.ks_checks"),
            budget=quality_budget,
            value_instrument=_n("audit.ks_statistic"),
            **common,
        ),
    )


class SLOPlane:
    """Burn-rate evaluator over one registry.

    Single-writer like the metric blocks: call :meth:`evaluate` from one
    thread (the heartbeat/export cadence).  Construction records the
    baseline frame, so the first evaluation already judges everything
    observed since the plane came up.

    Args:
      specs: objectives (default: :func:`default_slos`).
      registry: the registry to read; ``None`` binds to the active one at
        each call (and the plane attaches itself to whichever registry it
        reads, so exporters find it via ``registry.slo_plane``).
      clock: time source (injectable for deterministic window tests).
      max_frames: bounded history (frames arrive at evaluation cadence;
        the default covers an hour-long window at one-second beats).
      attach: publish this plane on its registry (``registry.slo_plane``)
        so exporters pick the verdicts up.  Per-shard planes (ISSUE 9)
        pass ``False`` — N shard planes must not fight over the one
        registry slot; the cluster aggregates their verdicts itself.
    """

    def __init__(
        self,
        specs: Optional[Iterable[SLOSpec]] = None,
        registry: Optional[Registry] = None,
        *,
        clock=time.time,
        max_frames: int = 4096,
        attach: bool = True,
    ) -> None:
        self._attach = bool(attach)
        self.specs: Tuple[SLOSpec, ...] = tuple(
            specs if specs is not None else default_slos()
        )
        names = [s.name for s in self.specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self._registry = registry
        self._clock = clock
        self._frames: Deque[Tuple[float, Dict[str, Tuple[float, float]]]] = (
            collections.deque(maxlen=max_frames)
        )
        self.last: Dict[str, SLOVerdict] = {}
        reg = self._resolve()
        if reg is not None:
            self._frames.append((float(clock()), self._capture(reg)))

    # ------------------------------------------------------------- plumbing

    def _resolve(self) -> Optional[Registry]:
        reg = self._registry if self._registry is not None else _obs.get()
        if (
            self._attach
            and reg is not None
            and getattr(reg, "slo_plane", None) is not self
        ):
            reg.slo_plane = self  # exporters find the plane via its registry
        return reg

    @staticmethod
    def _histogram_bad(h: Histogram, threshold: float) -> Tuple[float, float]:
        """(bad, total) for a histogram objective: observations whose
        bucket representative (the same geometric midpoint ``quantile()``
        reads back) exceeds ``threshold``.  Overflow is always bad."""
        counts = h.bucket_counts()
        bounds = h.bounds()
        bad = counts[-1]  # > hi: worse than any finite bucket
        for i, c in enumerate(counts[:-1]):
            if not c:
                continue
            lower = bounds[i - 1] if i else 0.0
            rep = math.sqrt(lower * bounds[i]) if lower else bounds[i]
            if rep > threshold:
                bad += c
        return float(bad), float(sum(counts))

    def _capture(
        self, reg: Registry
    ) -> Dict[str, Tuple[float, float]]:
        """One frame: per-spec (bad, total) cumulative event counts.
        Missing instruments read as (0, 0) — :meth:`Registry.peek` never
        creates, so the plane cannot geometry-default a histogram into
        existence before its owning site does."""
        frame: Dict[str, Tuple[float, float]] = {}
        for spec in self.specs:
            inst = reg.peek(spec.instrument)
            if spec.kind in ("latency_quantile", "staleness"):
                frame[spec.name] = (
                    self._histogram_bad(inst, spec.threshold)
                    if isinstance(inst, Histogram)
                    else (0.0, 0.0)
                )
            else:
                total = reg.peek(spec.total_instrument)
                frame[spec.name] = (
                    float(inst.value) if isinstance(inst, Counter) else 0.0,
                    float(total.value)
                    if isinstance(total, Counter)
                    else 0.0,
                )
        return frame

    def _window_base(
        self, now: float, window_s: float
    ) -> Dict[str, Tuple[float, float]]:
        """The newest frame at least ``window_s`` old, else the oldest
        frame (a young plane judges its whole life)."""
        base = self._frames[0][1] if self._frames else {}
        for ts, frame in self._frames:
            if ts <= now - window_s:
                base = frame
            else:
                break
        return base

    # ------------------------------------------------------------ judgment

    def evaluate(self, now: Optional[float] = None) -> Dict[str, SLOVerdict]:
        """Record one frame and judge every objective; returns (and
        caches in :attr:`last`) the verdicts keyed by spec name."""
        reg = self._resolve()
        if reg is None:
            return dict(self.last)  # telemetry off: nothing new to judge
        now = float(self._clock()) if now is None else float(now)
        frame = self._capture(reg)
        verdicts: Dict[str, SLOVerdict] = {}
        for spec in self.specs:
            budget = spec.error_budget()
            burns: Dict[float, Tuple[float, float, float]] = {}
            for window in (spec.short_window_s, spec.long_window_s):
                base = self._window_base(now, window)
                b0, t0 = base.get(spec.name, (0.0, 0.0))
                bad = max(0.0, frame[spec.name][0] - b0)
                total = max(0.0, frame[spec.name][1] - t0)
                frac = (bad / total) if total > 0 else 0.0
                burns[window] = (frac / budget, bad, total)
            burn_short, bad_s, total_s = burns[spec.short_window_s]
            burn_long, _, _ = burns[spec.long_window_s]
            floor = min(burn_short, burn_long)
            verdict = (
                "page"
                if floor >= spec.page_burn
                else "warn" if floor >= spec.warn_burn else "ok"
            )
            value = 0.0
            if spec.kind in ("latency_quantile", "staleness"):
                inst = reg.peek(spec.instrument)
                if isinstance(inst, Histogram):
                    value = inst.quantile(spec.quantile)
            elif spec.value_instrument:
                inst = reg.peek(spec.value_instrument)
                value = float(getattr(inst, "value", 0.0) or 0.0)
            else:
                value = (bad_s / total_s) if total_s > 0 else 0.0
            verdicts[spec.name] = SLOVerdict(
                name=spec.name,
                kind=spec.kind,
                verdict=verdict,
                burn_short=burn_short,
                burn_long=burn_long,
                bad=bad_s,
                total=total_s,
                budget=budget,
                threshold=spec.threshold,
                value=value,
                objective=spec.objective(),
            )
        paged = [
            name
            for name, v in verdicts.items()
            if v.verdict == "page"
            and (
                name not in self.last
                or self.last[name].verdict != "page"
            )
        ]
        self._frames.append((now, frame))
        self.last = verdicts
        if paged:
            # an SLO page transition is a flight-recorder trigger (ISSUE
            # 11): the page should arrive with its own postmortem bundle
            from . import flight as _flight

            fl = _flight.get()
            if fl is not None:
                fl.trigger("slo_page", slos=",".join(sorted(paged)))
        return verdicts

    def worst(self) -> str:
        """The most severe verdict across :attr:`last` (``ok`` when the
        plane has never evaluated)."""
        if not self.last:
            return "ok"
        return max(
            (v.verdict for v in self.last.values()),
            key=lambda v: _SEVERITY[v],
        )

    def snapshot(self, evaluate: bool = True) -> Dict[str, object]:
        """JSON-able export payload (what ``json_snapshot`` embeds under
        ``"slo"`` and ``reservoir_top`` renders as the verdict panel)."""
        if evaluate:
            self.evaluate()
        return {
            "worst": self.worst(),
            "verdicts": {k: v.as_dict() for k, v in self.last.items()},
        }
