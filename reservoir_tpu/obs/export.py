"""Exporters: Prometheus text format and a JSON snapshot.

Two render targets over one :class:`~reservoir_tpu.obs.registry.Registry`:

- :func:`prometheus_text` — the Prometheus exposition format (``# TYPE``
  headers, cumulative ``_bucket{le=...}`` lines for histograms, ``_sum``/
  ``_count``), golden-pinned by ``tests/test_obs.py`` so the wire format
  cannot drift silently;
- :func:`json_snapshot` / :func:`write_json_snapshot` — the machine-local
  form: one dict carrying the registry snapshot AND every live registered
  metric block (``BridgeMetrics``/``ServiceMetrics``/``HAMetrics`` via
  :func:`~reservoir_tpu.obs.registry.register_block`), which is what the
  heartbeat writer embeds into ``heartbeat.json`` and
  ``tools/reservoir_top.py`` tails.

Only occupied histogram buckets are emitted (plus the mandatory ``+Inf``):
a 180-bucket latency histogram with three occupied buckets costs four
lines, not 181.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Optional

from . import trace as _trace
from .registry import Counter, Gauge, Histogram, Registry, blocks, get

__all__ = ["prometheus_text", "json_snapshot", "write_json_snapshot"]


def _sanitize(name: str) -> str:
    return "".join(
        c if (c.isalnum() or c == "_") else "_" for c in name
    )


def _fmt(v: float) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _flatten(prefix: str, d: dict, out: dict) -> None:
    for key, value in d.items():
        name = f"{prefix}_{key}" if prefix else str(key)
        if isinstance(value, dict):
            _flatten(name, value, out)
        elif isinstance(value, bool) or isinstance(value, (int, float)):
            out[name] = value


def prometheus_text(
    registry: Optional[Registry] = None,
    *,
    prefix: str = "reservoir",
    include_blocks: bool = True,
) -> str:
    """Render ``registry`` (default: the active one) in Prometheus text
    exposition format.  ``include_blocks`` additionally renders every live
    registered metric block's numeric ``snapshot()`` fields as gauges with
    an ``instance`` label."""
    if registry is None:
        registry = get()
    lines = []
    if registry is not None:
        for inst in registry.instruments():
            name = f"{prefix}_{_sanitize(inst.name)}"
            if isinstance(inst, Counter):
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {_fmt(inst.value)}")
            elif isinstance(inst, Gauge):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_fmt(inst.value)}")
            elif isinstance(inst, Histogram):
                lines.append(f"# TYPE {name} histogram")
                bounds = inst.bounds()
                counts = inst.bucket_counts()
                cum = 0
                for i, c in enumerate(counts[:-1]):
                    cum += c
                    if c:
                        lines.append(
                            f'{name}_bucket{{le="{bounds[i]:g}"}} {cum}'
                        )
                cum += counts[-1]
                lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
                lines.append(f"{name}_sum {_fmt(inst.sum)}")
                lines.append(f"{name}_count {inst.count}")
    plane = getattr(registry, "slo_plane", None) if registry else None
    if plane is not None:
        # burn-rate verdicts (obs/slo.py): one gauge triple per objective,
        # verdict encoded 0/1/2 (ok/warn/page) so alert rules are a simple
        # threshold over reservoir_slo_verdict
        severity = {"ok": 0, "warn": 1, "page": 2}
        slo = plane.snapshot()
        verdicts = slo.get("verdicts", {})
        if verdicts:
            for metric, value_of in (
                ("verdict", lambda v: severity.get(v["verdict"], 0)),
                ("burn_short", lambda v: v["burn_short"]),
                ("burn_long", lambda v: v["burn_long"]),
            ):
                name = f"{prefix}_slo_{metric}"
                lines.append(f"# TYPE {name} gauge")
                for key in sorted(verdicts):
                    lines.append(
                        f'{name}{{slo="{_sanitize(key)}"}} '
                        f"{_fmt(value_of(verdicts[key]))}"
                    )
    tracer = _trace.get()
    if tracer is not None:
        # causal-trace attribution (ISSUE 11): per-stage share of the
        # end-to-end ingest wait, rendered only while a tracer is active
        # (the golden-pinned base format is unchanged when tracing is off)
        report = _trace.attribution(tracer.spans())
        if report["traces"]:
            name = f"{prefix}_trace_stage_share"
            lines.append(f"# TYPE {name} gauge")
            for stage in sorted(report["stages"]):
                lines.append(
                    f'{name}{{stage="{_sanitize(stage)}"}} '
                    f'{_fmt(report["stages"][stage]["share"])}'
                )
            lines.append(
                f'{name}{{stage="other"}} {_fmt(report["other"]["share"])}'
            )
            for metric, value in (
                ("traces", report["traces"]),
                ("e2e_p50_s", report["e2e_s"]["p50"]),
                ("e2e_p99_s", report["e2e_s"]["p99"]),
            ):
                name = f"{prefix}_trace_{metric}"
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_fmt(value)}")
    if include_blocks:
        by_name: dict = {}
        for kind, idx, block in blocks():
            flat: dict = {}
            _flatten("", block.snapshot(), flat)
            for field, value in flat.items():
                name = f"{prefix}_{_sanitize(kind)}_{_sanitize(field)}"
                by_name.setdefault(name, []).append((idx, value))
        for name in sorted(by_name):
            lines.append(f"# TYPE {name} gauge")
            for idx, value in by_name[name]:
                lines.append(f'{name}{{instance="{idx}"}} {_fmt(value)}')
    return "\n".join(lines) + ("\n" if lines else "")


def json_snapshot(
    registry: Optional[Registry] = None,
    *,
    include_blocks: bool = True,
    clock=time.time,
) -> dict:
    """One JSON-able dict: registry instruments plus (by default) every
    live registered metric block, keyed by kind with instance ids —
    the payload the heartbeat embeds and ``reservoir_top`` renders."""
    if registry is None:
        registry = get()
    out: dict = {"ts": float(clock())}
    out.update(
        registry.snapshot()
        if registry is not None
        else {"counters": {}, "gauges": {}, "histograms": {}}
    )
    if include_blocks:
        grouped: dict = {}
        for kind, idx, block in blocks():
            grouped.setdefault(kind, {})[str(idx)] = block.snapshot()
        out["blocks"] = grouped
    plane = getattr(registry, "slo_plane", None) if registry else None
    if plane is not None:
        # the verdict panel payload: rides heartbeat.json via the
        # HeartbeatWriter's embedded export, rendered by reservoir_top
        out["slo"] = plane.snapshot()
    tracer = _trace.get()
    if tracer is not None:
        # the attribution panel payload (ISSUE 11): same conditional-key
        # pattern as "slo" — present only while a tracer is active, so
        # heartbeats and reservoir_top pick it up with no new wiring
        out["trace"] = _trace.attribution(tracer.spans())
    return out


def write_json_snapshot(
    path: str, registry: Optional[Registry] = None, **kwargs
) -> dict:
    """Atomically write :func:`json_snapshot` to ``path`` (temp file +
    rename: a tailing ``reservoir_top`` never reads a torn export)."""
    snap = json_snapshot(registry, **kwargs)
    directory = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp.obs")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(snap, fh, default=str)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return snap
