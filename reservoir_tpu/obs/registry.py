"""Thread-safe instrument registry: counters, gauges, latency histograms.

The measurement substrate of the telemetry plane (ISSUE 6).  A
:class:`Registry` is a named map of three instrument kinds:

- :class:`Counter` — monotonically increasing totals (flushes, rejections);
- :class:`Gauge` — last-write-wins levels (replication lag, pending bytes);
- :class:`Histogram` — fixed **log-spaced** buckets over a configurable
  range, with exact ``count``/``sum``/``min``/``max`` and bucketed
  p50/p99/p99.9 readout.  Log spacing keeps the relative quantile error
  bounded by one bucket's width (``10**(1/buckets_per_decade)``, ~12% at
  the default 20 buckets per decade) across nine decades of latency —
  microseconds to minutes — in ~180 ints of memory.

Activation follows the fault plane's discipline exactly
(:mod:`reservoir_tpu.utils.faults`): a module-global
:func:`enable`/:func:`disable` pair, and every instrumented hot path gates
on ``get() is None`` — **zero overhead when disabled**: one module-global
load, one ``is None`` test, no locks, no allocation, no instrument lookup
(pinned by the trip-wire in ``tests/test_obs.py``, same as the faults
pin).  Instruments themselves are created lazily on first use and are
individually locked; the registry lock is taken only at get-or-create.

The released metric dataclasses (:class:`~reservoir_tpu.utils.metrics.BridgeMetrics`
/ ``ServiceMetrics`` / ``HAMetrics``) stay exactly what they were — plain
single-writer counter blocks with stable signatures — and are **absorbed**
into the telemetry plane by registration (:func:`register_block`): every
block constructed anywhere in the process is weakly tracked, and the
exporters (:mod:`reservoir_tpu.obs.export`) render live blocks' ``snapshot()``
fields as gauges next to the registry's own instruments.  ``metrics()``
returns are therefore unchanged views; the registry is the superset.
"""

from __future__ import annotations

import contextlib
import itertools
import math
import threading
import weakref
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "enable",
    "disable",
    "active",
    "get",
    "emit",
    "register_block",
    "blocks",
    "scoped",
]

#: Separator of the per-shard instrument-label convention (ISSUE 9):
#: ``serve.ingest_s@shard0`` is shard 0's admission histogram — same
#: metric family as the unscoped name, disjoint instrument.  Exporters
#: need no special handling (a scoped name is just a name); SLO planes
#: scope their specs with :func:`~reservoir_tpu.obs.slo.default_slos`'s
#: ``scope=`` so each failure domain is judged on its own instruments.
SCOPE_SEP = "@"


def scoped(name: str, scope: Optional[str] = None) -> str:
    """``name`` labeled with an instrument scope (``None`` = unscoped)."""
    return name if not scope else f"{name}{SCOPE_SEP}{scope}"


class Counter:
    """A monotonically increasing total (single instrument, thread-safe)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        # reservoir-lint: disable=guarded-by -- lock-free .value readout: a single float attribute read is GIL-atomic (exact-or-stale, never torn)
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """A last-write-wins level (thread-safe set/add)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        # reservoir-lint: disable=guarded-by -- lock-free .value readout: last-write-wins, a single float attribute read is GIL-atomic
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, n: float) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed log-spaced buckets with exact count/sum/min/max and bucketed
    quantile readout.

    Buckets are deterministic pure functions of ``(lo, hi,
    buckets_per_decade)``: bucket ``i`` holds values in
    ``(lo * 10**(i/bpd), lo * 10**((i+1)/bpd)]``, values ``<= lo`` land in
    bucket 0, values ``> hi`` in a dedicated overflow bucket whose
    representative is the exact observed max.  A quantile readout returns
    the geometric midpoint of the selected bucket, clamped to the exact
    observed ``[min, max]`` — so a single observation reads back exactly,
    and relative error is bounded by one bucket width.
    """

    __slots__ = (
        "name", "_lo", "_hi", "_bpd", "_n", "_counts",
        "_count", "_sum", "_min", "_max", "_lock",
    )

    def __init__(
        self,
        name: str,
        lo: float = 1e-6,
        hi: float = 1e3,
        buckets_per_decade: int = 20,
    ) -> None:
        if not (lo > 0 and hi > lo and buckets_per_decade > 0):
            raise ValueError(
                "histogram needs 0 < lo < hi and buckets_per_decade > 0"
            )
        self.name = name
        self._lo = float(lo)
        self._hi = float(hi)
        self._bpd = int(buckets_per_decade)
        self._n = int(math.ceil(math.log10(hi / lo) * buckets_per_decade))
        self._counts = [0] * (self._n + 1)  # +1: overflow (> hi)
        # reservoir-lint: disable=guarded-by -- lock-free stats readout: per-field reads are GIL-atomic; cross-field skew vs a concurrent observe() is accepted monitoring semantics (quantile() does lock)
        self._count = 0
        # reservoir-lint: disable=guarded-by -- lock-free stats readout (see _count)
        self._sum = 0.0
        # reservoir-lint: disable=guarded-by -- lock-free stats readout (see _count)
        self._min = math.inf
        # reservoir-lint: disable=guarded-by -- lock-free stats readout (see _count)
        self._max = -math.inf
        self._lock = threading.Lock()

    # ------------------------------------------------------------- geometry

    def bounds(self) -> List[float]:
        """Upper bucket bounds (exclusive of the overflow bucket) — a pure
        function of the constructor args, pinned by the determinism test."""
        return [
            self._lo * 10 ** ((i + 1) / self._bpd) for i in range(self._n)
        ]

    def bucket_counts(self) -> List[int]:
        """Per-bucket counts (last entry is the ``> hi`` overflow bucket)."""
        with self._lock:
            return list(self._counts)

    def _index(self, v: float) -> int:
        if v <= self._lo:
            return 0
        if v > self._hi:
            return self._n
        i = int(math.floor(math.log10(v / self._lo) * self._bpd))
        # float round-off can land an exact boundary one bucket high/low;
        # clamp into the regular range (the overflow bucket is > hi only)
        return min(max(i, 0), self._n - 1)

    # ------------------------------------------------------------ recording

    def observe(self, v: float) -> None:
        v = float(v)
        i = self._index(v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    # -------------------------------------------------------------- readout

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def min(self) -> float:
        return self._min if self._count else 0.0

    @property
    def max(self) -> float:
        return self._max if self._count else 0.0

    def quantile(self, q: float) -> float:
        """The q-quantile (0..1) from the bucket counts, clamped to the
        exact observed range.  0.0 when nothing was observed."""
        with self._lock:
            if not self._count:
                return 0.0
            rank = max(1, int(math.ceil(q * self._count)))
            cum = 0
            for i, c in enumerate(self._counts):
                cum += c
                if cum >= rank:
                    break
            if i >= self._n:  # overflow bucket: the max is its witness
                return self._max
            upper = self._lo * 10 ** ((i + 1) / self._bpd)
            lower = self._lo * 10 ** (i / self._bpd) if i else 0.0
            rep = math.sqrt(lower * upper) if lower else upper
            return min(max(rep, self._min), self._max)

    def percentiles(self) -> Tuple[float, float, float]:
        """(p50, p99, p99.9) — the latency readout every consumer wants."""
        return self.quantile(0.5), self.quantile(0.99), self.quantile(0.999)

    def snapshot(self) -> Dict[str, float]:
        p50, p99, p999 = self.percentiles()
        n = self._count
        return {
            "count": n,
            "sum": self._sum,
            "mean": (self._sum / n) if n else 0.0,
            "min": self.min,
            "max": self.max,
            "p50": p50,
            "p99": p99,
            "p999": p999,
        }


class Registry:
    """A named, thread-safe map of instruments (get-or-create semantics:
    ``registry.histogram("bridge.flush_s")`` from any thread returns the
    one shared instrument).  An optional
    :class:`~reservoir_tpu.obs.events.EventLog` rides along — the
    structured half of the plane — reachable through :func:`emit`."""

    def __init__(self, event_log=None) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}
        self.event_log = event_log
        # the SLO plane binds itself here (obs/slo.py) so exporters can
        # render burn-rate verdicts without new wiring at every call site
        self.slo_plane = None

    def _get(self, name: str, cls, *args, **kwargs):
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(name)
                if inst is None:
                    inst = cls(name, *args, **kwargs)
                    self._instruments[name] = inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"instrument {name!r} is a {type(inst).__name__}, not a "
                f"{cls.__name__}"
            )
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(
        self,
        name: str,
        lo: float = 1e-6,
        hi: float = 1e3,
        buckets_per_decade: int = 20,
    ) -> Histogram:
        return self._get(name, Histogram, lo, hi, buckets_per_decade)

    def peek(self, name: str) -> Optional[object]:
        """The instrument named ``name``, or ``None`` — never creates.
        Readers that must not geometry-default a histogram into existence
        before its owning site does (the SLO plane) use this."""
        return self._instruments.get(name)

    def instruments(self) -> List[object]:
        with self._lock:
            return [self._instruments[k] for k in sorted(self._instruments)]

    def snapshot(self) -> Dict[str, dict]:
        """Point-in-time view of every instrument, grouped by kind."""
        out: Dict[str, dict] = {"counters": {}, "gauges": {}, "histograms": {}}
        for inst in self.instruments():
            if isinstance(inst, Counter):
                out["counters"][inst.name] = inst.value
            elif isinstance(inst, Gauge):
                out["gauges"][inst.name] = inst.value
            else:
                out["histograms"][inst.name] = inst.snapshot()
        return out


# ---------------------------------------------------------------- activation

_REGISTRY: Optional[Registry] = None


def get() -> Optional[Registry]:
    """The active registry, or ``None`` (telemetry disabled — the default).
    Hot paths gate on this: one global load, one ``is None`` test."""
    return _REGISTRY


def enable(
    registry: Optional[Registry] = None,
    *,
    event_log=None,
    event_log_path: Optional[str] = None,
) -> Registry:
    """Activate telemetry process-wide; returns the active registry.
    ``event_log_path`` opens a fresh
    :class:`~reservoir_tpu.obs.events.EventLog` there (``event_log``
    passes one in); with neither, :func:`emit` stays a no-op."""
    global _REGISTRY
    if registry is None:
        registry = Registry(event_log=event_log)
    elif event_log is not None:
        registry.event_log = event_log
    if event_log_path is not None:
        from .events import EventLog

        registry.event_log = EventLog(event_log_path)
    _REGISTRY = registry
    return registry


def disable() -> None:
    """Deactivate telemetry (closing any active event log): every
    instrumented site reverts to the zero-overhead no-op path."""
    global _REGISTRY
    reg, _REGISTRY = _REGISTRY, None
    if reg is not None and reg.event_log is not None:
        reg.event_log.close()


@contextlib.contextmanager
def active(registry: Optional[Registry] = None, **kwargs) -> Iterator[Registry]:
    """``with obs.active() as reg: ...`` — scoped activation (tests)."""
    global _REGISTRY
    prev = _REGISTRY
    reg = enable(registry, **kwargs)
    try:
        yield reg
    finally:
        if reg.event_log is not None:
            reg.event_log.close()
        _REGISTRY = prev


# The flight recorder's event tap (obs/flight.py): when installed, every
# emit() lands in its bounded ring — even events the rate-limited log
# drops, and even with no registry active.  None (the default) costs one
# global load + ``is None`` test, the same budget as the registry gate.
_EVENT_TAP = None


def _set_event_tap(tap) -> None:
    global _EVENT_TAP
    _EVENT_TAP = tap


def emit(event: str, **fields) -> bool:
    """Write one structured event through the active registry's event log.
    No registry or no log: a no-op (global load + ``is None`` tests) —
    safe on any path, any rate."""
    tap = _EVENT_TAP
    if tap is not None:
        tap(event, fields)
    reg = _REGISTRY
    if reg is None:
        return False
    log = reg.event_log
    if log is None:
        return False
    return log.emit(event, **fields)


# ------------------------------------------------------------- metric blocks

# Released metric dataclasses register here at construction (their
# __post_init__), so exporters can render every live block without the
# owners growing new API.  Weak references: a block dies with its owner.
_BLOCKS_LOCK = threading.Lock()
_BLOCKS: List[Tuple[str, int, "weakref.ref"]] = []
_BLOCK_IDS = itertools.count()


def register_block(kind: str, block: object) -> None:
    """Track a metrics dataclass (``snapshot()``-bearing) for export under
    ``kind`` (``bridge``/``serve``/``ha``).  Construction-time only — never
    on a hot path."""
    ref = weakref.ref(block)
    with _BLOCKS_LOCK:
        _BLOCKS.append((kind, next(_BLOCK_IDS), ref))


def blocks() -> List[Tuple[str, int, object]]:
    """Live registered blocks as ``(kind, instance_id, block)``, pruning
    dead references in place."""
    out: List[Tuple[str, int, object]] = []
    with _BLOCKS_LOCK:
        alive = []
        for kind, idx, ref in _BLOCKS:
            obj = ref()
            if obj is not None:
                alive.append((kind, idx, ref))
                out.append((kind, idx, obj))
        _BLOCKS[:] = alive
    return out
