"""Centralized once/rate-limited logging, mirrored into the event log.

Before ISSUE 6 the codebase carried four copies of the same pattern —
``if not self._x_logged: self._x_logged = True; import logging; ...`` —
in ``engine.py`` (×3) and ``stream/bridge.py``, each invisible to any
structured consumer.  This module is the one implementation: the same
per-owner once semantics (the guard flag stays an attribute on the owner,
so "logged once per engine/bridge" survives object churn exactly as
before), plus a mirror of every emitted line into the telemetry event log
(:func:`reservoir_tpu.obs.emit`) when telemetry is enabled — a no-op
global-load-plus-``is None`` test otherwise.

:class:`RateLimited` covers the non-once case (a site that may fire
per-tile but should log at human rate).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ..obs import registry as _obs

__all__ = ["log_once", "warn_once", "info_once", "RateLimited"]


def log_once(
    owner: object,
    flag: str,
    level: int,
    message: str,
    *args,
    logger: Optional[str] = None,
    site: Optional[str] = None,
) -> bool:
    """Log ``message % args`` at ``level`` once per ``owner``: attribute
    ``flag`` on the owner is the guard (set here).  Returns whether this
    call logged.  When telemetry is enabled the same line is emitted as a
    structured ``log`` event with ``site`` as its correlation field."""
    if getattr(owner, flag, False):
        return False
    setattr(owner, flag, True)
    name = logger or type(owner).__module__
    logging.getLogger(name).log(level, message, *args)
    if _obs.get() is not None:
        _obs.emit(
            "log",
            level=logging.getLevelName(level).lower(),
            logger=name,
            site=site,
            message=(message % args) if args else message,
        )
    return True


def warn_once(
    owner: object,
    flag: str,
    message: str,
    *args,
    logger: Optional[str] = None,
    site: Optional[str] = None,
) -> bool:
    return log_once(
        owner, flag, logging.WARNING, message, *args, logger=logger, site=site
    )


def info_once(
    owner: object,
    flag: str,
    message: str,
    *args,
    logger: Optional[str] = None,
    site: Optional[str] = None,
) -> bool:
    return log_once(
        owner, flag, logging.INFO, message, *args, logger=logger, site=site
    )


class RateLimited:
    """Per-instance rate-limited logger: at most one line per
    ``min_interval_s``, with a suppressed-count suffix when lines were
    dropped in between (single-writer like the metric blocks)."""

    def __init__(
        self, logger: str, min_interval_s: float = 5.0, clock=time.monotonic
    ) -> None:
        self._logger = logging.getLogger(logger)
        self._name = logger
        self._interval = float(min_interval_s)
        self._clock = clock
        self._last = -float("inf")
        self._suppressed = 0

    def log(
        self, level: int, message: str, *args, site: Optional[str] = None
    ) -> bool:
        now = self._clock()
        if now - self._last < self._interval:
            self._suppressed += 1
            return False
        if self._suppressed:
            message = message + " (%d similar suppressed)"
            args = args + (self._suppressed,)
            self._suppressed = 0
        self._last = now
        self._logger.log(level, message, *args)
        if _obs.get() is not None:
            _obs.emit(
                "log",
                level=logging.getLevelName(level).lower(),
                logger=self._name,
                site=site,
                message=(message % args) if args else message,
            )
        return True

    def warning(self, message: str, *args, site: Optional[str] = None) -> bool:
        return self.log(logging.WARNING, message, *args, site=site)
