"""Checkpoint / resume for reservoir state (SURVEY §5 "checkpoint" row).

The reference has no checkpointing; its nearest analog is the reusable
sampler's copy-on-write snapshot (``Sampler.scala:353-381``) — a mid-stream
read that doesn't stop sampling.  Here snapshots are first-class: every
sampler's state is a small pure pytree (state ≪ stream by construction,
``Sampler.scala:11-12``), so a checkpoint is one ``.npz`` write, and resuming
is bit-exact — the counter-based RNG (:mod:`reservoir_tpu.ops.rng`) keys every
draw on the absolute stream index, so "run, checkpoint, restore, continue"
produces the *same* reservoirs as an uninterrupted run (pinned by
``tests/test_checkpoint.py``).

Format: a single ``.npz`` holding the state arrays (typed PRNG keys are
stored as their raw ``key_data`` words plus the impl name) and a JSON
manifest. Writes are atomic (temp file + ``os.replace``), so a crash during
checkpointing never corrupts the previous checkpoint.  This module is the
storage half of the SURVEY §5 failure-detection row: the *executable*
"replay from last snapshot" story lives in
:meth:`reservoir_tpu.stream.bridge.DeviceStreamBridge.recover`, which
auto-checkpoints through :func:`save_engine` every N flushes, journals the
post-checkpoint tiles, and replays them bit-exactly after a crash
(``tests/test_faults.py`` pins the end-to-end guarantee under injected
faults).  Reads are typed: a truncated/corrupt file raises
:class:`~reservoir_tpu.errors.CheckpointCorrupt`, a format-version mismatch
a clear forward-compat ``ValueError`` — recovery tooling never has to catch
raw numpy/zipfile internals.  The writer carries the ``checkpoint.write``
fault-injection site (:mod:`reservoir_tpu.utils.faults`), which is how the
"crash mid-checkpoint leaves the previous checkpoint intact" guarantee is
exercised in tests.

Self-contained on purpose: no orbax dependency — reservoir state is a
handful of ``[R, k]`` arrays, not a model tree, and a dependency-free format
keeps restore possible from any process (including CPU-only tooling).
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
import zipfile
from typing import Any, Callable, Optional, Tuple

import numpy as np

from ..errors import CheckpointCorrupt, CheckpointMismatch
from ..obs import registry as _obs
from . import faults
from .tracing import trace_span

__all__ = [
    "save_state",
    "load_state",
    "save_engine",
    "load_engine",
    "read_engine_metadata",
    "read_epoch",
    "write_epoch",
    "advance_epoch",
]

_FORMAT_VERSION = 1
_EPOCH_NAME = "epoch.json"


def _state_registry():
    # deferred: keep jax out of module import (mirrors the package's lazy
    # import policy, reservoir_tpu/__init__.py)
    from ..ops.algorithm_l import ReservoirState
    from ..ops.distinct import DistinctState
    from ..ops.weighted import WeightedState

    return {
        "ReservoirState": ReservoirState,
        "DistinctState": DistinctState,
        "WeightedState": WeightedState,
    }


def _pack_state(state: Any) -> Tuple[dict, dict]:
    """Split a state NamedTuple into (arrays, manifest-fields)."""
    import jax
    import jax.random as jr

    arrays: dict = {}
    fields = []
    for name, value in zip(type(state)._fields, state):
        if value is None:  # optional field (e.g. DistinctState.value_hi)
            fields.append({"name": name, "kind": "none"})
        elif jax.dtypes.issubdtype(value.dtype, jax.dtypes.prng_key):
            arrays[name] = np.asarray(jr.key_data(value))
            fields.append(
                {"name": name, "kind": "prng_key", "impl": str(jr.key_impl(value))}
            )
        else:
            arrays[name] = np.asarray(value)
            fields.append({"name": name, "kind": "array"})
    return arrays, {"state_class": type(state).__name__, "fields": fields}


def _unpack_state(arrays: dict, manifest: dict) -> Any:
    import jax.numpy as jnp
    import jax.random as jr

    cls = _state_registry()[manifest["state_class"]]
    values = []
    for field in manifest["fields"]:
        if field["kind"] == "none":
            values.append(None)
            continue
        raw = arrays[field["name"]]
        if field["kind"] == "prng_key":
            values.append(jr.wrap_key_data(jnp.asarray(raw), impl=field["impl"]))
        else:
            restored = jnp.asarray(raw)
            if restored.dtype != raw.dtype:
                # e.g. an int64 count array restored in an x64-disabled
                # process: jnp.asarray would silently narrow it and counts
                # would wrap — refuse instead of corrupting the resume
                raise ValueError(
                    f"checkpoint field {field['name']!r} has dtype "
                    f"{raw.dtype}, which this process would narrow to "
                    f"{restored.dtype}; enable jax x64 to restore it"
                )
            values.append(restored)
    return cls(*values)


def _atomic_write_npz(path: str, arrays: dict, manifest: dict) -> None:
    directory = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp.npz")
    try:
        # mkstemp's 0600 would survive the rename; honor the umask like a
        # plain open() so other tooling can read the checkpoint
        umask = os.umask(0)
        os.umask(umask)
        os.chmod(tmp, 0o666 & ~umask)
        with os.fdopen(fd, "wb") as fh:
            # the injection site fires inside the temp-file guard: a
            # scheduled "crash" mid-write must leave the previous
            # checkpoint untouched and no temp litter behind (pinned by
            # tests/test_faults.py)
            faults.fire("checkpoint.write")
            np.savez(
                fh,
                __manifest__=np.frombuffer(
                    json.dumps(manifest).encode(), dtype=np.uint8
                ),
                **arrays,
            )
            # flush file data before the rename: the rename alone is
            # journaled, the data is not — without this a crash can leave a
            # truncated file under the final name
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        dir_fd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _read_npz(path: str) -> Tuple[dict, dict]:
    try:
        with np.load(path) as data:
            if "__manifest__" not in data.files:
                raise CheckpointCorrupt(
                    f"{path!r} has no checkpoint manifest (not written by "
                    "save_state/save_engine, or corrupted)"
                )
            manifest = json.loads(bytes(data["__manifest__"]).decode())
            arrays = {k: data[k] for k in data.files if k != "__manifest__"}
    except FileNotFoundError:
        raise  # a missing file is an absent checkpoint, not a corrupt one
    except (zipfile.BadZipFile, EOFError, OSError, KeyError, ValueError) as e:
        # truncated zip container, truncated member, undecodable manifest —
        # surface ONE typed error instead of numpy/zipfile internals
        # (json.JSONDecodeError is a ValueError subclass)
        if isinstance(e, CheckpointCorrupt):
            raise
        raise CheckpointCorrupt(
            f"checkpoint {path!r} is truncated or corrupt "
            f"({type(e).__name__}: {e})"
        ) from e
    version = manifest.get("format_version")
    if version != _FORMAT_VERSION:
        newer = isinstance(version, int) and version > _FORMAT_VERSION
        raise ValueError(
            f"checkpoint {path!r} has format version {version!r}; this "
            f"build reads version {_FORMAT_VERSION}"
            + (
                " — the file was written by a newer reservoir_tpu; upgrade "
                "this installation to restore it"
                if newer
                else ""
            )
        )
    return arrays, manifest


# ------------------------------------------------------------- epoch fencing


def read_epoch(directory: str) -> int:
    """The primary epoch persisted in a checkpoint directory (0 when none
    was ever written).  A writer admitted at epoch E must refuse durable
    writes once the persisted epoch exceeds E (the HA plane's split-brain
    fence, :class:`~reservoir_tpu.errors.FencedError`)."""
    try:
        with open(os.path.join(directory, _EPOCH_NAME), encoding="utf-8") as fh:
            return int(json.load(fh)["epoch"])
    except FileNotFoundError:
        return 0
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise CheckpointCorrupt(
            f"epoch file in {directory!r} is unreadable "
            f"({type(e).__name__}: {e})"
        ) from e


def write_epoch(directory: str, epoch: int) -> int:
    """Persist ``epoch`` atomically (temp file + rename, fsynced file AND
    directory: the fence must survive an OS crash — an un-durable epoch
    bump could un-fence the old primary on reboot)."""
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp.epoch")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump({"epoch": int(epoch)}, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, os.path.join(directory, _EPOCH_NAME))
        dir_fd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return int(epoch)


def advance_epoch(directory: str) -> int:
    """Bump and persist the primary epoch; returns the new value.  This is
    the fencing half of a failover promotion: every writer admitted at an
    older epoch fails its next durable write with ``FencedError``."""
    return write_epoch(directory, read_epoch(directory) + 1)


def save_state(path: str, state: Any, metadata: Optional[dict] = None) -> None:
    """Write one state pytree (``ReservoirState`` / ``DistinctState`` /
    ``WeightedState``) to ``path`` atomically.  ``metadata`` (JSON-able) rides
    along and comes back from :func:`load_state`."""
    arrays, manifest = _pack_state(state)
    manifest["format_version"] = _FORMAT_VERSION
    manifest["metadata"] = metadata or {}
    _atomic_write_npz(path, arrays, manifest)


def load_state(path: str, with_metadata: bool = False):
    """Restore a state pytree saved by :func:`save_state`; the returned state
    resumes sampling bit-exactly (counter-keyed draws carry no hidden host
    RNG)."""
    arrays, manifest = _read_npz(path)
    state = _unpack_state(arrays, manifest)
    return (state, manifest["metadata"]) if with_metadata else state


# ------------------------------------------------------------------ engines


def _config_to_jsonable(config) -> dict:
    import jax.numpy as jnp

    d = dataclasses.asdict(config)
    for key, value in d.items():
        if key.endswith("_dtype") and value is not None:
            # "wide" is a count-dtype sentinel (emulated-uint64 planes),
            # not a numpy dtype — persist it verbatim
            d[key] = value if value == "wide" else jnp.dtype(value).name
    return d


def save_engine(path: str, engine, metadata: Optional[dict] = None) -> None:
    """Checkpoint a live :class:`~reservoir_tpu.engine.ReservoirEngine`:
    state + config + lifecycle, enough to :func:`load_engine` and continue
    streaming exactly where it stopped.

    ``map_fn`` / ``hash_fn`` are code, not data — they are recorded only as
    present/absent and must be re-supplied to :func:`load_engine`.
    """
    engine._check_open()
    arrays, manifest = _pack_state(engine._state)
    manifest["format_version"] = _FORMAT_VERSION
    manifest["metadata"] = metadata or {}
    import jax

    manifest["engine"] = {
        "config": _config_to_jsonable(engine.config),
        "reusable": engine._reusable,
        "min_count": engine._min_count,
        "has_map_fn": engine._map_fn is not None,
        "has_hash_fn": engine._hash_fn is not None,
        # the backend this checkpoint was taken on: the recovery pre-flight
        # names it when a restore lands on an incompatible mesh
        "backend": {
            "platform": jax.default_backend(),
            "device_count": jax.device_count(),
        },
    }
    # telemetry (ISSUE 6): the write is traced (Perfetto shows
    # `reservoir_checkpoint_write` next to the flush spans) and, when the
    # registry is enabled, timed into `checkpoint.write_s`
    reg = _obs.get()
    t0 = time.perf_counter() if reg is not None else 0.0
    with trace_span("reservoir_checkpoint_write"):
        _atomic_write_npz(path, arrays, manifest)
    if reg is not None:
        reg.histogram("checkpoint.write_s").observe(time.perf_counter() - t0)


def read_engine_metadata(path: str) -> dict:
    """The ``metadata`` dict a checkpoint was saved with, WITHOUT restoring
    the engine (no jax state construction — the journal follower polls this
    to learn a newer checkpoint's flush watermark cheaply)."""
    try:
        with np.load(path) as data:
            if "__manifest__" not in data.files:
                raise CheckpointCorrupt(
                    f"{path!r} has no checkpoint manifest"
                )
            manifest = json.loads(bytes(data["__manifest__"]).decode())
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, EOFError, OSError, KeyError, ValueError) as e:
        if isinstance(e, CheckpointCorrupt):
            raise
        raise CheckpointCorrupt(
            f"checkpoint {path!r} is truncated or corrupt "
            f"({type(e).__name__}: {e})"
        ) from e
    return manifest.get("metadata", {})


#: State fields whose second dimension is the sample capacity ``k`` — the
#: pre-flight checks these against ``config.max_sample_size``.
_K_FIELDS = frozenset({"samples", "values", "lkeys", "hash_hi", "hash_lo"})


def _preflight(path: str, config, arrays: dict, manifest: dict) -> None:
    """Typed recovery pre-flight: refuse a restore whose state arrays or
    backend requirements cannot match, naming the mismatch, instead of an
    opaque shape/compile error deep inside XLA."""
    R = config.num_reservoirs
    for field in manifest.get("fields", ()):
        if field.get("kind") == "none":
            continue
        name = field["name"]
        arr = arrays.get(name)
        if arr is None:
            raise CheckpointCorrupt(
                f"checkpoint {path!r}: state field {name!r} listed in the "
                "manifest is missing from the archive"
            )
        if arr.ndim < 1 or arr.shape[0] != R:
            raise CheckpointMismatch(
                f"checkpoint {path!r}: state field {name!r} has leading "
                f"dimension {arr.shape[0] if arr.ndim else '<scalar>'}, but "
                f"the recorded config has num_reservoirs={R}"
            )
        if name in _K_FIELDS and arr.ndim >= 2 and (
            arr.shape[1] != config.max_sample_size
        ):
            raise CheckpointMismatch(
                f"checkpoint {path!r}: state field {name!r} has sample "
                f"capacity {arr.shape[1]}, but the recorded config has "
                f"max_sample_size={config.max_sample_size}"
            )
    if config.mesh_axis is not None:
        import jax

        live = jax.device_count()
        if R % live:
            saved = (manifest.get("engine") or {}).get("backend") or {}
            was = (
                f"; it was taken on {saved['device_count']} "
                f"{saved.get('platform', '?')} device(s)"
                if saved.get("device_count")
                else ""
            )
            raise CheckpointMismatch(
                f"checkpoint {path!r} shards {R} reservoirs over mesh axis "
                f"{config.mesh_axis!r}, which does not divide evenly over "
                f"the {live} device(s) of the live backend{was}"
            )


def load_engine(
    path: str,
    map_fn: Optional[Callable] = None,
    hash_fn: Optional[Callable] = None,
    engine_cls: Optional[type] = None,
    *,
    with_metadata: bool = False,
):
    """Reconstruct a checkpointed engine.  Raises if the checkpoint was taken
    with a ``map_fn``/``hash_fn`` and none is supplied (or vice versa) — a
    silent mismatch would quietly change what gets stored.  ``engine_cls``
    lets ``SubEngine.restore(path)`` come back as the subclass.
    ``with_metadata=True`` returns ``(engine, metadata)`` — the bridge's
    recovery path reads its journal watermark from there."""
    from ..config import SamplerConfig
    from ..engine import ReservoirEngine

    arrays, manifest = _read_npz(path)
    info = manifest.get("engine")
    if info is None:
        raise ValueError(
            f"{path!r} is a bare state checkpoint; use load_state()"
        )
    for flag, fn, name in (
        ("has_map_fn", map_fn, "map_fn"),
        ("has_hash_fn", hash_fn, "hash_fn"),
    ):
        if info[flag] != (fn is not None):
            raise ValueError(
                f"checkpoint was saved with {name} "
                f"{'present' if info[flag] else 'absent'}; restore must match"
            )
    config = SamplerConfig(**info["config"])
    _preflight(path, config, arrays, manifest)
    engine = (engine_cls or ReservoirEngine)(
        config,
        map_fn=map_fn,
        hash_fn=hash_fn,
        reusable=info["reusable"],
        _initial_state=_unpack_state(arrays, manifest),
    )
    engine._min_count = info["min_count"]
    if with_metadata:
        return engine, manifest["metadata"]
    return engine
