"""THE one copy of the backend-liveness probe contract.

The axon TPU tunnel fails two ways: a fast ``RuntimeError: ...
UNAVAILABLE`` and a silent hang inside ``jax.devices()`` (observed
2026-07-29; outages last 10+ hours).  A hang in the caller's process is
unrecoverable, so liveness is always checked in a THROWAWAY subprocess
with a hard timeout.  ``bench.py``, ``tools/tpu_watch.py`` and
:mod:`.selftest` all import this module — a tweak for the tunnel's next
failure mode lands in exactly one place.
"""

from __future__ import annotations

import subprocess
import sys

__all__ = ["PROBE_SNIPPET", "probe_backend_proc"]

PROBE_SNIPPET = (
    "import jax, sys; d = jax.devices(); "
    "x = jax.numpy.zeros((8,)); float(x.sum()); "
    "sys.stdout.write(d[0].platform)"
)


def probe_backend_proc(timeout_s: float):
    """Probe the default backend in a throwaway subprocess.

    Returns the platform string (e.g. ``"tpu"``) on success, None on
    failure or hang.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c", PROBE_SNIPPET],
            capture_output=True,
            timeout=timeout_s,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    return proc.stdout.strip() or None
