"""THE one copy of the backend-liveness probe contract.

The axon TPU tunnel fails two ways: a fast ``RuntimeError: ...
UNAVAILABLE`` and a silent hang inside ``jax.devices()`` (observed
2026-07-29; outages last 10+ hours).  A hang in the caller's process is
unrecoverable, so liveness is always checked in a THROWAWAY subprocess
with a hard timeout.  ``bench.py``, ``tools/tpu_watch.py`` and
:mod:`.selftest` all import this module — a tweak for the tunnel's next
failure mode lands in exactly one place.
"""

from __future__ import annotations

import subprocess
import sys

__all__ = ["PROBE_SNIPPET", "probe_backend_proc"]

PROBE_SNIPPET = (
    "import jax, sys; d = jax.devices(); "
    "x = jax.numpy.zeros((8,)); float(x.sum()); "
    "sys.stdout.write(d[0].platform)"
)


def probe_backend_proc(timeout_s: float, platform: "str | None" = None):
    """Probe the default backend in a throwaway subprocess.

    Returns the platform string (e.g. ``"tpu"``) on success, None on
    failure or hang.  ``platform``: pin the child to a jax_platforms
    string (e.g. ``"cpu"``, ``"tpu,cpu"``) via the in-process config
    update — the ONLY pin that works here (the axon sitecustomize
    overrides the ``JAX_PLATFORMS`` env var).
    """
    snippet = PROBE_SNIPPET
    if platform is not None:
        snippet = (
            f"import jax; jax.config.update('jax_platforms', {platform!r}); "
            + snippet
        )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", snippet],
            capture_output=True,
            timeout=timeout_s,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    return proc.stdout.strip() or None
