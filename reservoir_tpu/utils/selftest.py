"""On-backend Pallas==XLA parity selftest (VERDICT r2 item 2).

The interpret-mode suites pin the *algorithms*; the three Mosaic-only
lowering bugs found in round 2 (BENCH.md) proved the CPU interpreter hides
real failure modes.  ``tests/test_pallas_device.py`` covers hardware but is
device-gated — skipped in CI and absent from driver artifacts.  This module
packages the same bit-equality checks (four of them) as a cheap callable so
the bench artifact itself can prove ``pallas == xla`` on the chip: ``bench.py``
embeds the result dict into its one JSON line, and
``__graft_entry__.device_selftest()`` exposes it to the driver directly.

Checks:
  - algl:      steady-state tile update, int32 samples
  - algl_fill: fill + fill-completing tiles through the fill-capable
               kernel (r4: impl='pallas' covers the whole life cycle)
  - distinct:  bottom-k insert/shift over duplicated keys, 3 chained steps
  - weighted:  A-ExpJ accept/evict with zero-weight lanes

Each check compares every leaf of the resulting state pytrees with
bit-exact ``array_equal``.  Shapes are backend-dependent: on TPU the
production block sizes (R=64 rows x B=256, Mosaic-compiled, a few seconds
each); on the CPU *interpreter* the same shapes take many MINUTES (measured
>15 min for the original trio), so CPU runs shrink to the interpret-suite shapes
(R=8, B=64) — still the same trace, still bit-exact, just sized for the
interpreter.  Callers that must never hang (driver entry points, bench)
run this in a subprocess with a hard timeout — see
``__graft_entry__.device_selftest`` and ``bench.py``.
"""

from __future__ import annotations

from typing import Any, Dict

__all__ = ["device_selftest", "device_selftest_subprocess"]


def _shapes(interpret: bool):
    """(R, block_r, B) — production blocks on hardware, tiny on interpreter.

    On hardware the algl row-block follows ``RESERVOIR_BENCH_BLOCK_R``
    (the bench's own knob) so a selftest embedded in a non-default-block
    capture — e.g. the sweep winner's re-capture — proves parity at the
    exact kernel shape that produced the number."""
    import os

    if interpret:
        return (8, 8, 64)
    try:
        block_r = int(os.environ.get("RESERVOIR_BENCH_BLOCK_R", 64))
    except ValueError:
        block_r = 64
    if block_r <= 0:  # 0 = the bench's auto-pick sentinel
        block_r = 64
    return (max(8, block_r), max(8, block_r), 256)


def _leaves_equal(a, b) -> bool:
    import jax
    import jax.random as jr
    import numpy as np

    def mat(x):
        if hasattr(x, "dtype") and jax.dtypes.issubdtype(
            x.dtype, jax.dtypes.prng_key
        ):
            x = jr.key_data(x)
        return np.asarray(x)

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    if len(la) != len(lb):
        return False
    return all(np.array_equal(mat(x), mat(y)) for x, y in zip(la, lb))


def _check_algl(interpret: bool) -> bool:
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from ..ops import algorithm_l as al
    from ..ops import algorithm_l_pallas as alp

    R, block_r, B = _shapes(interpret)
    k = 128 if not interpret else 16
    state = al.init(jr.key(0), R, k)
    fill = jax.lax.broadcasted_iota(jnp.int32, (R, max(B, k)), 1)
    state = al.update(state, fill)
    batch = 10_000 + jax.lax.broadcasted_iota(jnp.int32, (R, B), 1)
    ref = al.update_steady(state, batch)
    got = alp.update_steady_pallas(
        state, batch, block_r=block_r, interpret=interpret
    )
    return _leaves_equal(ref, got)


def _check_algl_fill(interpret: bool) -> bool:
    """The fill-capable kernel across the life-cycle boundary (r4:
    impl='pallas' covers fill): ``k`` is chosen in ``(B, 2B)`` so tile 1
    is a pure fill and tile 2 ENTERS with ``0 < count < k`` — exercising
    the count-offset fill scatter (``dest = count + lane``) — and
    completes the fill MID-tile, with steady accepts in the same tile.
    Bit-equal to the XLA ``update`` chain after each tile."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from ..ops import algorithm_l as al
    from ..ops import algorithm_l_pallas as alp

    R, block_r, B = _shapes(interpret)
    k = 384 if not interpret else 96  # B < k < 2B: boundary mid-tile 2
    ref = pal = al.init(jr.key(8), R, k)
    for t in range(2):
        batch = 1 + t * B + jax.lax.broadcasted_iota(jnp.int32, (R, B), 1)
        ref = al.update(ref, batch)
        pal = alp.update_pallas(
            pal, batch, block_r=block_r, interpret=interpret
        )
        if not _leaves_equal(ref, pal):
            return False
    return True


def _check_distinct(interpret: bool) -> bool:
    import jax.numpy as jnp
    import jax.random as jr

    from ..ops import distinct as dd
    from ..ops import distinct_pallas as dp

    R, _, B = _shapes(interpret)
    k = 64 if not interpret else 8
    s_ref = s_pal = dd.init(jr.key(6), R, k)
    for step in range(3):
        batch = jr.randint(
            jr.fold_in(jr.key(7), step), (R, B), 0, 500, jnp.int32
        )
        s_ref = dd.update(s_ref, batch)
        s_pal = dp.update_pallas(
            s_pal, batch, block_r=8 if interpret else None,
            interpret=interpret,
        )
        if not _leaves_equal(s_ref, s_pal):
            return False
    return True


def _check_weighted(interpret: bool) -> bool:
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from ..ops import weighted as ww
    from ..ops import weighted_pallas as wp

    R, _, B = _shapes(interpret)
    k = 64 if not interpret else 8
    state = ww.init(jr.key(3), R, k)
    elems = jax.lax.broadcasted_iota(jnp.int32, (R, B), 1)
    weights = jr.randint(jr.key(4), (R, B), 1, 5).astype(jnp.float32)
    weights = weights * (jr.uniform(jr.key(5), (R, B)) > 0.2)
    ref = ww.update(state, elems, weights)
    got = wp.update_pallas(
        state, elems, weights, block_r=8 if interpret else None,
        interpret=interpret,
    )
    return _leaves_equal(ref, got)


def _check_gated(interpret: bool) -> bool:
    """Gated-vs-ungated bridge bit-parity on the live backend (ISSUE 8).

    The ingest-side skip gate's host replica runs jitted on the CPU
    backend while the engine runs on whatever backend serves — on CPU the
    two are the same compiled math (bit-identical by construction, the
    tier-1 pin); on TPU this check is the OPEN question the capture rows
    exist to answer: do the host-CPU and TPU transcendentals agree to the
    last ulp across a real stream?  The result rides the ``parity_probe``
    selftest JSON as ``gated_parity`` — a pinned capture row instead of
    the r04-era null."""
    import numpy as np

    from ..config import SamplerConfig
    from ..stream.bridge import DeviceStreamBridge

    S, k, B = (8, 8, 64) if interpret else (64, 16, 256)
    rounds = 8
    rng = np.random.default_rng(12)
    data = rng.integers(0, 1 << 30, (S, rounds * B)).astype(np.int32)
    results = []
    for gated in (False, True):
        cfg = SamplerConfig(max_sample_size=k, num_reservoirs=S, tile_size=B)
        bridge = DeviceStreamBridge(cfg, key=5, gated=gated, gate_tile=32)
        for s in range(S):
            bridge.push(s, data[s])
        results.append(bridge.complete())
    return all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(results[0], results[1])
    )


def _check_merge(interpret: bool) -> bool:
    """Device-collective-vs-host merge-tree bit-parity (ISSUE 12).

    ``merge_samples_device`` on ``impl="auto"`` — the Pallas
    ``make_async_remote_copy`` ring on TPU, XLA ``all_gather`` elsewhere —
    must match the host pairwise tree bit-for-bit across all three modes
    and a non-power-of-two part count (the odd-leftover carry is the tree
    shape most worth pinning on real interconnect)."""
    import numpy as np

    import jax.random as jr

    from ..ops import distinct as dd
    from ..ops import weighted as ww
    from ..parallel.merge import merge_samples_device, merge_samples_host

    del interpret  # same shapes everywhere: the collective is plain XLA
    k, n_parts = 8, 5
    rng = np.random.default_rng(21)
    uparts = [
        (rng.integers(0, 1 << 30, k).astype(np.int32), int(rng.integers(k, 6 * k)))
        for _ in range(n_parts)
    ]
    want, wt = merge_samples_host(uparts, 17, max_sample_size=k)
    got, gt = merge_samples_device(uparts, 17, max_sample_size=k)
    if gt != wt or not np.array_equal(got, want):
        return False
    wparts = []
    for p in range(n_parts):
        st = ww.update(
            ww.init(jr.key(200 + p), 1, k),
            (p * 1000 + np.arange(3 * k, dtype=np.int32))[None],
            (1.0 + np.arange(3 * k, dtype=np.float32) % 7)[None],
        )
        wparts.append(
            (
                np.asarray(st.samples)[0],
                np.asarray(st.lkeys)[0],
                int(np.asarray(st.count)[0]),
            )
        )
    for a, b in zip(
        merge_samples_device(wparts, max_sample_size=k, mode="weighted"),
        merge_samples_device(
            wparts, max_sample_size=k, mode="weighted", impl="host"
        ),
    ):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            return False
    dparts = []
    for p in range(n_parts):
        st = dd.update(
            dd.init(jr.key(77), 1, k),  # shared salts: one logical stream
            (p * 1000 + np.arange(4 * k, dtype=np.int32))[None],
        )
        dparts.append(
            (
                np.asarray(st.values)[0],
                np.asarray(st.hash_hi)[0],
                np.asarray(st.hash_lo)[0],
                int(np.asarray(st.size)[0]),
                int(np.asarray(st.count)[0]),
                np.asarray(st.salts)[0],
            )
        )
    for a, b in zip(
        merge_samples_device(dparts, max_sample_size=k, mode="distinct"),
        merge_samples_device(
            dparts, max_sample_size=k, mode="distinct", impl="host"
        ),
    ):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            return False
    return True


def _check_ks(interpret: bool):
    """On-backend statistical-quality gate: pooled one-sample KS of the
    device sampler's output against the exact uniform law, at the literal
    BASELINE 1% gate (``tests/test_ks_gate.py`` is the CPU-CI twin; this
    copy runs on whatever backend serves the selftest so the bench
    artifact carries the gate from real hardware).  Pool N = R*k =
    131,072 puts the null 95th percentile ~2.7x below the gate
    (false-fail ~1e-11).  Combined with the bit-parity checks above, the
    gate covers the Pallas kernels transitively.

    Returns ``(ks_distance, ok)``.
    """
    import jax
    import jax.numpy as jnp
    import jax.random as jr
    import numpy as np

    from ..ops import algorithm_l as al
    from .stats import KS_GATE, ks_one_sample_uniform

    # Same shapes on every backend: the check is plain XLA (fast even on
    # CPU — the interpreter shrink only matters for Pallas checks), and a
    # smaller pool would put the null KS scale ABOVE the 1% gate.
    del interpret
    R, k, n, B = 2048, 64, 8192, 512
    state = al.init(jr.key(0), R, k)
    fn = jax.jit(al.update, donate_argnums=0)
    for start in range(0, n, B):
        batch = start + jax.lax.broadcasted_iota(jnp.int32, (R, B), 1)
        state = fn(state, batch)
    samples, sizes = al.result(state)
    assert int(np.asarray(sizes).min()) == k
    ks = ks_one_sample_uniform(np.asarray(samples).ravel(), n)
    return ks, ks < KS_GATE


def _check_ks_distinct():
    """On-backend twin of ``tests/test_ks_gate.py::
    test_distinct_mode_ks_uniform_over_distinct_values`` (VERDICT r4 item
    6): inclusion uniform over DISTINCT values of a 2x-repeated stream
    (``Sampler.scala:394-408`` semantics), same pool (N = R*k = 65,536,
    null 95th pct ~0.0053) and the same literal 1% gate."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr
    import numpy as np

    from ..ops import distinct as dd
    from .stats import KS_GATE, ks_one_sample_uniform

    R, k, n, B = 2048, 32, 2048, 256
    state = dd.init(jr.key(2), R, k)
    fn = jax.jit(dd.update, donate_argnums=0)
    for _rep in range(2):  # every value appears twice
        for start in range(0, n, B):
            batch = start + jax.lax.broadcasted_iota(jnp.int32, (R, B), 1)
            state = fn(state, batch)
    samples, sizes = dd.result(state)
    assert int(np.asarray(sizes).min()) == k
    ks = ks_one_sample_uniform(np.asarray(samples).ravel(), n)
    return ks, ks < KS_GATE


def _check_ks_weighted():
    """On-backend twin of ``tests/test_ks_gate.py::
    test_weighted_mode_ks_uniform_when_weights_equal``: equal weights
    degrade A-ExpJ to uniform sampling, gated at the same 1% bound
    (N = R*k = 65,536)."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr
    import numpy as np

    from ..ops import weighted as ww
    from .stats import KS_GATE, ks_one_sample_uniform

    R, k, n, B = 2048, 32, 4096, 512
    state = ww.init(jr.key(3), R, k)
    fn = jax.jit(ww.update, donate_argnums=0)
    for start in range(0, n, B):
        batch = start + jax.lax.broadcasted_iota(jnp.int32, (R, B), 1)
        state = fn(state, batch, jnp.ones((R, B), jnp.float32))
    samples, sizes = ww.result(state)
    assert int(np.asarray(sizes).min()) == k
    ks = ks_one_sample_uniform(np.asarray(samples).ravel(), n)
    return ks, ks < KS_GATE


def device_selftest(emit_partial=None) -> Dict[str, Any]:
    """Run every parity check on the live backend.

    Returns ``{"platform": ..., "algl": bool, "algl_fill": bool,
    "distinct": bool, "weighted": bool, "pallas_parity": bool,
    "gated_parity": bool, "merge_parity": bool,
    "ks_ok": bool, ["ks_uniform": float],
    "ks_distinct_ok": bool, ["ks_distinct": float],
    "ks_weighted_ok": bool, ["ks_weighted": float],
    ["<name>_error": str], ["ks*_error": str]}`` — never raises; a crash
    in any check is recorded as failure with the message under its own
    ``*_error`` key (the ``ks*`` distance keys are absent when that KS
    check itself crashed).  ``pallas_parity`` is strictly the AND of the
    bit-equality checks; the three KS gates (algl uniform, distinct-mode
    uniform-over-distinct, weighted equal-weight uniform — VERDICT r4
    item 6) report separately, each at the literal 1% BASELINE bound.

    ``emit_partial``: optional callable invoked with a COPY of the result
    dict after each completed stage (parity block, then each KS gate).
    A subprocess caller prints these as they land so a wall-clock cap
    hit mid-KS salvages the parity evidence instead of erasing it (the
    r4 failure mode: one timeout cost the round its parity bit).
    """
    import jax

    platform = jax.default_backend()
    interpret = platform == "cpu"  # Mosaic lowers on TPU only
    out: Dict[str, Any] = {"platform": platform}

    def _stage_done():
        if emit_partial is not None:
            try:
                emit_partial(dict(out))
            except Exception:
                pass  # progress reporting must never kill the checks

    ok = True
    for name, fn in (
        ("algl", _check_algl),
        ("algl_fill", _check_algl_fill),
        ("distinct", _check_distinct),
        ("weighted", _check_weighted),
    ):
        try:
            out[name] = bool(fn(interpret))
        except Exception as e:  # lowering/runtime regression — record it
            out[name] = False
            out[f"{name}_error"] = f"{type(e).__name__}: {e}"[:500]
        ok = ok and out[name]
    out["pallas_parity"] = ok
    _stage_done()
    # gated-vs-ungated bridge parity (ISSUE 8): separate key — on TPU it
    # additionally crosses host-CPU-vs-device transcendentals, and that
    # empirical answer must not erase the Pallas bit-parity evidence
    try:
        out["gated_parity"] = bool(_check_gated(interpret))
    except Exception as e:
        out["gated_parity"] = False
        out["gated_parity_error"] = f"{type(e).__name__}: {e}"[:500]
    _stage_done()
    # device-collective-vs-host merge-tree parity (ISSUE 12): on TPU this
    # is the Pallas ring permute's bit evidence; separate key so a
    # collective regression can't erase the kernel parity bits above
    try:
        out["merge_parity"] = bool(_check_merge(interpret))
    except Exception as e:
        out["merge_parity"] = False
        out["merge_parity_error"] = f"{type(e).__name__}: {e}"[:500]
    _stage_done()
    try:
        out["ks_uniform"], out["ks_ok"] = _check_ks(interpret)
    except Exception as e:
        out["ks_ok"] = False
        out["ks_error"] = f"{type(e).__name__}: {e}"[:500]
    _stage_done()
    for name, fn in (
        ("ks_distinct", _check_ks_distinct),
        ("ks_weighted", _check_ks_weighted),
    ):
        try:
            out[name], out[f"{name}_ok"] = fn()
        except Exception as e:
            out[f"{name}_ok"] = False
            out[f"{name}_error"] = f"{type(e).__name__}: {e}"[:500]
        _stage_done()
    return out


def device_selftest_subprocess(
    timeout_s: float = 900.0,
    skip_probe: bool = False,
    platform: "str | None" = None,
) -> Dict[str, Any]:
    """Run :func:`device_selftest` in a throwaway subprocess.

    The in-process variant can hang with the whole caller: backend init
    over a dead tunnel hangs inside ``jax.devices()``, and a Mosaic hang
    mid-kernel is unkillable from Python.  Drivers and ``bench.py`` call
    this wrapper instead — a hang costs ``timeout_s`` and is *recorded*,
    never inherited.

    ``skip_probe``: the tunneled backend admits ONE client at a time, so
    the liveness pre-probe is a false negative whenever the caller's
    process (or a sibling) holds the client.  A caller that has itself
    just probed successfully — and has NOT yet initialized its own
    in-process backend — passes ``skip_probe=True`` and the child goes
    straight to work (bench.py runs the selftest in exactly that gap;
    r4: the post-run selftest always failed its probe because the bench
    parent still held the tunnel client even after ``clear_backends``).

    ``platform``: pin the child (and its probe) to a jax_platforms
    string so a pinned-platform caller gets evidence from the backend it
    is actually measuring, not the process default (the axon
    sitecustomize overrides ``JAX_PLATFORMS``, so the pin rides an
    in-process config update in the child).
    """
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    # fast liveness probe first: a dead tunnel must cost ~a minute, not
    # the full selftest timeout (backend init hangs inside jax.devices())
    from .probe import probe_backend_proc

    if not skip_probe and probe_backend_proc(60.0, platform) is None:
        return {
            "pallas_parity": False,
            "error": "backend unreachable (probe failed/hung)",
        }
    # The child prints a JSON line after EVERY completed stage (parity
    # block, then each KS gate) and the parent keeps the last parseable
    # one — so a timeout mid-KS salvages the parity evidence instead of
    # erasing it (r4: one 900 s timeout cost the round its parity bit).
    pin = (
        f"import jax; jax.config.update('jax_platforms', {platform!r})\n"
        if platform is not None
        else ""
    )
    code = (
        pin
        + "import json, sys\n"
        "from reservoir_tpu.utils.selftest import device_selftest\n"
        "def _p(d):\n"
        "    sys.stdout.write(json.dumps(d) + '\\n'); sys.stdout.flush()\n"
        "_p(device_selftest(emit_partial=_p))\n"
    )

    def _last_json(text_out):
        if isinstance(text_out, bytes):
            text_out = text_out.decode(errors="replace")
        for line in reversed((text_out or "").splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
        return None

    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            timeout=timeout_s,
            text=True,
            cwd=repo,
        )
    except subprocess.TimeoutExpired as e:
        salvaged = _last_json(e.stdout)
        if salvaged is not None:
            salvaged["partial"] = (
                f"timed out after {timeout_s:.0f}s; last completed stage kept"
            )
            return salvaged
        return {
            "pallas_parity": False,
            "error": f"selftest subprocess timed out after {timeout_s:.0f}s",
        }
    parsed = _last_json(proc.stdout)
    if parsed is not None:
        if proc.returncode != 0:
            # the child died AFTER emitting this stage (e.g. a Mosaic
            # segfault mid-KS — the hazard the isolation exists for):
            # keep the completed-stage evidence but never pass it off
            # as a clean full run
            parsed["partial"] = (
                f"child crashed rc={proc.returncode} after last emitted "
                "stage: " + proc.stderr[-300:]
            )
        return parsed
    return {
        "pallas_parity": False,
        "error": (
            f"selftest subprocess rc={proc.returncode}: "
            + proc.stderr[-300:]
        ),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(device_selftest()))
