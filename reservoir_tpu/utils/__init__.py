"""Auxiliary subsystems: metrics, tracing, checkpointing (SURVEY §5).

The reference has none of these (no logging/metrics dependency, no tracing
hooks, no checkpointing — SURVEY §5 table); they are mandated additions for
the TPU framework.  Everything here is dependency-light and optional: the
core sampling path never requires this package.
"""

from .checkpoint import load_engine, load_state, save_engine, save_state
from .metrics import BridgeMetrics
from .tracing import trace_span

__all__ = [
    "BridgeMetrics",
    "load_engine",
    "load_state",
    "save_engine",
    "save_state",
    "trace_span",
]
