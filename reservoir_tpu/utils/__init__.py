"""Auxiliary subsystems: metrics, tracing, checkpointing, fault injection
(SURVEY §5).

The reference has none of these (no logging/metrics dependency, no tracing
hooks, no checkpointing, no fault injection — SURVEY §5 table); they are
mandated additions for the TPU framework.  Everything here is
dependency-light and optional: the core sampling path never requires this
package, and the fault plane (:mod:`reservoir_tpu.utils.faults`) is a
zero-overhead no-op unless explicitly installed.
"""

from .checkpoint import load_engine, load_state, save_engine, save_state
from .faults import FaultPlane, FaultRule
from .metrics import BridgeMetrics
from .tracing import trace_span

__all__ = [
    "BridgeMetrics",
    "FaultPlane",
    "FaultRule",
    "load_engine",
    "load_state",
    "save_engine",
    "save_state",
    "trace_span",
]
