"""Profiler scopes + capture harness (SURVEY §5 "Tracing" row).

The reference ships no tracing; its perf story is the JVM inliner.  Here the
story is XLA + the JAX profiler: named ``TraceAnnotation`` scopes make bridge
flushes and result gathers visible in a Perfetto trace captured with
:func:`profile_capture`.  Falls back to no-ops when the profiler is
unavailable so the hot path never depends on it.

Workflow (the documented harness VERDICT r1 flagged as missing)::

    from reservoir_tpu.utils.tracing import profile_capture

    with profile_capture("/tmp/reservoir-trace"):
        engine.sample(tile)            # spans: reservoir_bridge_flush, ...
        engine.result_arrays()

    # open ui.perfetto.dev -> load the .trace.json.gz under
    # /tmp/reservoir-trace/plugins/profile/*/  (or `tensorboard
    # --logdir /tmp/reservoir-trace` with the profile plugin)

Every bridge flush (``reservoir_bridge_flush``) and result gather
(``reservoir_bridge_result``) is already annotated; wrap additional regions
with :func:`trace_span`.  ``RESERVOIR_TPU_TRACE_DIR`` makes :func:`maybe_profile`
capture without code changes — the env hook ``bench.py`` and tests use.
"""

from __future__ import annotations

import contextlib
import os
from typing import ContextManager, Iterator, Optional

__all__ = ["trace_span", "profile_capture", "maybe_profile"]


def trace_span(name: str) -> ContextManager[None]:
    """A named profiler scope (no-op if the JAX profiler is unavailable)."""
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler always present with jax
        return contextlib.nullcontext()


@contextlib.contextmanager
def profile_capture(log_dir: str, host_tracer_level: int = 2) -> Iterator[str]:
    """Capture a Perfetto/XPlane trace of the enclosed region into
    ``log_dir`` (viewable in Perfetto or TensorBoard's profile plugin).

    Yields the log dir.  Exceptions inside the region still stop the trace
    (the capture is flushed, not lost) — a failed run is exactly when the
    trace matters.
    """
    import jax.profiler

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir, create_perfetto_trace=False)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def maybe_profile(default: Optional[str] = None) -> ContextManager[object]:
    """:func:`profile_capture` gated on ``RESERVOIR_TPU_TRACE_DIR`` (or
    ``default``): no env var, no-op — drop-in for always-on code paths."""
    log_dir = os.environ.get("RESERVOIR_TPU_TRACE_DIR", default)
    if not log_dir:
        return contextlib.nullcontext()
    return profile_capture(log_dir)
