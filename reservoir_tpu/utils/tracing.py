"""Profiler scopes around bridge/kernel dispatch (SURVEY §5 "Tracing" row).

The reference ships no tracing; its perf story is the JVM inliner.  Here the
story is XLA + the JAX profiler: named ``TraceAnnotation`` scopes make bridge
flushes and result gathers visible in a Perfetto trace captured with
``jax.profiler.start_trace``.  Falls back to a no-op context manager when the
profiler is unavailable so the hot path never depends on it.
"""

from __future__ import annotations

import contextlib
from typing import ContextManager


def trace_span(name: str) -> ContextManager[None]:
    """A named profiler scope (no-op if the JAX profiler is unavailable)."""
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler always present with jax
        return contextlib.nullcontext()
