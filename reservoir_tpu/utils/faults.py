"""Deterministic fault-injection plane (SURVEY §5 failure-detection row).

The reference's only failure machinery is the stream completion protocol
(``SampleImpl.scala:43-57``); nothing in it — or in this framework before
this module — was ever *tested under injected faults*.  This plane makes
failure a first-class, reproducible input: named injection sites sit on the
hot paths (:data:`SITES`), and a :class:`FaultPlane` holds a seeded schedule
of :class:`FaultRule` entries saying which site fails, when (step
predicate), how (exception type or a delay simulating a hung device), and
how often.

Activation is explicit and doubly scoped:

- **globally** via :func:`install` / the :func:`active` context manager /
  the ``RESERVOIR_FAULTS`` env spec (parsed once at import;
  :func:`install_from_env` re-reads it), reaching every site including
  ``checkpoint.write`` and ``native.staging``;
- **per-bridge/engine** by passing a plane to
  :class:`~reservoir_tpu.stream.bridge.DeviceStreamBridge` /
  :class:`~reservoir_tpu.engine.ReservoirEngine` (``faults=``), reaching the
  ``bridge.*`` and ``engine.*`` sites of that instance only.

When nothing is installed, every site is a no-op: :func:`fire` is one
module-global load and an ``is None`` test — no allocation, no locking, no
counter traffic (pinned by ``tests/test_faults.py``).

Env spec grammar (semicolon-separated rules; keys after the site are
comma-separated ``key=value`` pairs)::

    RESERVOIR_FAULTS="seed=7;bridge.dispatch:exc=TransientDeviceError,times=2;engine.update:exc=RuntimeError,after=10,every=5"

``exc`` names an exception from :mod:`reservoir_tpu.errors`, a builtin, or
``none`` for a delay-only rule (a simulated hang for the watchdog).
"""

from __future__ import annotations

import builtins
import contextlib
import dataclasses
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple, Union

__all__ = [
    "SITES",
    "InjectedFault",
    "FaultRule",
    "FaultPlane",
    "fire",
    "install",
    "uninstall",
    "active",
    "install_from_env",
    "from_spec",
]

#: The named injection sites wired into the runtime.  ``bridge.*`` fire on
#: the stream bridge's demux (producer thread) and device dispatch (worker
#: thread), ``engine.update`` on every engine tile update, ``engine.pallas``
#: only when a tile is about to dispatch to a Pallas kernel (the demotion
#: trigger), ``checkpoint.write`` inside the atomic checkpoint writer,
#: ``native.staging`` on the staging buffer's push/drain paths, and
#: ``serve.ingest`` on the serving plane's per-session ingest (surfaced to
#: the caller as a typed per-session error — the service stays live).
#: The HA plane (ISSUE 5) adds ``replica.ship`` (the journal follower's
#: read/tail path), ``replica.apply`` (applying one shipped tile to the
#: standby engine — state advances only on success, so an injected failure
#: is retried bit-exactly on the next poll), and ``ha.heartbeat`` (the
#: primary's heartbeat write and the controller's read — a failing writer
#: goes stale and triggers promotion).
#: The sharded serving plane (ISSUE 9) adds ``shard.route`` (the cluster's
#: session->shard resolution — an injected failure surfaces as a typed
#: per-call error, the routing table and every other shard stay live) and
#: ``shard.promote`` (a shard unit's failover promotion — an injected
#: failure leaves the standby un-promoted and re-promotable).
SITES: Tuple[str, ...] = (
    "bridge.dispatch",
    "bridge.demux",
    "checkpoint.write",
    "engine.update",
    "engine.pallas",
    "native.staging",
    "serve.ingest",
    "replica.ship",
    "replica.apply",
    "ha.heartbeat",
    "shard.route",
    "shard.promote",
)


class InjectedFault(RuntimeError):
    """Default exception raised by a rule that names no ``exc``."""


@dataclasses.dataclass
class FaultRule:
    """One scheduled failure at one site.

    Attributes:
      site: injection-site name (one of :data:`SITES`; unknown names are
        legal — they simply never fire — so specs survive site renames).
      exc: exception class (or factory taking the message) to raise, or
        ``None`` for a delay-only rule (simulated hang, nothing raised).
      after: 0-based hit index at which the rule becomes eligible.
      every: fire on every ``every``-th eligible hit (1 = each one).
      times: maximum number of fires (``None`` = unlimited).
      p: per-eligible-hit fire probability, drawn from the plane's seeded
        RNG — deterministic for a fixed plane seed and hit sequence.
      delay: seconds to sleep before raising (or before returning, when
        ``exc`` is None) — models slow/hung devices for the watchdog.
      message: override for the raised exception's message.
    """

    site: str
    exc: Optional[Union[type, Callable[[str], BaseException]]] = InjectedFault
    after: int = 0
    every: int = 1
    times: Optional[int] = None
    p: float = 1.0
    delay: float = 0.0
    message: str = ""
    fired: int = dataclasses.field(default=0, init=False)


class FaultPlane:
    """A seeded schedule of :class:`FaultRule` entries plus per-site hit
    counters.  Thread-safe: sites fire from the producer thread, the flush
    worker, and watchdog timers concurrently."""

    def __init__(self, rules: Optional[List[FaultRule]] = None, seed: int = 0) -> None:
        self._rules: Dict[str, List[FaultRule]] = {}
        for rule in rules or []:
            self._rules.setdefault(rule.site, []).append(rule)
        self._hits: Dict[str, int] = {}
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def add(self, rule: FaultRule) -> "FaultPlane":
        with self._lock:
            self._rules.setdefault(rule.site, []).append(rule)
        return self

    def hits(self) -> Dict[str, int]:
        """Per-site hit counts observed while this plane was active — the
        coverage ledger ``tests/test_faults.py`` asserts against."""
        with self._lock:
            return dict(self._hits)

    def reset(self) -> None:
        with self._lock:
            self._hits.clear()
            for rules in self._rules.values():
                for rule in rules:
                    rule.fired = 0

    def fire(self, site: str) -> None:
        """Record a hit at ``site`` and raise/delay per the matching rules."""
        with self._lock:
            hit = self._hits.get(site, 0)
            self._hits[site] = hit + 1
            due: Optional[FaultRule] = None
            for rule in self._rules.get(site, ()):
                if hit < rule.after:
                    continue
                if (hit - rule.after) % rule.every:
                    continue
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if rule.p < 1.0 and self._rng.random() >= rule.p:
                    continue
                rule.fired += 1
                due = rule
                break
        if due is None:
            return
        if due.delay:
            time.sleep(due.delay)
        if due.exc is not None:
            raise due.exc(
                due.message or f"injected fault at {site} (hit {hit})"
            )


_PLANE: Optional[FaultPlane] = None


def fire(site: str, plane: Optional[FaultPlane] = None) -> None:
    """Injection point.  ``plane`` is an instance-scoped plane (a bridge's or
    engine's own); when absent, the globally installed plane applies.  With
    neither, this is the zero-overhead no-op path: one global load, one
    ``is None`` test, return."""
    if plane is None:
        plane = _PLANE
        if plane is None:
            return
    plane.fire(site)


def install(plane: FaultPlane) -> FaultPlane:
    """Activate ``plane`` globally (every site in every component)."""
    global _PLANE
    _PLANE = plane
    return plane


def uninstall() -> None:
    global _PLANE
    _PLANE = None


@contextlib.contextmanager
def active(plane: FaultPlane):
    """``with faults.active(plane): ...`` — scoped global activation."""
    global _PLANE
    prev = _PLANE
    _PLANE = plane
    try:
        yield plane
    finally:
        _PLANE = prev


def _resolve_exc(name: str) -> Optional[type]:
    if name.lower() in ("none", "hang"):
        return None
    from .. import errors

    exc = getattr(errors, name, None) or getattr(builtins, name, None)
    if not (isinstance(exc, type) and issubclass(exc, BaseException)):
        raise ValueError(f"RESERVOIR_FAULTS: unknown exception type {name!r}")
    return exc


def from_spec(spec: str) -> FaultPlane:
    """Parse a ``RESERVOIR_FAULTS`` spec string into a plane (grammar in the
    module docstring)."""
    rules: List[FaultRule] = []
    seed = 0
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if part.startswith("seed="):
            seed = int(part[5:])
            continue
        site, _, kvs = part.partition(":")
        kwargs: Dict[str, object] = {}
        for kv in filter(None, (s.strip() for s in kvs.split(","))):
            key, _, value = kv.partition("=")
            if key == "exc":
                kwargs["exc"] = _resolve_exc(value)
            elif key in ("after", "every", "times"):
                kwargs[key] = int(value)
            elif key in ("p", "delay"):
                kwargs[key] = float(value)
            elif key == "message":
                kwargs["message"] = value
            else:
                raise ValueError(f"RESERVOIR_FAULTS: unknown rule key {key!r}")
        rules.append(FaultRule(site.strip(), **kwargs))
    return FaultPlane(rules, seed=seed)


def install_from_env() -> Optional[FaultPlane]:
    """(Re-)read ``RESERVOIR_FAULTS`` and install the plane it describes;
    uninstalls when the variable is empty/unset.  Called once at import so a
    spec in the environment reaches child processes with no code change."""
    spec = os.environ.get("RESERVOIR_FAULTS")
    if not spec:
        uninstall()
        return None
    return install(from_spec(spec))


install_from_env()
