"""Lightweight counters for the host<->device stream bridge.

The reference exposes no metrics at all — its only observable state is
``isOpen`` and the result length (SURVEY §5 "Metrics" row).  The bridge adds
the counters that matter for a TPU feed path: elements consumed, device
flushes dispatched, and wall-clock throughput, so a user can see whether the
host feed or the device kernel is the bottleneck (SURVEY §7.3 warns the
bridge may be the real bottleneck at 1e9 elem/s).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional

from ..obs import registry as _obs


@dataclasses.dataclass
class ServiceMetrics:
    """Counter block of one :class:`~reservoir_tpu.serve.service.ReservoirService`
    (single-writer like :class:`BridgeMetrics`; the bridge underneath keeps
    its own counters — these are the session-plane ones).

    ``sessions_open`` is the live lease count; ``evictions`` counts TTL/LRU
    removals (``closes`` are explicit); ``recycles`` counts rows re-leased to
    a new tenant (each one is an engine row reset); ``snapshot_hits`` /
    ``snapshot_misses`` split live snapshot reads by whether the
    ``flushed_seq``-keyed device->host cache served them; ``rejections``
    counts admission-control 429s (:class:`~reservoir_tpu.errors.ServiceSaturated`).
    """

    sessions_open: int = 0
    sessions_opened: int = 0
    closes: int = 0
    evictions: int = 0
    recycles: int = 0
    snapshot_hits: int = 0
    snapshot_misses: int = 0
    rejections: int = 0
    ingested_elements: int = 0
    recoveries: int = 0

    def __post_init__(self) -> None:
        # absorb into the telemetry plane (ISSUE 6): exporters render every
        # live block; construction-time only, the counters stay plain
        # attributes (released signature + single-writer contract unchanged)
        _obs.register_block("serve", self)

    def snapshot(self) -> Dict[str, float]:
        """Point-in-time dict view (the bench/capture row format)."""
        return {
            "sessions_open": self.sessions_open,
            "sessions_opened": self.sessions_opened,
            "closes": self.closes,
            "evictions": self.evictions,
            "recycles": self.recycles,
            "snapshot_hits": self.snapshot_hits,
            "snapshot_misses": self.snapshot_misses,
            "rejections": self.rejections,
            "ingested_elements": self.ingested_elements,
            "recoveries": self.recoveries,
        }


@dataclasses.dataclass
class HAMetrics:
    """Counter block of the HA plane (ISSUE 5): one per replica/controller
    pair (``StandbyReplica`` and its ``FailoverController`` share one;
    the primary's ``HeartbeatWriter`` keeps its own).

    ``lag_seq``/``lag_s`` are the replication lag at the last poll: flush
    sequences the standby has not applied yet, and seconds since it was
    last provably caught up.  ``promotions`` counts successful failovers;
    ``fenced_writes`` writes refused because a newer epoch was persisted
    (split-brain attempts stopped); ``ship_errors``/``apply_errors`` split
    replication failures by phase (reading the journal vs applying a tile
    — both are retried on the next poll, so nonzero values mean lag, never
    corruption); ``bootstraps`` counts checkpoint-shipping bootstraps
    (1 at construction, +1 whenever a journal rotation outran the tail).
    """

    lag_seq: int = 0
    lag_s: float = 0.0
    promotions: int = 0
    fenced_writes: int = 0
    ship_errors: int = 0
    apply_errors: int = 0
    applied_tiles: int = 0
    applied_ops: int = 0
    bootstraps: int = 0
    heartbeats: int = 0

    def __post_init__(self) -> None:
        _obs.register_block("ha", self)  # exporter view; counters unchanged

    def snapshot(self) -> Dict[str, float]:
        """Point-in-time dict view (the bench/capture row format)."""
        return {
            "lag_seq": self.lag_seq,
            "lag_s": self.lag_s,
            "promotions": self.promotions,
            "fenced_writes": self.fenced_writes,
            "ship_errors": self.ship_errors,
            "apply_errors": self.apply_errors,
            "applied_tiles": self.applied_tiles,
            "applied_ops": self.applied_ops,
            "bootstraps": self.bootstraps,
            "heartbeats": self.heartbeats,
        }


@dataclasses.dataclass
class BridgeMetrics:
    """Mutable counter block owned by one bridge (single-writer, like the
    sampler itself — not synchronized)."""

    elements: int = 0
    flushes: int = 0
    flushed_elements: int = 0
    completions: int = 0
    failures: int = 0
    # robustness-plane counters (ISSUE 3): transient flush retries executed
    # by the pipeline worker, watchdog trips (hung-device flushes failed
    # with FlushTimeout), recoveries (bridges reconstructed via
    # DeviceStreamBridge.recover), Pallas->XLA demotions observed on the
    # owning engine, and auto-checkpoints taken.  The worker/watchdog
    # threads increment retries/watchdog_trips — benign races with snapshot
    # reads, same telemetry contract as the stage times below.
    # (init=False like demux_threads: the v0.1.0 released __init__
    # signature stays stable under the backward-compat gate; owners
    # increment the counters post-construction)
    retries: int = dataclasses.field(default=0, init=False)
    watchdog_trips: int = dataclasses.field(default=0, init=False)
    recoveries: int = dataclasses.field(default=0, init=False)
    demotions: int = dataclasses.field(default=0, init=False)
    checkpoints: int = dataclasses.field(default=0, init=False)
    # HA/durability counters (ISSUE 5): journal_syncs counts fsyncs issued
    # by a durability="fsync" journal (pinned zero in the default buffered
    # mode); fenced_writes counts flush/checkpoint attempts refused because
    # a newer primary epoch was persisted (FencedError — the split-brain
    # fence held).  init=False: released __init__ signature stays stable.
    journal_syncs: int = dataclasses.field(default=0, init=False)
    fenced_writes: int = dataclasses.field(default=0, init=False)
    # ingest-side skip gate (ISSUE 8, additive/init=False like the rest):
    # gated_dispatches counts compacted candidate-tile flushes;
    # gate_buffered_flushes counts chunks (staging flushes or pre-staging
    # push slices) absorbed into the candidate buffer with NO device
    # dispatch (the coalescing win);
    # gate_bytes_shipped/elided split the pre-gate element bytes by fate
    # (their ratio is the skip fraction); gate_eval_s is host time spent
    # in the vectorized skip-recursion eval.  All zero on ungated bridges.
    gated_dispatches: int = dataclasses.field(default=0, init=False)
    gate_buffered_flushes: int = dataclasses.field(default=0, init=False)
    gate_bytes_shipped: int = dataclasses.field(default=0, init=False)
    gate_bytes_elided: int = dataclasses.field(default=0, init=False)
    gate_eval_s: float = dataclasses.field(default=0.0, init=False)
    # per-stage busy time (VERDICT r3 item 5 — the config-5 decomposition):
    # demux = host scatter into the staging tile; drain = fill-count
    # read (+ tile copy in non-zero-copy mode); dispatch = device
    # transfer+execute, accumulated on the worker thread when pipelined
    # (concurrent float writes from one worker race benignly with snapshot
    # reads — stage times are telemetry, not control flow)
    demux_s: float = 0.0
    drain_s: float = 0.0
    dispatch_s: float = 0.0
    # demux worker count (native staging pool; 1 = serial/fallback) — a
    # capture's stage table states how parallel its scatter actually was.
    # init=False keeps the v0.1.0 __init__ signature released-stable (the
    # backward-compat gate is strict about signature strings); the owner
    # sets it post-construction.
    demux_threads: int = dataclasses.field(default=1, init=False)
    _t0: Optional[float] = None

    def __post_init__(self) -> None:
        _obs.register_block("bridge", self)  # exporter view; unchanged block

    def start(self) -> None:
        if self._t0 is None:
            self._t0 = time.perf_counter()

    def snapshot(self) -> Dict[str, float]:
        """Point-in-time view, including elements/sec since first element
        and the per-stage decomposition (elem/s through each host stage)."""
        elapsed = (time.perf_counter() - self._t0) if self._t0 is not None else 0.0

        def rate(busy_s: float, n: int) -> float:
            return (n / busy_s) if busy_s > 0 else 0.0

        return {
            "elements": self.elements,
            "flushes": self.flushes,
            "flushed_elements": self.flushed_elements,
            "completions": self.completions,
            "failures": self.failures,
            "retries": self.retries,
            "watchdog_trips": self.watchdog_trips,
            "recoveries": self.recoveries,
            "demotions": self.demotions,
            "checkpoints": self.checkpoints,
            "journal_syncs": self.journal_syncs,
            "fenced_writes": self.fenced_writes,
            "gated_dispatches": self.gated_dispatches,
            "gate_buffered_flushes": self.gate_buffered_flushes,
            "gate_bytes_shipped": self.gate_bytes_shipped,
            "gate_bytes_elided": self.gate_bytes_elided,
            "gate_eval_s": self.gate_eval_s,
            "gate_skip_frac": (
                self.gate_bytes_elided
                / (self.gate_bytes_shipped + self.gate_bytes_elided)
                if (self.gate_bytes_shipped + self.gate_bytes_elided)
                else 0.0
            ),
            "elapsed_s": elapsed,
            "elements_per_sec": (self.elements / elapsed) if elapsed > 0 else 0.0,
            "stages": {
                "demux_s": self.demux_s,
                "drain_s": self.drain_s,
                "dispatch_s": self.dispatch_s,
                "demux_threads": self.demux_threads,
                "demux_elem_per_s": rate(self.demux_s, self.elements),
                "drain_elem_per_s": rate(self.drain_s, self.flushed_elements),
                "dispatch_elem_per_s": rate(
                    self.dispatch_s, self.flushed_elements
                ),
            },
        }
