"""Statistical-gate helpers shared by CI tests and the on-backend selftest.

THE one copy of the BASELINE 1% KS-gate formula (the convention
:mod:`.probe` establishes for the backend-liveness contract): the CI twin
``tests/test_ks_gate.py`` and the bench-embedded selftest
(:mod:`.selftest`) both import from here, so the gate a driver artifact
reports is by construction the gate CI enforces.
"""

from __future__ import annotations

import numpy as np

__all__ = ["KS_GATE", "ks_one_sample_uniform"]

#: the literal BASELINE "within 1% KS-distance" acceptance gate
KS_GATE = 0.01


def ks_one_sample_uniform(values: np.ndarray, n: int) -> float:
    """``sup_x |ECDF(x) - x/n|`` for values drawn from ``{0..n-1}``.

    The exact one-sample Kolmogorov-Smirnov statistic against the discrete
    uniform law on an ``n``-element ordered stream (the discrete-grid bias
    is ``<= 1/n``, negligible at the pool sizes the gates use).
    """
    s = np.sort(np.asarray(values)) / float(n)
    m = len(s)
    ecdf_hi = np.arange(1, m + 1) / m
    ecdf_lo = np.arange(0, m) / m
    return float(np.maximum(np.abs(ecdf_hi - s), np.abs(s - ecdf_lo)).max())
