"""Pallas TPU kernel for the Algorithm-L steady-state hot path (SURVEY §7.2 M4).

Why a kernel at all: the XLA vmap path (:mod:`.algorithm_l`) carries
``samples [R, k]`` through a batched ``while_loop``, and XLA's batched-loop
lowering applies a per-lane select over the *entire* carry on every
iteration — ~3 × R × k × 4 bytes of HBM traffic per acceptance round.  Here
the reservoir block lives in VMEM for the whole tile: acceptances mutate the
ref in place, so per-tile HBM traffic drops to exactly one read of the batch
tile plus one read+write of the state block — the minimum the algorithm
admits.

Grid-pipelined batch streaming (the roofline restructure): the grid is 2-D,
``(row-block, batch-chunk)``.  The ``[block_r, k]`` reservoir block and its
scalar columns stay VMEM-resident across the whole batch axis (their block
index ignores the chunk dimension, so Mosaic keeps one buffer and writes it
back once per row-block), while the batch streams HBM→VMEM one
``[block_r, chunk_b]`` chunk at a time.  Mosaic's grid pipeline
double-buffers that input stream automatically: chunk ``j+1``'s DMA is in
flight while chunk ``j``'s acceptance loop runs, so element reads approach
wire rate instead of being serialized behind the ``while_loop``.  The
per-shape geometry ``(block_r, chunk_b, gather_chunk)`` is tunable — see
:mod:`.autotune` for the persistent cache the engine and bench consult.

Bit-equivalence with the vmap path is by construction, not by luck: both
paths run the *same* ``_advance_words`` trace (threefry counter draws keyed
on the absolute accept index, :mod:`reservoir_tpu.ops.threefry`), and the
acceptance indices are independent of the chunk decomposition (each lane's
``nxt`` chain is consumed in order, chunk by chunk), so
``update_steady_pallas(state, tile) == update_steady(state, tile)`` holds
exactly for every ``(block_r, chunk_b)`` geometry — pinned by
``tests/test_pallas_algl.py`` in interpret mode on CPU (including chunk
boundaries that split a lane's acceptance indices), and on hardware by the
device-gated ``tests/test_pallas_device.py`` (skipped when no TPU backend
is available; Mosaic's lowering of the log/exp chain in ``_advance_words``
is only truly exercised there).

Scope (``ReservoirEngine._update_fn`` dispatches here via :func:`supports`
and falls back to the XLA path otherwise): steady state only
(every reservoir past its fill phase — the reference's hot regime,
``Sampler.scala:257``), full tiles (no ``valid`` raggedness), identity
``map_fn``, int32 counters.  Any R: reservoir rows that do not fill the
last row-block are padded with inert lanes (``nxt`` pinned past the tile,
so they take zero acceptance rounds) and sliced off after.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .algorithm_l import ReservoirState, _advance_words
from .rng import key_words

__all__ = ["supports", "pick_block_r", "update_pallas", "update_steady_pallas"]

# one-hot batch gathers are chunked to this many lanes per instruction:
# full-width [block_r, B] selects+reduces in the acceptance while_loop are
# the prime Mosaic compile-time suspect past block 64 (BENCH.md r2: block
# 128 compiled >6 min); fixed-width chunks keep each op's vreg footprint
# constant as block_r/B grow.  Integer sums over disjoint chunks stay
# exact, so bit-equivalence with the XLA path is unaffected.
# RESERVOIR_ALGL_CHUNK_B overrides (0 = full-width gathers, the pre-r4
# shape) so a hardware window can A/B the chunking's runtime cost at the
# proven block sizes — it exists for compile-time control, not speed.
_GATHER_CHUNK_B = int(os.environ.get("RESERVOIR_ALGL_CHUNK_B", "512"))
# batch-streaming chunk (the 2-D grid's inner axis): 0 = whole tile in one
# grid cell (the pre-r6 shape, and the compile-proven default).  Nonzero
# values stream the batch through VMEM chunk-by-chunk with Mosaic's
# double-buffered grid pipeline; the sweep tool / autotune cache pick the
# winner per device+shape.
_STREAM_CHUNK_B = int(os.environ.get("RESERVOIR_ALGL_STREAM_CHUNK", "0"))


def pick_block_r(num_reservoirs: int, k: int, tile_b: int) -> int:
    """VMEM-aware row-block from the shared per-kernel byte-budget table
    (:data:`~reservoir_tpu.ops.blocking.KERNEL_VMEM`)."""
    from .blocking import kernel_block_r

    return kernel_block_r("algl", num_reservoirs, k, tile_b)


def supports(
    state: ReservoirState,
    valid,
    map_fn,
    block_r: "int | None" = None,
    batch: "jax.Array | None" = None,
) -> bool:
    """True iff this kernel can take the tile (else: XLA path).

    R-divisibility is no longer required — non-divisible R pads the last
    row-block with inert lanes.
    """
    return (
        valid is None
        and map_fn is None
        and state.count.ndim == 1  # WIDE (emulated-uint64) states: XLA path
        and state.count.dtype == jnp.int32
        and state.samples.dtype in (jnp.int32, jnp.float32, jnp.uint32)
        and (batch is None or batch.dtype == state.samples.dtype)
    )


def _kernel(samples_ref, count_ref, nxt_ref, logw_ref, key_ref, batch_ref,
            out_samples_ref, out_nxt_ref, out_logw_ref, *, k: int,
            chunk_b: int, gather_chunk: int, fill: bool):
    """One grid cell = one ``[block_r]`` row-block × one ``[chunk_b]``
    batch chunk.

    The state blocks (``out_*``) are VMEM-resident across the whole chunk
    axis — their index maps ignore the chunk dimension, so chunk ``j`` reads
    the carry chunk ``j-1`` left behind and only the last chunk's result is
    written back to HBM.  Chunk 0 seeds the carry from the inputs behind a
    ``pl.when``.

    All per-reservoir scalars are ``[block_r, 1]`` columns (TPU wants >= 2-D);
    the acceptance loop is lockstep over the block's lanes with masked
    updates — a lane whose chain is done (or whose next acceptance lies in a
    later chunk) rides along untouched, the exact semantics of the vmapped
    ``while_loop`` it replaces.  Because every lane consumes its ``nxt``
    chain in order and each chunk only admits acceptances with
    ``nxt <= count + (j+1)·chunk_b``, the draw sequence per lane is
    identical to the single-chunk kernel — chunking cannot move an
    acceptance index.

    ``fill=True`` additionally runs the fill-phase scatter (element with
    absolute index ``idx <= k`` goes to slot ``idx - 1``, arrival order —
    ``Sampler.scala:253-255``) as a k-step in-VMEM one-hot loop, the
    weighted kernel's pattern (:mod:`.weighted_pallas`); chunks past the
    fill prefix (and steady tiles) skip it behind a ``pl.when`` so the hot
    path pays one compare.
    """
    count = count_ref[:, :]            # [r, 1] int32 (pre-tile count)
    j = pl.program_id(1)
    base = j * jnp.int32(chunk_b)      # this chunk's offset in the tile
    end = count + base + jnp.int32(chunk_b)
    k1 = key_ref[:, 0:1]
    k2 = key_ref[:, 1:2]
    block_r = count.shape[0]

    g = min(chunk_b, gather_chunk) if gather_chunk > 0 else chunk_b
    if chunk_b % g != 0:  # odd widths: one full-width gather
        g = chunk_b
    n_g = chunk_b // g
    lane_c = jax.lax.broadcasted_iota(jnp.int32, (block_r, g), 1)
    lane_k = jax.lax.broadcasted_iota(jnp.int32, (block_r, k), 1)

    # chunk 0 seeds the VMEM-resident carry; later chunks mutate in place.
    @pl.when(j == 0)
    def _seed_carry():
        out_samples_ref[:, :] = samples_ref[:, :]
        out_nxt_ref[:, :] = nxt_ref[:, :]
        out_logw_ref[:, :] = logw_ref[:, :]

    if fill:
        lane_b = jax.lax.broadcasted_iota(jnp.int32, (block_r, chunk_b), 1)
        # element at local lane j has absolute index count + base + j + 1;
        # those with index <= k take slot count + base + j, in arrival order
        dest = count + base + lane_b              # [r, chunk]
        dest = jnp.where(dest < k, dest, k)       # k -> dropped
        elem_bits_all = jax.lax.bitcast_convert_type(
            batch_ref[:, :], jnp.int32
        )

        def fill_slot(s, _):
            col = dest == s                       # at most one lane per row
            wrote = jnp.any(col, axis=1, keepdims=True)
            # integer-bit one-hot gather: exact for every dtype (cf. the
            # acceptance gather below)
            e_bits = jnp.sum(
                jnp.where(col, elem_bits_all, 0), axis=1, keepdims=True
            )
            slot_mask = (lane_k == s) & wrote
            out_samples_ref[:, :] = jnp.where(
                slot_mask,
                jax.lax.bitcast_convert_type(e_bits, out_samples_ref.dtype),
                out_samples_ref[:, :],
            )
            return 0

        @pl.when(jnp.any(count + base < k))
        def _run_fill():
            jax.lax.fori_loop(0, k, fill_slot, 0)

    def cond(carry):
        nxt, _ = carry
        return jnp.any(nxt <= end)

    def body(carry):
        nxt, log_w = carry
        active = nxt <= end                       # [r, 1]
        pos = nxt - count - 1 - base              # [r, 1] in [0, chunk) active
        # gather batch[r, pos_r] as a one-hot masked reduction (no per-row
        # dynamic gather on the VPU), CHUNKED over the batch axis so each
        # select+reduce touches a fixed [r, g] window — constant vreg
        # footprint per instruction regardless of B (Mosaic compile-time
        # control, see _GATHER_CHUNK_B).
        # The sum is over integer bit patterns: exactly one lane across all
        # chunks is selected and the rest contribute literal zero, so the
        # total is exact for every dtype — including the float32 -0.0 sign
        # bit, which a float sum would drop (-0.0 + 0.0 == +0.0 in IEEE).
        def gather_window(c, acc):
            off = c * g
            bits = jax.lax.bitcast_convert_type(
                batch_ref[:, pl.dslice(off, g)], jnp.int32
            )
            onehot = lane_c == (pos - off)
            return acc + jnp.sum(
                jnp.where(onehot, bits, 0), axis=1, keepdims=True
            )

        elem_bits = jax.lax.fori_loop(
            0,
            n_g,
            gather_window,
            jnp.zeros((block_r, 1), jnp.int32),
            unroll=False,
        )
        elem = jax.lax.bitcast_convert_type(elem_bits, batch_ref.dtype)
        slot, log_w_n, nxt_n = _advance_words(log_w, nxt, k1, k2, nxt, k)
        write = (lane_k == slot) & active
        out_samples_ref[:, :] = jnp.where(
            write, elem.astype(out_samples_ref.dtype), out_samples_ref[:, :]
        )
        return (
            jnp.where(active, nxt_n, nxt),
            jnp.where(active, log_w_n, log_w),
        )

    nxt, log_w = jax.lax.while_loop(
        cond, body, (out_nxt_ref[:, :], out_logw_ref[:, :])
    )
    out_nxt_ref[:, :] = nxt
    out_logw_ref[:, :] = log_w


def update_pallas(
    state: ReservoirState,
    batch: jax.Array,
    *,
    block_r: "int | None" = None,
    chunk_b: "int | None" = None,
    gather_chunk: "int | None" = None,
    interpret: bool = False,
) -> ReservoirState:
    """FILL-CAPABLE tile update, bit-identical to
    :func:`reservoir_tpu.ops.algorithm_l.update` on full tiles — covers the
    whole stream life cycle, so ``impl="pallas"`` no longer falls back to
    XLA for fill/partially-filled tiles (VERDICT r3 item 7).  The fill
    scatter costs a k-step in-VMEM loop only while some reservoir in a
    row-block is below k; steady blocks (and batch chunks past the fill
    prefix) skip it behind one compare.
    """
    return _update_pallas(
        state, batch, block_r=block_r, chunk_b=chunk_b,
        gather_chunk=gather_chunk, interpret=interpret, fill=True,
    )


def update_steady_pallas(
    state: ReservoirState,
    batch: jax.Array,
    *,
    block_r: "int | None" = None,
    chunk_b: "int | None" = None,
    gather_chunk: "int | None" = None,
    interpret: bool = False,
) -> ReservoirState:
    """Steady-state tile update, bit-identical to
    :func:`reservoir_tpu.ops.algorithm_l.update_steady` on full tiles.

    ``batch`` is ``[R, B]``; reservoir r consumes its full row.  Requires
    :func:`supports`; ``interpret=True`` runs the Mosaic interpreter (CPU
    equivalence tests).  Geometry knobs (see :mod:`.autotune` for the
    persistent per-device cache):

    - ``block_r``: reservoir rows per grid cell (``None`` = VMEM-aware
      auto-size, :func:`pick_block_r`); any R is accepted — a partial last
      row-block is padded with inert lanes (``nxt`` pinned past the tile
      end, so their acceptance loop never iterates) and sliced off.
    - ``chunk_b``: batch-streaming chunk — the tile's batch axis is split
      into ``B // chunk_b`` grid cells whose HBM→VMEM loads Mosaic
      double-buffers against the previous chunk's acceptance loop.
      ``None``/0 (or a non-divisor of B) = whole tile in one cell.
    - ``gather_chunk``: lanes per one-hot select+reduce inside the
      acceptance loop (compile-time control; 0 = full width, ``None`` =
      the ``RESERVOIR_ALGL_CHUNK_B`` env default).
    """
    return _update_pallas(
        state, batch, block_r=block_r, chunk_b=chunk_b,
        gather_chunk=gather_chunk, interpret=interpret, fill=False,
    )


def _update_pallas(
    state: ReservoirState,
    batch: jax.Array,
    *,
    block_r: "int | None",
    chunk_b: "int | None",
    gather_chunk: "int | None",
    interpret: bool,
    fill: bool,
) -> ReservoirState:
    R, k = state.samples.shape
    B = batch.shape[1]
    if batch.shape[0] != R:
        raise ValueError(
            f"batch has {batch.shape[0]} rows for {R} reservoirs"
        )
    if not supports(state, None, None, block_r, batch):
        raise ValueError(
            "pallas algl kernel: unsupported config (need int32 counters, "
            "int32/float32/uint32 samples, batch dtype == samples dtype); "
            "use ops.algorithm_l.update / update_steady"
        )
    if block_r is None:
        block_r = pick_block_r(R, k, B)
    if gather_chunk is None:
        gather_chunk = _GATHER_CHUNK_B
    if chunk_b is None:
        chunk_b = _STREAM_CHUNK_B
    from .blocking import resolve_chunk

    # invalid chunks run the whole tile in one grid cell (the
    # compile-proven shape) — never a crash, never a different result
    chunk_b = resolve_chunk(B, chunk_b)
    R_orig = R
    if R % block_r != 0:
        from .blocking import shrink_block_to

        block_r = shrink_block_to(R, block_r)
        pad = (-R) % block_r
        if pad:
            # inert pad lanes: count 0, nxt = B + 1 > end, so cond() is
            # false for them from the first round — zero extra work beyond
            # the block's lockstep rides
            state = ReservoirState(
                samples=jnp.pad(state.samples, ((0, pad), (0, 0))),
                count=jnp.pad(state.count, (0, pad)),
                nxt=jnp.pad(
                    state.nxt, (0, pad), constant_values=np.int32(B + 1)
                ),
                log_w=jnp.pad(state.log_w, (0, pad)),
                key=jnp.concatenate([state.key, state.key[-pad:]]),
            )
            batch = jnp.pad(batch, ((0, pad), (0, 0)))
            R = R + pad
    kd1, kd2 = key_words(state.key)               # [R] uint32 each
    key_data = jnp.stack([kd1, kd2], axis=1)      # [R, 2]

    # state blocks: row-block i, chunk-invariant (VMEM-resident across j)
    col = lambda i, j: (i, 0)  # noqa: E731
    col_spec = lambda w: pl.BlockSpec(  # noqa: E731
        (block_r, w), col, memory_space=pltpu.VMEM
    )

    out_samples, out_nxt, out_logw = pl.pallas_call(
        functools.partial(
            _kernel, k=k, chunk_b=chunk_b, gather_chunk=gather_chunk,
            fill=fill,
        ),
        grid=(R // block_r, B // chunk_b),
        in_specs=[
            col_spec(k),
            col_spec(1),
            col_spec(1),
            col_spec(1),
            col_spec(2),
            # the streamed input: chunk j of row-block i — the only block
            # whose index varies along the inner grid axis, so Mosaic's
            # pipeline double-buffers exactly this HBM->VMEM stream
            pl.BlockSpec(
                (block_r, chunk_b),
                lambda i, j: (i, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=(col_spec(k), col_spec(1), col_spec(1)),
        out_shape=(
            jax.ShapeDtypeStruct((R, k), state.samples.dtype),
            jax.ShapeDtypeStruct((R, 1), state.nxt.dtype),
            jax.ShapeDtypeStruct((R, 1), state.log_w.dtype),
        ),
        interpret=interpret,
    )(
        state.samples,
        state.count.reshape(R, 1),
        state.nxt.reshape(R, 1),
        state.log_w.reshape(R, 1),
        key_data,
        batch,
    )
    if R != R_orig:  # drop the inert pad lanes
        out_samples = out_samples[:R_orig]
        out_nxt = out_nxt[:R_orig]
        out_logw = out_logw[:R_orig]
        state = jax.tree.map(lambda x: x[:R_orig], state)
    return ReservoirState(
        samples=out_samples,
        count=state.count + jnp.asarray(B, state.count.dtype),
        nxt=out_nxt.reshape(R_orig),
        log_w=out_logw.reshape(R_orig),
        key=state.key,
    )
