"""Shared prefix-sum used by both the XLA paths and the Pallas kernels.

Mosaic has no lowering for the ``cumsum`` primitive (NotImplementedError on
TPU, observed 2026-07-30), so Pallas kernels cannot call ``jnp.cumsum``.
This log-step shifted-add scan (Hillis-Steele) lowers everywhere.  For float
inputs the summation *association* determines the rounded partial sums, so
any path that must stay bit-identical to a Pallas kernel (the weighted
A-ExpJ weight cumsum — ``ops.weighted`` vs ``ops.weighted_pallas``) uses
this same helper rather than ``jnp.cumsum``: identical decomposition ==
identical floats, on every backend.  Integer scans are exact under any
association; Pallas kernels still use this helper for them (no cumsum
primitive), while XLA-only integer scans keep ``jnp.cumsum``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["lane_cumsum"]


def lane_cumsum(x: jax.Array, axis: int = -1) -> jax.Array:
    """Inclusive prefix sum along ``axis`` via log2(n) shifted adds."""
    axis = axis % x.ndim
    n = x.shape[axis]
    d = 1
    while d < n:
        kept = jax.lax.slice_in_dim(x, 0, n - d, axis=axis)
        zeros = jnp.zeros_like(jax.lax.slice_in_dim(x, 0, d, axis=axis))
        x = x + jnp.concatenate([zeros, kept], axis=axis)
        d *= 2
    return x
