"""Shared prefix-sum used by both the XLA paths and the Pallas kernels.

Mosaic has no lowering for the ``cumsum`` primitive (NotImplementedError on
TPU, observed 2026-07-30), so Pallas kernels cannot call ``jnp.cumsum``.
This scan lowers everywhere.  For float inputs the summation *association*
determines the rounded partial sums, so any path that must stay bit-identical
to a Pallas kernel (the weighted A-ExpJ weight cumsum — ``ops.weighted`` vs
``ops.weighted_pallas``) uses this same helper rather than ``jnp.cumsum``:
identical decomposition == identical floats, on every backend.  Integer
scans are exact under any association; Pallas kernels still use this helper
for them (no cumsum primitive), while XLA-only integer scans keep
``jnp.cumsum``.

The association is **blocked** so the grid-pipelined kernels can stream a
tile through VMEM in chunks without changing a single partial-sum bit:
the axis is split into fixed ``_CUMSUM_BLOCK``-lane blocks, each block is
scanned with the log-step shifted-add (Hillis-Steele) form, and a scalar
carry — the running inclusive sum at each block's last lane — is folded
across blocks *sequentially*.  A kernel that consumes the axis in chunks
that are multiples of ``_CUMSUM_BLOCK`` reproduces the exact same float
adds in the exact same order by carrying that scalar across grid cells
(:func:`lane_cumsum_carry`), so the full-tile XLA path and every chunked
grid decomposition agree bit-for-bit by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["lane_cumsum", "lane_cumsum_carry", "CUMSUM_BLOCK"]

# The fixed block of the shared association — one TPU vreg lane row.  This
# is an ALGORITHMIC constant, not a tuning knob: both the XLA paths and
# every kernel chunk geometry must agree on it, and chunked kernels only
# accept batch chunks that are multiples of it (ops.blocking.resolve_chunk).
CUMSUM_BLOCK = 128
_CUMSUM_BLOCK = CUMSUM_BLOCK


def _hillis(x: jax.Array, axis: int) -> jax.Array:
    """Inclusive prefix sum along ``axis`` via log2(n) shifted adds."""
    n = x.shape[axis]
    d = 1
    while d < n:
        kept = jax.lax.slice_in_dim(x, 0, n - d, axis=axis)
        zeros = jnp.zeros_like(jax.lax.slice_in_dim(x, 0, d, axis=axis))
        x = x + jnp.concatenate([zeros, kept], axis=axis)
        d *= 2
    return x


def lane_cumsum_carry(
    x: jax.Array, carry: "jax.Array | None", axis: int = -1
) -> "tuple[jax.Array, jax.Array]":
    """Inclusive blocked prefix sum with an explicit scalar carry.

    Returns ``(cw, carry_out)``: ``cw[..., p] = carry + x[..., :p+1]`` under
    the blocked association above, and ``carry_out`` is ``cw``'s last lane —
    the value to feed the next chunk so the concatenation of per-chunk scans
    is bit-identical to one scan over the concatenated axis (chunk widths
    must be multiples of ``CUMSUM_BLOCK``).  ``carry=None`` starts a fresh
    scan; chunked kernels seed their carry ref with literal ``0.0`` instead,
    and the single ``+ 0.0`` per block is the identity for every partial
    sum a nonnegative-weight scan can produce.
    """
    axis = axis % x.ndim
    n = x.shape[axis]
    parts = []
    for off in range(0, n, _CUMSUM_BLOCK):
        w = min(_CUMSUM_BLOCK, n - off)
        h = _hillis(jax.lax.slice_in_dim(x, off, off + w, axis=axis), axis)
        if carry is not None:
            h = h + carry
        parts.append(h)
        carry = jax.lax.slice_in_dim(h, w - 1, w, axis=axis)
    out = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=axis)
    return out, carry


def lane_cumsum(x: jax.Array, axis: int = -1) -> jax.Array:
    """Inclusive prefix sum along ``axis`` (the shared blocked association)."""
    out, _ = lane_cumsum_carry(x, None, axis=axis)
    return out
