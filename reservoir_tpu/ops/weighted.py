"""Weighted reservoir sampling on device: batched A-ExpJ (SURVEY §7.2 M6).

Capability beyond the reference (BASELINE config 4): R lockstep weighted
reservoirs, each holding the k items with the largest Efraimidis-Spirakis
keys ``u^(1/w)`` seen so far.  The exponential-jumps structure maps onto
tiles exactly like Algorithm L's skip counts (:mod:`.algorithm_l`):

- state carries ``xw`` — the remaining *weight* to skip before the next
  acceptance (the weighted analog of ``nxt - count``);
- per tile, a masked cumulative-sum of the weights turns "skip until
  cumulative weight crosses xw" into one ``searchsorted`` per acceptance;
  a tile with no acceptance costs one cumsum + one compare per reservoir,
  and skipped items draw no RNG;
- the acceptance ``while_loop`` gives the crossing item a key conditioned
  to beat the current threshold (``r2 ~ U(T^w, 1)``, ``lkey = log(r2)/w``),
  replaces the argmin slot, and redraws ``xw`` against the new threshold.

RNG is counter-keyed on the absolute item index (three channels per index:
fill-key u, conditional-key u, jump u; the fill-completion jump draw is keyed
on index k), so tile splits cannot change which draws an item consumes.
Tile-split invariance is bit-exact when the weight partial sums are exact in
float32 (e.g. integer weights summing below 2^24) and within float rounding
otherwise — the jump accumulator ``xw`` is carried across tiles as a float.

Keys and ``xw`` live in log-space (SURVEY §7.3).

Zero-weight contract (one contract across oracle, kernel, engine and bridge
— VERDICT r1 item 7): weights must be **nonnegative**; ``w == 0`` means
"counted but never sampled", exactly as the CPU oracle defines it.  Zero-
weight items take no reservoir slot during fill (slots go to positive-weight
items by arrival rank), contribute nothing to the jump accumulator, and can
never be the crossing item of an exponential jump (they are flat spans of
the weight cumsum).  Negative weights raise wherever weights cross the host
boundary.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from .prefix import lane_cumsum
from .rng import uniforms as rng_uniforms

__all__ = ["WeightedState", "init", "update", "update_steady", "result", "merge"]

_NEG_INF = float("-inf")


class WeightedState(NamedTuple):
    """R lockstep weighted reservoirs (A-ExpJ)."""

    samples: jax.Array  # [R, k] sample dtype
    lkeys: jax.Array  # [R, k] f32 — log of ES keys; -inf = empty slot
    count: jax.Array  # [R] count dtype
    xw: jax.Array  # [R] f32 — remaining weight to skip; +inf while filling
    key: jax.Array  # [R] PRNG keys


def _uniforms(key: jax.Array, idx) -> jax.Array:
    """Three (0,1] f32 uniforms for absolute index ``idx``:
    [0] fill key, [1] conditional key (r2), [2] jump draw."""
    return rng_uniforms(key, idx, (3,))


def init(
    key: jax.Array,
    num_reservoirs: int,
    k: int,
    sample_dtype: Any = jnp.int32,
    count_dtype: Any = jnp.int32,
) -> WeightedState:
    keys = jr.split(key, num_reservoirs)
    return WeightedState(
        samples=jnp.zeros((num_reservoirs, k), sample_dtype),
        lkeys=jnp.full((num_reservoirs, k), _NEG_INF, jnp.float32),
        count=jnp.zeros((num_reservoirs,), jnp.dtype(count_dtype)),
        xw=jnp.full((num_reservoirs,), jnp.inf, jnp.float32),
        key=keys,
    )


def _draw_xw(u3: jax.Array, lt: jax.Array) -> jax.Array:
    """``Xw = log(r)/log(T)`` in log-space, guarding the degenerate
    threshold-key-of-1 case (nothing can beat it -> skip forever)."""
    return jnp.where(lt >= 0.0, jnp.inf, jnp.log(u3) / lt)


def _update_one(
    samples,
    lkeys,
    count,
    xw,
    key,
    elems,
    weights,
    valid,
    k: int,
    map_fn: Optional[Callable],
    fill: bool,
):
    bsz = elems.shape[0]
    count_dtype = count.dtype
    in_tile = jnp.arange(bsz) < valid
    idx_abs = count + jnp.arange(1, bsz + 1, dtype=count_dtype)
    wf = weights.astype(jnp.float32)
    positive = in_tile & (wf > 0.0)  # zero-weight: counted, never sampled
    w_masked = jnp.where(in_tile, wf, 0.0)
    # lane_cumsum, not jnp.cumsum: the Pallas kernel must reproduce these
    # partial sums bit-for-bit, and Mosaic has no cumsum primitive — both
    # paths share the one log-step association (ops.prefix)
    cw = lane_cumsum(w_masked)
    total_w = jnp.where(valid > 0, cw[bsz - 1], 0.0)
    # filled slots are a prefix by construction; -inf lkey == empty slot
    # (fill keys are clamped finite below so the sentinel is unambiguous)
    n_filled = jnp.sum(lkeys > _NEG_INF).astype(jnp.int32)
    need = jnp.maximum(k - n_filled, 0)
    prank = jnp.cumsum(positive.astype(jnp.int32))  # 1-based positive rank

    if fill:
        # fill phase: positive-weight items take the next free slots in
        # arrival order (zero-weight items advance only the count — the
        # oracle's "never sampled" contract); draws stay keyed on the
        # absolute index so tile splits cannot change them.
        fill_mask = positive & (prank <= need)
        u_fill = jax.vmap(lambda i: _uniforms(key, i)[0])(idx_abs)
        lk_fill = jnp.where(
            positive, jnp.log(u_fill) / jnp.maximum(wf, jnp.float32(1e-45)),
            _NEG_INF,
        )
        lk_fill = jnp.maximum(lk_fill, jnp.finfo(jnp.float32).min)
        dest = jnp.where(fill_mask, n_filled + prank - 1, k)
        values = map_fn(elems) if map_fn is not None else elems
        samples = samples.at[dest].set(
            jnp.asarray(values, samples.dtype), mode="drop"
        )
        lkeys = lkeys.at[dest].set(lk_fill, mode="drop")
        # fill completing inside this tile draws the first jump, keyed on
        # index k, against the threshold of the just-filled reservoir
        n_pos = jnp.where(valid > 0, prank[bsz - 1], 0)
        completes = (n_filled < k) & (n_filled + n_pos >= k)
        u3_init = _uniforms(key, jnp.asarray(k, count_dtype))[2]
        xw = jnp.where(completes, _draw_xw(u3_init, jnp.min(lkeys)), xw)

    # acceptance scanning starts after the fill-completing item (the
    # ``need``-th positive item of the tile); an unfinished fill leaves
    # start == bsz with xw still +inf -> no acceptances
    j0 = jnp.searchsorted(prank, need, side="left").astype(jnp.int32)
    start = jnp.where(need > 0, jnp.minimum(j0 + 1, bsz), 0).astype(jnp.int32)
    base0 = jnp.where(start > 0, cw[jnp.maximum(start - 1, 0)], 0.0)

    lane = jnp.arange(bsz, dtype=jnp.int32)

    def next_j(base, xw_c, cur):
        # first POSITIVE lane at or past ``cur`` whose prefix weight reaches
        # the jump target.  Under exact partial sums this is exactly
        # ``searchsorted(cw, base+xw, 'left')`` clamped to ``cur`` — but the
        # shared log-step prefix sum (ops.prefix) has ulp-scale dips, under
        # which a raw searchsorted could land on a zero-weight lane and the
        # accept body would then compute log(1)/0 = NaN.  Restricting to
        # positive lanes makes the scan NaN-free by construction, and the
        # integer min is reproduced bit-for-bit by the Pallas kernel.
        mask = positive & (cw >= base + xw_c) & (lane >= cur)
        return jnp.min(jnp.where(mask, lane, bsz)).astype(jnp.int32)

    def cond(carry):
        _, _, xw_c, base, cur = carry
        return next_j(base, xw_c, cur) < bsz

    def body(carry):
        samples_c, lkeys_c, xw_c, base, cur = carry
        j = next_j(base, xw_c, cur)
        w_c = w_masked[j]
        idx = count + 1 + j.astype(count_dtype)
        u = _uniforms(key, idx)
        lt = jnp.min(lkeys_c)
        t = jnp.exp(w_c * lt)
        r2 = t + u[1] * (1.0 - t)
        # clamp finite: -inf is the empty-slot sentinel (result/size)
        lkey_new = jnp.maximum(
            jnp.log(r2) / w_c, jnp.finfo(jnp.float32).min
        )
        slot = jnp.argmin(lkeys_c).astype(jnp.int32)
        value = map_fn(elems[j]) if map_fn is not None else elems[j]
        samples_c = samples_c.at[slot].set(jnp.asarray(value, samples_c.dtype))
        lkeys_c = lkeys_c.at[slot].set(lkey_new)
        xw_n = _draw_xw(u[2], jnp.min(lkeys_c))
        return samples_c, lkeys_c, xw_n, cw[j], j + 1

    samples, lkeys, xw, base, _cur = jax.lax.while_loop(
        cond, body, (samples, lkeys, xw, base0, start)
    )
    # carry the unconsumed jump across the tile boundary
    xw = xw - (total_w - base)
    count = count + valid.astype(count_dtype)
    return samples, lkeys, count, xw


def _update(state, elems, weights, valid, map_fn, fill):
    k = state.samples.shape[1]
    if valid is None and not fill:
        valid_arg = jnp.asarray(elems.shape[1], jnp.int32)
        in_axes = (0, 0, 0, 0, 0, 0, 0, None)
    elif valid is None:
        # per-lane valid array: the scalar-broadcast variant makes XLA
        # compile the masked fill scatter pathologically slowly on TPU
        # (~20x, measured on algorithm_l's identical structure 2026-07-29)
        valid_arg = jnp.full((elems.shape[0],), elems.shape[1], jnp.int32)
        in_axes = (0, 0, 0, 0, 0, 0, 0, 0)
    else:
        valid_arg = valid
        in_axes = (0, 0, 0, 0, 0, 0, 0, 0)
    samples, lkeys, count, xw = jax.vmap(
        functools.partial(_update_one, k=k, map_fn=map_fn, fill=fill),
        in_axes=in_axes,
    )(state.samples, state.lkeys, state.count, state.xw, state.key, elems, weights, valid_arg)
    return WeightedState(samples, lkeys, count, xw, state.key)


def update(
    state: WeightedState,
    elems: jax.Array,
    weights: jax.Array,
    valid: Optional[jax.Array] = None,
    map_fn: Optional[Callable] = None,
) -> WeightedState:
    """Consume one ``([R, B], [R, B])`` (elements, weights) tile pair."""
    return _update(state, elems, weights, valid, map_fn, fill=True)


def update_steady(
    state: WeightedState,
    elems: jax.Array,
    weights: jax.Array,
    valid: Optional[jax.Array] = None,
    map_fn: Optional[Callable] = None,
) -> WeightedState:
    """:func:`update` without the fill scatter (all reservoirs full)."""
    return _update(state, elems, weights, valid, map_fn, fill=False)


def merge_parts(
    samples_a: jax.Array,
    lkeys_a: jax.Array,
    count_a: jax.Array,
    samples_b: jax.Array,
    lkeys_b: jax.Array,
    count_b: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k-of-union merge on raw ``(samples, lkeys, count)`` triples — the
    composable core shared by :func:`merge` and the stream-axis collective
    merger (:mod:`reservoir_tpu.parallel.merge`).

    Exact: ES keys are i.i.d. draws per item, so the global top-k of the
    union is the correct merged sample regardless of how the stream was
    sharded.
    """
    k = samples_a.shape[1]

    def one(sa, lka, ca, sb, lkb, cb):
        m_s = jnp.concatenate([sa, sb])
        m_lk = jnp.concatenate([lka, lkb])
        # sort by descending lkey: top-k first
        order = jnp.argsort(-m_lk)
        return m_s[order[:k]], m_lk[order[:k]], ca + cb

    return jax.vmap(one)(
        samples_a, lkeys_a, count_a, samples_b, lkeys_b, count_b
    )


def merge(state_a: WeightedState, state_b: WeightedState) -> WeightedState:
    """State-level wrapper over :func:`merge_parts`.

    The merged ``xw`` is not meaningful (we keep A's to allow result-only
    use) — continue streaming on the per-shard states, as with Algorithm-L
    merges.
    """
    samples, lkeys, count = merge_parts(
        state_a.samples, state_a.lkeys, state_a.count,
        state_b.samples, state_b.lkeys, state_b.count,
    )
    return WeightedState(samples, lkeys, count, state_a.xw, state_a.key)


def result(state: WeightedState) -> Tuple[jax.Array, jax.Array]:
    """``(samples [R, k], size [R])`` — size is the number of filled slots
    (equal to min(count, k) only when no zero-weight items were seen; a
    zero-weight item counts but never occupies a slot)."""
    size = jnp.sum(state.lkeys > _NEG_INF, axis=1).astype(state.count.dtype)
    k = state.samples.shape[1]
    mask = jnp.arange(k)[None, :] < size[:, None]
    return jnp.where(mask, state.samples, jnp.zeros_like(state.samples)), size
