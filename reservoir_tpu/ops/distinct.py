"""Distinct-value sampling on device: salted bottom-k via XLA sorts (M3).

The reference's ``RandomValues`` engine (``Sampler.scala:383-412``) keeps the
k distinct values with the smallest salted 64-bit hashes using a max-heap +
membership set.  Pointer-chasing heaps and hash sets have no TPU analog
(SURVEY §7.3 "Distinct mode without hash tables"); the device design exploits
that bottom-k-of-a-hash is a *mergeable summary*:

    state (k entries) ∪ tile (B entries)  --sort+dedup+truncate-->  state'

Per tile and reservoir: scramble the tile's hashes (same integer-exact
:func:`~reservoir_tpu.ops.hashing.scramble64` as the CPU oracle — results are
bit-comparable), concatenate with the carried entries, multi-key sort
``(pad, hash_hi, hash_lo, value)``, mask duplicate runs, re-sort survivors,
keep the k smallest.  Two ``lax.sort`` passes of k+B lanes replace the
reference's per-element heap ops; a whole tile costs O((k+B) log(k+B))
comparisons regardless of duplication structure.

Semantics preserved (SURVEY §2.2 invariant 6): inclusion is uniform over
distinct values via the salted hash order; dedup is by value (equal values
have equal hashes and collapse to one entry).  Two *distinct* values
colliding in the full 64-bit hash are both kept — same as the reference,
whose membership set is keyed on value while only the threshold uses the
hash (``Sampler.scala:396-408``); hash-order ties are the shared ~2^-64
bias source.  ``map`` applies to every element (it feeds the hash,
``Sampler.scala:155, 395``).  Tile-split invariance holds because the merge
is associative and order-insensitive.

Sample dtype must be a 32-bit integer type for now: the default hash and the
dedup key embed the value's 4-byte pattern (validated at :func:`init`).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from .hashing import default_hash64, scramble64

__all__ = ["DistinctState", "init", "update", "update_steady", "result", "merge"]

_U32_MAX = jnp.uint32(0xFFFFFFFF)


class DistinctState(NamedTuple):
    """R lockstep distinct-value reservoirs.

    Entries ``[r, i]`` for ``i < size[r]`` are the current bottom-k, sorted by
    scrambled hash ascending; the rest are canonical padding (hash = MAX,
    value = 0) marked by ``size``.
    """

    values: jax.Array  # [R, k] sample dtype
    hash_hi: jax.Array  # [R, k] uint32
    hash_lo: jax.Array  # [R, k] uint32
    size: jax.Array  # [R] int32
    count: jax.Array  # [R] count dtype — total elements seen
    salts: jax.Array  # [R, 4] uint32 — (r0_hi, r0_lo, r1_hi, r1_lo)


def init(
    key: jax.Array,
    num_reservoirs: int,
    k: int,
    sample_dtype: Any = jnp.int32,
    count_dtype: Any = jnp.int32,
) -> DistinctState:
    """Empty reservoirs with per-instance salts drawn once
    (``Sampler.scala:385-388``)."""
    sample_dtype = jnp.dtype(sample_dtype)
    if not (
        jnp.issubdtype(sample_dtype, jnp.integer) and sample_dtype.itemsize == 4
    ):
        raise ValueError(
            "distinct mode currently requires a 32-bit integer sample dtype "
            f"(value bits feed the hash and dedup key); got {sample_dtype}"
        )
    salts = jr.bits(key, (num_reservoirs, 4), jnp.uint32)
    return DistinctState(
        values=jnp.zeros((num_reservoirs, k), sample_dtype),
        hash_hi=jnp.full((num_reservoirs, k), _U32_MAX),
        hash_lo=jnp.full((num_reservoirs, k), _U32_MAX),
        size=jnp.zeros((num_reservoirs,), jnp.int32),
        count=jnp.zeros((num_reservoirs,), count_dtype),
        salts=salts,
    )


def _update_one(
    values,
    hash_hi,
    hash_lo,
    size,
    count,
    salts,
    batch,
    valid,
    k: int,
    map_fn: Optional[Callable],
    hash_fn: Optional[Callable],
):
    """Single-reservoir tile merge (vmapped over R)."""
    bsz = batch.shape[0]
    mapped = map_fn(batch) if map_fn is not None else batch  # every element
    if hash_fn is not None:
        bhi, blo = hash_fn(mapped)
    else:
        bhi, blo = default_hash64(mapped)
    bhi, blo = scramble64(
        bhi.astype(jnp.uint32),
        blo.astype(jnp.uint32),
        salts[0],
        salts[1],
        salts[2],
        salts[3],
    )

    in_tile = jnp.arange(bsz) < valid
    # pad key: carried padding (>= size) and masked tile lanes sort last
    carried_pad = (jnp.arange(k) >= size).astype(jnp.uint32)
    tile_pad = (~in_tile).astype(jnp.uint32)

    m_values = jnp.concatenate([values, jnp.asarray(mapped, values.dtype)])
    m_hi = jnp.concatenate([hash_hi, bhi])
    m_lo = jnp.concatenate([hash_lo, blo])
    m_pad = jnp.concatenate([carried_pad, tile_pad])
    # stable sortable view of the value for tie-grouping (dedup key);
    # init() guarantees a 4-byte integer dtype
    m_vbits = m_values.view(jnp.uint32)

    # sort by (pad, hash, value-bits): equal values -> equal hashes -> adjacent
    m_pad, m_hi, m_lo, m_vbits, m_values = jax.lax.sort(
        (m_pad, m_hi, m_lo, m_vbits, m_values), num_keys=4
    )
    same_as_prev = (
        (m_pad == jnp.roll(m_pad, 1))
        & (m_hi == jnp.roll(m_hi, 1))
        & (m_lo == jnp.roll(m_lo, 1))
        & (m_vbits == jnp.roll(m_vbits, 1))
    )
    same_as_prev = same_as_prev.at[0].set(False)
    dup_or_pad = same_as_prev | (m_pad == 1)

    # demote duplicates and padding to canonical padding, re-sort, keep k
    m_hi = jnp.where(dup_or_pad, _U32_MAX, m_hi)
    m_lo = jnp.where(dup_or_pad, _U32_MAX, m_lo)
    m_pad2 = dup_or_pad.astype(jnp.uint32)
    m_values = jnp.where(dup_or_pad, jnp.zeros((), m_values.dtype), m_values)
    m_pad2, m_hi, m_lo, m_values = jax.lax.sort(
        (m_pad2, m_hi, m_lo, m_values), num_keys=3
    )

    new_values = m_values[:k]
    new_hi = m_hi[:k]
    new_lo = m_lo[:k]
    n_unique = jnp.sum(1 - m_pad2).astype(jnp.int32)
    new_size = jnp.minimum(n_unique, k)
    new_count = count + valid.astype(count.dtype)
    return new_values, new_hi, new_lo, new_size, new_count


def update(
    state: DistinctState,
    batch: jax.Array,
    valid: Optional[jax.Array] = None,
    map_fn: Optional[Callable] = None,
    hash_fn: Optional[Callable] = None,
) -> DistinctState:
    """Merge one ``[R, B]`` tile into the bottom-k state.

    ``hash_fn`` (optional) maps a mapped-value tile to a ``(hi, lo)`` uint32
    pair *before* salting — the user-hash hook of ``Sampler.distinct``
    (``Sampler.scala:173-180``); default embeds int32 values sign-extended.
    """
    k = state.values.shape[1]
    if valid is None:
        valid_arg = jnp.asarray(batch.shape[1], jnp.int32)
        in_axes = (0, 0, 0, 0, 0, 0, 0, None)
    else:
        valid_arg = valid
        in_axes = (0, 0, 0, 0, 0, 0, 0, 0)
    values, hi, lo, size, count = jax.vmap(
        functools.partial(_update_one, k=k, map_fn=map_fn, hash_fn=hash_fn),
        in_axes=in_axes,
    )(
        state.values,
        state.hash_hi,
        state.hash_lo,
        state.size,
        state.count,
        state.salts,
        batch,
        valid_arg,
    )
    return DistinctState(values, hi, lo, size, count, state.salts)


#: Distinct mode has no fill/steady split — the merge is one code path.
update_steady = update


def merge(state_a: DistinctState, state_b: DistinctState) -> DistinctState:
    """Merge two distinct-reservoir sets over shards of the same logical
    streams: union of entries, dedup, keep the bottom-k hashes.

    Exact by the mergeable-summary property of bottom-k sketches.  Both
    states MUST share salts (same ``init`` key) — hashes are only comparable
    under one salt; shards of one logical stream are created that way.
    ``count`` adds; tile-split invariance extends across shards.
    """
    k = state_a.values.shape[1]

    def one(va, hia, loa, sza, ca, vb, hib, lob, szb, cb, salts):
        pad_a = (jnp.arange(k) >= sza).astype(jnp.uint32)
        pad_b = (jnp.arange(k) >= szb).astype(jnp.uint32)
        m_values = jnp.concatenate([va, vb])
        m_hi = jnp.concatenate([hia, hib])
        m_lo = jnp.concatenate([loa, lob])
        m_pad = jnp.concatenate([pad_a, pad_b])
        m_vbits = m_values.view(jnp.uint32)
        m_pad, m_hi, m_lo, m_vbits, m_values = jax.lax.sort(
            (m_pad, m_hi, m_lo, m_vbits, m_values), num_keys=4
        )
        same = (
            (m_pad == jnp.roll(m_pad, 1))
            & (m_hi == jnp.roll(m_hi, 1))
            & (m_lo == jnp.roll(m_lo, 1))
            & (m_vbits == jnp.roll(m_vbits, 1))
        )
        same = same.at[0].set(False)
        drop = same | (m_pad == 1)
        m_hi = jnp.where(drop, _U32_MAX, m_hi)
        m_lo = jnp.where(drop, _U32_MAX, m_lo)
        m_values = jnp.where(drop, jnp.zeros((), m_values.dtype), m_values)
        m_pad2 = drop.astype(jnp.uint32)
        m_pad2, m_hi, m_lo, m_values = jax.lax.sort(
            (m_pad2, m_hi, m_lo, m_values), num_keys=3
        )
        n_unique = jnp.sum(1 - m_pad2).astype(jnp.int32)
        return (
            m_values[:k],
            m_hi[:k],
            m_lo[:k],
            jnp.minimum(n_unique, k),
            ca + cb,
        )

    values, hi, lo, size, count = jax.vmap(one)(
        state_a.values, state_a.hash_hi, state_a.hash_lo, state_a.size,
        state_a.count,
        state_b.values, state_b.hash_hi, state_b.hash_lo, state_b.size,
        state_b.count,
        state_a.salts,
    )
    return DistinctState(values, hi, lo, size, count, state_a.salts)


def result(state: DistinctState) -> Tuple[jax.Array, jax.Array]:
    """``(values [R, k], size [R])``, sorted by scrambled hash ascending —
    the order the contract leaves unspecified (``Sampler.scala:411``), made
    canonical (and oracle-comparable) here."""
    return state.values, state.size
