"""Distinct-value sampling on device: salted bottom-k via XLA sorts (M3).

The reference's ``RandomValues`` engine (``Sampler.scala:383-412``) keeps the
k distinct values with the smallest salted 64-bit hashes using a max-heap +
membership set.  Pointer-chasing heaps and hash sets have no TPU analog
(SURVEY §7.3 "Distinct mode without hash tables"); the device design exploits
that bottom-k-of-a-hash is a *mergeable summary*:

    state (k entries) ∪ tile (B entries)  --sort+dedup+truncate-->  state'

Per tile and reservoir: scramble the tile's hashes (same integer-exact
:func:`~reservoir_tpu.ops.hashing.scramble64` as the CPU oracle — results are
bit-comparable), concatenate with the carried entries, multi-key sort
``(pad, hash_hi, hash_lo, value)``, mask duplicate runs, re-sort survivors,
keep the k smallest.  Two ``lax.sort`` passes of k+B lanes replace the
reference's per-element heap ops; a whole tile costs O((k+B) log(k+B))
comparisons regardless of duplication structure.

Semantics preserved (SURVEY §2.2 invariant 6): inclusion is uniform over
distinct values via the salted hash order; dedup is by value (equal values
have equal hashes and collapse to one entry).  Two *distinct* values
colliding in the full 64-bit hash are both kept — same as the reference,
whose membership set is keyed on value while only the threshold uses the
hash (``Sampler.scala:396-408``); hash-order ties are the shared ~2^-64
bias source.  ``map`` applies to every element (it feeds the hash,
``Sampler.scala:155, 395``).  Tile-split invariance holds because the merge
is associative and order-insensitive.

Sample dtypes: any 32-bit integer type natively, and 64-bit integer keys
(the realistic dedup workload, ``Sampler.scala:173-180`` takes any ``B`` +
hash) via **bit-plane storage** — a 64-bit value lives as two ``[R, k]``
uint32 planes (``value_hi`` + ``values``), never as a device int64: TPU has
no native 64-bit lanes, so the planes keep every op on the fast uint32 VPU
path and x64 mode stays off.  Callers feed 64-bit tiles as an
``(hi, lo)``-plane pair (the engine splits host int64 arrays automatically)
and reassemble results with :func:`assemble_values`.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from .hashing import default_hash64, scramble64

__all__ = [
    "DistinctState",
    "init",
    "update",
    "update_steady",
    "result",
    "merge",
    "assemble_values",
    "split_values",
]

_U32_MAX = jnp.uint32(0xFFFFFFFF)


class DistinctState(NamedTuple):
    """R lockstep distinct-value reservoirs.

    Entries ``[r, i]`` for ``i < size[r]`` are the current bottom-k, sorted by
    scrambled hash ascending; the rest are canonical padding (hash = MAX,
    value = 0) marked by ``size``.

    ``value_hi`` is None for 4-byte sample dtypes (``values`` carries the
    sample dtype directly).  For 8-byte integer keys, ``values`` is the low
    uint32 bit-plane and ``value_hi`` the high plane —
    :func:`assemble_values` reassembles host-side.
    """

    values: jax.Array  # [R, k] sample dtype (narrow) / uint32 lo plane (wide)
    hash_hi: jax.Array  # [R, k] uint32
    hash_lo: jax.Array  # [R, k] uint32
    size: jax.Array  # [R] int32
    count: jax.Array  # [R] count dtype — total elements seen
    salts: jax.Array  # [R, 4] uint32 — (r0_hi, r0_lo, r1_hi, r1_lo)
    value_hi: Optional[jax.Array] = None  # [R, k] uint32 — 64-bit key mode

    @property
    def wide(self) -> bool:
        """True when this state stores 64-bit keys as bit-planes."""
        return self.value_hi is not None


def split_values_host(values: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Split a host int64/uint64 array into ``(hi, lo)`` uint32 HOST planes
    — the single owner of the wide-tile bit layout and its dtype check
    (used per-tile via :func:`split_values` and whole-stream by the
    engine's fused scan, which reshapes the planes before one staged
    transfer)."""
    v = np.asarray(values)
    if v.dtype.itemsize != 8 or v.dtype.kind not in "iu":
        raise ValueError(
            f"expected 64-bit integer keys; got dtype {v.dtype}"
        )
    u = v.view(np.uint64)
    hi = (u >> np.uint64(32)).astype(np.uint32)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return hi, lo


def split_values(values: np.ndarray) -> Tuple[jax.Array, jax.Array]:
    """Split a host int64/uint64 array into ``(hi, lo)`` uint32 device planes
    — the wide-mode tile format."""
    hi, lo = split_values_host(values)
    # device_put (async) over jnp.asarray (chunked-synchronous on tunneled
    # backends); hi/lo are freshly allocated above, so the async read is safe
    return jax.device_put(hi), jax.device_put(lo)


def assemble_values(
    values, value_hi, sample_dtype: Any
) -> np.ndarray:
    """Host-side inverse of the bit-plane storage: reassemble user-dtype
    values from a (possibly wide) state's value arrays."""
    sample_dtype = np.dtype(sample_dtype)
    vlo = np.asarray(values)
    if value_hi is None:
        return vlo.view(sample_dtype) if vlo.dtype != sample_dtype else vlo
    hi = np.asarray(value_hi).astype(np.uint64)
    lo = np.asarray(vlo).astype(np.uint64)
    return ((hi << np.uint64(32)) | lo).view(sample_dtype)


def init(
    key: jax.Array,
    num_reservoirs: int,
    k: int,
    sample_dtype: Any = jnp.int32,
    count_dtype: Any = jnp.int32,
) -> DistinctState:
    """Empty reservoirs with per-instance salts drawn once
    (``Sampler.scala:385-388``).  8-byte integer ``sample_dtype`` selects
    wide (bit-plane) storage."""
    sample_dtype = jnp.dtype(sample_dtype)
    if not (
        jnp.issubdtype(sample_dtype, jnp.integer)
        and sample_dtype.itemsize in (4, 8)
    ):
        raise ValueError(
            "distinct mode requires a 32- or 64-bit integer sample dtype "
            f"(value bits feed the hash and dedup key); got {sample_dtype}"
        )
    wide = sample_dtype.itemsize == 8
    salts = jr.bits(key, (num_reservoirs, 4), jnp.uint32)
    return DistinctState(
        values=jnp.zeros(
            (num_reservoirs, k), jnp.uint32 if wide else sample_dtype
        ),
        hash_hi=jnp.full((num_reservoirs, k), _U32_MAX),
        hash_lo=jnp.full((num_reservoirs, k), _U32_MAX),
        size=jnp.zeros((num_reservoirs,), jnp.int32),
        count=jnp.zeros((num_reservoirs,), count_dtype),
        salts=salts,
        value_hi=jnp.zeros((num_reservoirs, k), jnp.uint32) if wide else None,
    )


def _value_planes(batch) -> Tuple[jax.Array, jax.Array]:
    """Uniform bit-plane view of a batch: an ``(hi, lo)`` uint32 pair for
    wide tiles, a sign-extended embedding (same as :func:`default_hash64`)
    for 4-byte tiles."""
    if isinstance(batch, tuple):
        bhi, blo = batch
        return bhi.astype(jnp.uint32), blo.astype(jnp.uint32)
    hi, lo = default_hash64(batch)
    return hi.astype(jnp.uint32), lo.astype(jnp.uint32)


def _bottom_k_merge(pad, hhi, hlo, vhi, vlo, k: int):
    """Shared sort-dedup-truncate core of :func:`update` and :func:`merge`.

    One code path for narrow and wide keys: values travel as uint32
    bit-planes, dedup groups on the full (hash, value-bits) key.  One
    ``lax.sort`` pass of ``len(pad)`` lanes replaces the reference's
    per-element heap ops; the dedup/padding squeeze-out afterwards is a
    *stable compaction* of an already-sorted array (survivors keep their
    relative hash order), so it is a cumsum-rank scatter in O(n), not a
    second O(n log n) sort.
    """
    n = pad.shape[0]
    # sort by (pad, hash, value-bits): equal values -> equal hashes -> adjacent
    pad, hhi, hlo, vhi, vlo = jax.lax.sort(
        (pad, hhi, hlo, vhi, vlo), num_keys=5
    )
    same_as_prev = (
        (pad == jnp.roll(pad, 1))
        & (hhi == jnp.roll(hhi, 1))
        & (hlo == jnp.roll(hlo, 1))
        & (vhi == jnp.roll(vhi, 1))
        & (vlo == jnp.roll(vlo, 1))
    )
    same_as_prev = same_as_prev.at[0].set(False)
    keep = ~(same_as_prev | (pad == 1))

    # compact survivors to the front (their order is already hash-ascending);
    # only the first k destinations are materialized — the rest drop
    dest = jnp.where(keep, jnp.cumsum(keep.astype(jnp.int32)) - 1, n)
    out_hhi = jnp.full((k,), _U32_MAX).at[dest].set(hhi, mode="drop")
    out_hlo = jnp.full((k,), _U32_MAX).at[dest].set(hlo, mode="drop")
    out_vhi = jnp.zeros((k,), jnp.uint32).at[dest].set(vhi, mode="drop")
    out_vlo = jnp.zeros((k,), jnp.uint32).at[dest].set(vlo, mode="drop")
    n_unique = jnp.sum(keep).astype(jnp.int32)
    return out_hhi, out_hlo, out_vhi, out_vlo, jnp.minimum(n_unique, k)


def _update_one(
    values,
    value_hi,
    hash_hi,
    hash_lo,
    size,
    count,
    salts,
    batch,
    valid,
    k: int,
    map_fn: Optional[Callable],
    hash_fn: Optional[Callable],
    wide: bool,
):
    """Single-reservoir tile merge (vmapped over R)."""
    bsz = batch[0].shape[0] if isinstance(batch, tuple) else batch.shape[0]
    mapped = map_fn(batch) if map_fn is not None else batch  # every element
    if hash_fn is not None:
        bhi, blo = hash_fn(mapped)
    else:
        bhi, blo = _value_planes(mapped)  # identity embedding (Sampler.scala:75)
    bhi, blo = scramble64(
        bhi.astype(jnp.uint32),
        blo.astype(jnp.uint32),
        salts[0],
        salts[1],
        salts[2],
        salts[3],
    )
    bvhi, bvlo = _value_planes(mapped)

    in_tile = jnp.arange(bsz) < valid
    # pad key: carried padding (>= size) and masked tile lanes sort last
    carried_pad = (jnp.arange(k) >= size).astype(jnp.uint32)
    tile_pad = (~in_tile).astype(jnp.uint32)

    cvlo = values if wide else values.view(jnp.uint32)
    cvhi = value_hi if wide else _carried_hi(values)
    m_pad = jnp.concatenate([carried_pad, tile_pad])
    m_hi = jnp.concatenate([hash_hi, bhi])
    m_lo = jnp.concatenate([hash_lo, blo])
    m_vhi = jnp.concatenate([cvhi, bvhi])
    m_vlo = jnp.concatenate([cvlo, bvlo])

    new_hi, new_lo, new_vhi, new_vlo, new_size = _bottom_k_merge(
        m_pad, m_hi, m_lo, m_vhi, m_vlo, k
    )
    new_count = count + valid.astype(count.dtype)
    if wide:
        return new_vlo, new_vhi, new_hi, new_lo, new_size, new_count
    return (
        new_vlo.view(values.dtype),
        new_vhi,  # recomputed view, discarded by the caller in narrow mode
        new_hi,
        new_lo,
        new_size,
        new_count,
    )


def _carried_hi(values) -> jax.Array:
    """Sign-extension plane of carried 4-byte values (dedup key symmetry
    with the tile side's :func:`_value_planes`)."""
    hi, _ = default_hash64(values)
    return hi.astype(jnp.uint32)


def update(
    state: DistinctState,
    batch,
    valid: Optional[jax.Array] = None,
    map_fn: Optional[Callable] = None,
    hash_fn: Optional[Callable] = None,
) -> DistinctState:
    """Merge one ``[R, B]`` tile into the bottom-k state.

    ``batch`` is a ``[R, B]`` array of the sample dtype, or — in wide
    (64-bit key) mode — an ``(hi, lo)`` pair of ``[R, B]`` uint32 planes
    (:func:`split_values`).  ``hash_fn`` (optional) maps a mapped-value tile
    to a ``(hi, lo)`` uint32 pair *before* salting — the user-hash hook of
    ``Sampler.distinct`` (``Sampler.scala:173-180``); the default embeds the
    value bits identically to the CPU oracle's default hash.
    """
    k = state.values.shape[1]
    wide = state.wide
    if wide and not isinstance(batch, tuple):
        raise ValueError(
            "wide (64-bit key) states take batches as (hi, lo) uint32 plane "
            "pairs; see ops.distinct.split_values"
        )
    if valid is None:
        bsz = batch[0].shape[1] if wide else batch.shape[1]
        valid_arg = jnp.asarray(bsz, jnp.int32)
        valid_ax = None
    else:
        valid_arg = valid
        valid_ax = 0
    vhi_ax = 0 if wide else None
    values, value_hi, hi, lo, size, count = jax.vmap(
        functools.partial(
            _update_one, k=k, map_fn=map_fn, hash_fn=hash_fn, wide=wide
        ),
        in_axes=(0, vhi_ax, 0, 0, 0, 0, 0, 0, valid_ax),
    )(
        state.values,
        state.value_hi,
        state.hash_hi,
        state.hash_lo,
        state.size,
        state.count,
        state.salts,
        batch,
        valid_arg,
    )
    return DistinctState(
        values, hi, lo, size, count, state.salts,
        value_hi=value_hi if wide else None,
    )


#: Distinct mode has no fill/steady split — the merge is one code path.
update_steady = update


def merge(state_a: DistinctState, state_b: DistinctState) -> DistinctState:
    """Merge two distinct-reservoir sets over shards of the same logical
    streams: union of entries, dedup, keep the bottom-k hashes.

    Exact by the mergeable-summary property of bottom-k sketches.  Both
    states MUST share salts (same ``init`` key) — hashes are only comparable
    under one salt; shards of one logical stream are created that way.
    ``count`` adds; tile-split invariance extends across shards.
    """
    k = state_a.values.shape[1]
    wide = state_a.wide
    if wide != state_b.wide:
        raise ValueError("cannot merge narrow and wide distinct states")

    def one(va, vha, hia, loa, sza, ca, vb, vhb, hib, lob, szb, cb):
        pad = jnp.concatenate(
            [
                (jnp.arange(k) >= sza).astype(jnp.uint32),
                (jnp.arange(k) >= szb).astype(jnp.uint32),
            ]
        )
        m_hi = jnp.concatenate([hia, hib])
        m_lo = jnp.concatenate([loa, lob])
        if wide:
            m_vhi = jnp.concatenate([vha, vhb])
            m_vlo = jnp.concatenate([va, vb])
        else:
            m_vhi = jnp.concatenate([_carried_hi(va), _carried_hi(vb)])
            m_vlo = jnp.concatenate([va, vb]).view(jnp.uint32)
        n_hi, n_lo, n_vhi, n_vlo, n_size = _bottom_k_merge(
            pad, m_hi, m_lo, m_vhi, m_vlo, k
        )
        n_values = n_vlo if wide else n_vlo.view(va.dtype)
        return n_values, n_vhi, n_hi, n_lo, n_size, ca + cb

    vh_ax = 0 if wide else None
    values, value_hi, hi, lo, size, count = jax.vmap(
        one, in_axes=(0, vh_ax, 0, 0, 0, 0, 0, vh_ax, 0, 0, 0, 0)
    )(
        state_a.values, state_a.value_hi, state_a.hash_hi, state_a.hash_lo,
        state_a.size, state_a.count,
        state_b.values, state_b.value_hi, state_b.hash_hi, state_b.hash_lo,
        state_b.size, state_b.count,
    )
    return DistinctState(
        values, hi, lo, size, count, state_a.salts,
        value_hi=value_hi if wide else None,
    )


def result(state: DistinctState) -> Tuple[jax.Array, jax.Array]:
    """``(values [R, k], size [R])``, sorted by scrambled hash ascending —
    the order the contract leaves unspecified (``Sampler.scala:411``), made
    canonical (and oracle-comparable) here.  Wide states return the low
    plane; reassemble with :func:`assemble_values` (+ ``state.value_hi``)."""
    return state.values, state.size
