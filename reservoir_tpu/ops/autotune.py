"""Persistent block-geometry autotuner for the Pallas kernels + skip gate.

Every kernel's throughput is set by shape knobs — ``block_r`` (reservoir
rows per grid cell), ``chunk_b`` (batch-streaming chunk of the 2-D grid
pipeline) and, for Algorithm-L only, ``gather_chunk`` (lanes per one-hot
select+reduce) — whose winners are device- and shape-specific and can only
be measured on live hardware.  The sweep tool
(``tools/tpu_block_sweep.py``, kernel-parameterized) records winners into
a small JSON cache keyed by ``(kernel, device_kind, R, k, B, dtype)``, and
``ReservoirEngine._update_fn`` / ``bench.py`` consult it at jit-cache time
for whichever kernel the engine dispatches.

Absent a cache entry (every CPU test run, any untuned device/shape) the
lookup returns ``None`` and callers keep the hardcoded defaults, so
interpret-mode behavior is byte-identical with or without the file.  The
cache is *advisory geometry only* — every geometry is bit-identical by
construction (see the kernel modules), so a stale entry can cost speed,
never correctness.

Schema: version 2 prefixes every key with the kernel name and stamps the
file with ``"_schema": 2``.  Version-1 files (the algl-only era: bare
``device|R=..|..`` keys, no stamp) are migrated silently on load — each
bare key is read as an ``algl`` entry — and rewritten in the new schema on
the first :func:`record`.  Version 3 (ISSUE 14) adds the ``serve`` entry
kind — service-knob winners keyed by workload fingerprint
(:mod:`reservoir_tpu.serve.autotune` owns the key format and entry
shape) — without touching the kernel-geometry key form at all, so a v2
file loads unchanged and round-trips losslessly once a serve entry is
recorded next to its kernel entries.  The generic :func:`lookup_raw` /
:func:`record_raw` pair is the extension surface: new entry kinds ride
the same atomic tmp+rename store without teaching this module their
schema.

File location: ``$RESERVOIR_ALGL_AUTOTUNE_CACHE`` if set, else
``TPU_ALGL_AUTOTUNE.json`` at the repo root (committed with the sweep
evidence so tuned geometry survives across sessions).  Writes are atomic
(tmp + rename) and loads are mtime-memoized, so the per-jit lookup cost is
a stat.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import NamedTuple, Optional

import numpy as np

__all__ = [
    "Geometry",
    "KERNELS",
    "ENTRY_KINDS",
    "cache_path",
    "make_key",
    "load",
    "lookup",
    "lookup_raw",
    "record",
    "record_raw",
    "record_if_better",
]

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_DEFAULT_CACHE = os.path.join(_REPO, "TPU_ALGL_AUTOTUNE.json")

_SCHEMA = 3
#: The kernel dimension of the cache key — one entry space per Pallas path,
#: plus the host-side ``gate`` pseudo-kernel (the skip-ahead gate's
#: ``gate_tile``/``gate_push_chunk`` pair is a throughput geometry too, and
#: the sweep measures it the same way).
KERNELS = ("algl", "weighted", "distinct", "gate")
#: Every key prefix the store accepts: the kernel geometries plus the
#: schema-3 ``serve`` knob entries (ISSUE 14 — the serving plane's tuned
#: knobs live in the same file, same atomic write, same mtime memo; the
#: serve layer owns their key format and entry shape via
#: :func:`lookup_raw`/:func:`record_raw`).
ENTRY_KINDS = KERNELS + ("serve",)

# (path, mtime) -> parsed dict; loads are hot (one per engine jit-cache
# miss), files are tiny and almost never change mid-process
_LOAD_MEMO: dict = {}


class Geometry(NamedTuple):
    """One tuned kernel geometry.

    ``block_r``: rows per grid cell (0 = kernel auto-size).
    ``chunk_b``: batch-streaming chunk (0 = whole tile, no 2-D grid).
    ``gather_chunk``: one-hot gather window (0 = full width; algl only —
    the weighted/distinct kernels ignore it).
    ``gate_tile`` / ``gate_push_chunk``: candidate-tile width and push
    slice width of the skip-ahead gate (``kernel="gate"`` entries only;
    0 = untuned, callers keep their defaults).  Schema-additive trailing
    fields — entries written before they existed read back as 0.
    """

    block_r: int
    chunk_b: int
    gather_chunk: int
    gate_tile: int = 0
    gate_push_chunk: int = 0


def cache_path() -> str:
    return os.environ.get("RESERVOIR_ALGL_AUTOTUNE_CACHE", _DEFAULT_CACHE)


def make_key(
    device_kind: str, R: int, k: int, B: int, dtype, *, kernel: str = "algl"
) -> str:
    """Stable cache key: the geometry winner depends on all six."""
    return (
        f"{kernel}|{device_kind}|R={R}|k={k}|B={B}|{np.dtype(dtype).name}"
    )


def _migrate(data: dict) -> dict:
    """Entries in schema-2 key form, whatever schema the file was.

    A v1 file has no ``"_schema"`` stamp and bare (kernel-less) keys —
    every such key was written by the algl-only sweep era, so it maps to
    ``algl|<key>``.  The stamp key itself never reaches callers."""
    if data.get("_schema") == _SCHEMA:
        return {key: v for key, v in data.items() if key != "_schema"}
    out = {}
    for key, v in data.items():
        if key == "_schema" or not isinstance(key, str):
            continue
        if key.split("|", 1)[0] in ENTRY_KINDS:
            out[key] = v
        else:
            out["algl|" + key] = v
    return out


def load(path: "str | None" = None) -> dict:
    """The parsed cache entries keyed in schema-2 form ({} when absent or
    unparseable — a corrupt cache must degrade to defaults, never break
    sampling).  Version-1 files are migrated in memory here; the first
    :func:`record` persists the migration."""
    path = path or cache_path()
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return {}
    memo = _LOAD_MEMO.get(path)
    if memo is not None and memo[0] == mtime:
        return memo[1]
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except (OSError, json.JSONDecodeError):
        data = {}
    data = _migrate(data)
    _LOAD_MEMO[path] = (mtime, data)
    return data


def lookup(
    device_kind: str,
    R: int,
    k: int,
    B: int,
    dtype,
    path: "str | None" = None,
    *,
    kernel: str = "algl",
) -> Optional[Geometry]:
    """The tuned geometry for this kernel+device+shape, or None (use the
    kernel's hardcoded defaults)."""
    entry = load(path).get(
        make_key(device_kind, R, k, B, dtype, kernel=kernel)
    )
    if not isinstance(entry, dict):
        return None
    try:
        return Geometry(
            block_r=int(entry["block_r"]),
            chunk_b=int(entry.get("chunk_b", 0)),
            gather_chunk=int(entry.get("gather_chunk", 0)),
            gate_tile=int(entry.get("gate_tile", 0)),
            gate_push_chunk=int(entry.get("gate_push_chunk", 0)),
        )
    except (KeyError, TypeError, ValueError):
        return None


def lookup_raw(key: str, path: "str | None" = None) -> Optional[dict]:
    """The raw entry dict under ``key``, or None.  The extension surface
    for non-geometry entry kinds (``serve|...`` knob winners): the caller
    owns the key format and the entry shape; this module only guarantees
    the atomic store and the mtime-memoized load."""
    entry = load(path).get(key)
    return entry if isinstance(entry, dict) else None


def record_raw(key: str, entry: dict, path: "str | None" = None) -> None:
    """Write one raw entry (atomic tmp+rename; merges with the existing
    file, migrating it to the current schema as it does).  The key's
    prefix must be a registered entry kind — anything else would be
    rewritten as an ``algl`` key by the v1 migration on the next load."""
    kind = key.split("|", 1)[0]
    if kind not in ENTRY_KINDS:
        raise ValueError(
            f"unknown entry kind {kind!r}: key prefix must be one of "
            f"{ENTRY_KINDS}"
        )
    path = path or cache_path()
    data = dict(load(path))
    data[key] = entry
    data["_schema"] = _SCHEMA
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".autotune.", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _LOAD_MEMO.pop(path, None)


def record(
    device_kind: str,
    R: int,
    k: int,
    B: int,
    dtype,
    geometry: Geometry,
    elem_per_sec: "float | None" = None,
    source: "str | None" = None,
    path: "str | None" = None,
    *,
    kernel: str = "algl",
) -> None:
    """Write one geometry entry (atomic tmp+rename; merges with the
    existing file, migrating a v1 file to the current schema as it does).
    ``elem_per_sec``/``source`` ride along as provenance —
    :func:`record_if_better` uses the rate to keep only winners."""
    entry = {
        "block_r": int(geometry.block_r),
        "chunk_b": int(geometry.chunk_b),
        "gather_chunk": int(geometry.gather_chunk),
    }
    # gate fields only when set — non-gate entries keep their exact shape
    if geometry.gate_tile:
        entry["gate_tile"] = int(geometry.gate_tile)
    if geometry.gate_push_chunk:
        entry["gate_push_chunk"] = int(geometry.gate_push_chunk)
    if elem_per_sec is not None:
        entry["elem_per_sec"] = float(elem_per_sec)
    if source is not None:
        entry["source"] = source
    record_raw(
        make_key(device_kind, R, k, B, dtype, kernel=kernel), entry, path
    )


def record_if_better(
    device_kind: str,
    R: int,
    k: int,
    B: int,
    dtype,
    geometry: Geometry,
    elem_per_sec: float,
    source: "str | None" = None,
    path: "str | None" = None,
    *,
    kernel: str = "algl",
) -> bool:
    """Record only if no entry exists or this rate beats the stored one
    (sweep callers: every variant reports through here, winners stick).
    Returns whether the entry was written."""
    entry = load(path).get(
        make_key(device_kind, R, k, B, dtype, kernel=kernel)
    )
    if isinstance(entry, dict):
        prev = entry.get("elem_per_sec")
        if isinstance(prev, (int, float)) and prev >= elem_per_sec:
            return False
    record(
        device_kind, R, k, B, dtype, geometry,
        elem_per_sec=elem_per_sec, source=source, path=path, kernel=kernel,
    )
    return True
