"""Pure-jnp Threefry-2x32 — the framework's one counter-based RNG core.

The reference threads a sequential ``scala.util.Random`` through its hot loop
(``Sampler.scala:199, 228-236``); this framework keys every draw on a counter
instead (see :mod:`reservoir_tpu.ops.rng`).  The cipher here is the same
Threefry-2x32 that backs ``jax.random`` — re-implemented with plain jnp
bitwise ops so that the *identical* math runs in three places:

- the XLA vmap kernels (:mod:`reservoir_tpu.ops.algorithm_l`),
- the Pallas TPU kernel (:mod:`reservoir_tpu.ops.algorithm_l_pallas`), whose
  traced body cannot call ``jax.random`` primitives, and
- any host-side oracle that wants draw parity.

Bit-compatibility with ``jax.random`` (threefry impl, partitionable mode) is
pinned by ``tests/test_threefry.py``: ``fold_in_words`` matches
``jr.key_data(jr.fold_in(key, idx))`` and ``bits_words`` matches
``jr.bits(key, (n,), uint32)`` word-for-word.  That equality is what makes
"vmap path == Pallas path" testable bit-for-bit rather than statistically.

All functions take raw ``uint32`` key words (``jr.key_data(key)``), never
typed key arrays — typed keys cannot cross a ``pallas_call`` boundary.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "threefry2x32",
    "fold_in_words",
    "fold_in_words_pair",
    "bits_words",
    "counter_bits",
    "counter_bits_pair",
]

_PARITY = np.uint32(0x1BD11BDA)
# Rotation schedule for Threefry-2x32, 20 rounds in 5 groups of 4.
_ROTATIONS = ((13, 15, 26, 6), (17, 29, 16, 24))


def _rotl(x: jax.Array, d: int) -> jax.Array:
    return (x << np.uint32(d)) | (x >> np.uint32(32 - d))


def threefry2x32(
    k1: jax.Array, k2: jax.Array, x0: jax.Array, x1: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Hash independent 2-word blocks ``(x0, x1)`` under key ``(k1, k2)``.

    Elementwise over broadcastable uint32 arrays — each lane is one block,
    exactly the semantics of jax's ``threefry2x32_p`` primitive.
    """
    ks0 = jnp.asarray(k1, jnp.uint32)
    ks1 = jnp.asarray(k2, jnp.uint32)
    ks2 = ks0 ^ ks1 ^ _PARITY
    ks = (ks0, ks1, ks2)
    x0 = jnp.asarray(x0, jnp.uint32) + ks0
    x1 = jnp.asarray(x1, jnp.uint32) + ks1
    for group in range(5):
        for r in _ROTATIONS[group % 2]:
            x0 = x0 + x1
            x1 = _rotl(x1, r) ^ x0
        x0 = x0 + ks[(group + 1) % 3]
        x1 = x1 + ks[(group + 2) % 3] + np.uint32(group + 1)
    return x0, x1


def fold_in_words(
    k1: jax.Array, k2: jax.Array, idx: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """``jr.fold_in(key, idx)`` on raw words: one block hash of the seed pair
    ``[idx >> 32, idx & 0xffffffff]`` (jax's ``threefry_seed`` layout; the
    high word of a 32-bit index is 0).

    Deliberate improvement over ``jr.fold_in``, which casts its operand to
    uint32 and therefore repeats draws with period 2^32: for 64-bit ``idx``
    the high word is folded in too, so int64 streams past 2^32 elements per
    reservoir keep fresh draws.  Identical to jax for any idx < 2^32.
    """
    idx = jnp.asarray(idx)
    lo = idx.astype(jnp.uint32)
    if idx.dtype.itemsize == 8:
        hi = (idx >> 32).astype(jnp.uint32)
    else:
        hi = jnp.zeros_like(lo)
    return threefry2x32(k1, k2, hi, lo)


def fold_in_words_pair(
    k1: jax.Array, k2: jax.Array, idx_hi: jax.Array, idx_lo: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """:func:`fold_in_words` for a 64-bit index carried as explicit
    ``(hi, lo)`` uint32 words — the form emulated-uint64 state uses when
    x64 is off (:mod:`reservoir_tpu.ops.u64e`).  Bit-identical to
    ``fold_in_words(k1, k2, (hi << 32) | lo)`` by construction: both hash
    the block ``(hi, lo)``."""
    return threefry2x32(
        k1, k2, jnp.asarray(idx_hi, jnp.uint32), jnp.asarray(idx_lo, jnp.uint32)
    )


def bits_words(k1: jax.Array, k2: jax.Array, n: int):
    """``jr.bits(key, (n,), uint32)`` on raw words, for small static ``n``:
    word ``j`` comes from block ``(0, j)`` as ``out0 ^ out1`` (jax's
    partitionable counter layout: 64-bit iota split hi/lo, xor-folded).

    Returns a tuple of ``n`` arrays shaped like ``k1`` — kept separate (not
    stacked) so callers inside Pallas stay free of reshapes.
    """
    words = []
    zero = jnp.zeros_like(jnp.asarray(k1, jnp.uint32))
    for j in range(n):
        b0, b1 = threefry2x32(k1, k2, zero, zero + np.uint32(j))
        words.append(b0 ^ b1)
    return tuple(words)


def counter_bits(k1: jax.Array, k2: jax.Array, idx: jax.Array, n: int):
    """The framework's standard per-event draw: ``n`` uint32 words for the
    counter-derived key ``fold_in(key, idx)`` — elementwise over ``idx``
    lanes.  Equals ``jr.bits(jr.fold_in(key, idx), (n,), uint32)`` for
    idx < 2^32; for 64-bit ``idx`` the full index is folded in (see
    :func:`fold_in_words`)."""
    f1, f2 = fold_in_words(k1, k2, idx)
    return bits_words(f1, f2, n)


def counter_bits_pair(
    k1: jax.Array, k2: jax.Array, idx_hi: jax.Array, idx_lo: jax.Array, n: int
):
    """:func:`counter_bits` for an index carried as ``(hi, lo)`` words —
    bit-identical to the int64 path for the same logical index."""
    f1, f2 = fold_in_words_pair(k1, k2, idx_hi, idx_lo)
    return bits_words(f1, f2, n)
