"""Pallas ring all-gather for the device-side reservoir merge (ISSUE 12).

The collective half of :func:`reservoir_tpu.parallel.merge.merge_samples_device`:
per-part reservoir state (sample rows, counts, and the per-mode sub-state
leaves) moves between devices as chip-to-chip ``make_async_remote_copy``
remote DMAs around a logical ring — the SNIPPETS [1]/[3] pattern — instead
of an XLA ``all_gather``.  The kernel is DATA MOVEMENT only: it fills the
``[d, b, W]`` gathered buffer and the deterministic node-numbered merge
tree then runs on-chip in the enclosing ``shard_map`` program, so the
merged result is bit-identical to the XLA-collective and host paths by
construction (same pairwise math, same tree order — the kernel never
touches a sample value).

Ring protocol (one step per remote block):

- every device stores its local block into its own slot of the output
  buffer, then barriers with both ring neighbors
  (``get_barrier_semaphore``, the collective-id handshake);
- at step ``s`` each device forwards the block it holds for logical part
  slot ``(my - s) mod d`` to its right neighbor's same slot and waits for
  the matching block ``(my - 1 - s) mod d`` arriving from the left.  Each
  output slot is written exactly once, and a slot is only forwarded one
  step after its arrival was waited on, so the fully-waited ring needs no
  double buffer.

TPU-only (remote DMA does not lower on the CPU interpreter): callers gate
on :func:`available` and demote to XLA collectives — parity on real
hardware rides the ``parity_probe`` selftest JSON (``merge_parity`` row).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["available", "ring_all_gather", "gather_parts"]

# Lane/sublane-friendly pack geometry: the packed part matrix is padded to
# [b multiple of 8, W multiple of 128] uint32 words before it rides the ring.
_LANES = 128
_SUBLANES = 8


def available() -> bool:
    """Whether the ring kernel can lower here (TPU backend only — remote
    DMA has no CPU-interpreter path)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _ring_kernel(local_ref, out_ref, send_sem, recv_sem, *, axis, d):
    my_id = jax.lax.axis_index(axis)
    right = jax.lax.rem(my_id + 1, d)
    left = jax.lax.rem(my_id + d - 1, d)
    # local block lands in its own output slot before anything moves
    out_ref[pl.ds(my_id, 1)] = local_ref[:][None]
    # neighbor handshake: no remote DMA may land before both neighbors
    # have entered the kernel (their output buffers exist)
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(
        barrier, inc=1, device_id=(left,),
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    pltpu.semaphore_signal(
        barrier, inc=1, device_id=(right,),
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    pltpu.semaphore_wait(barrier, 2)
    for step in range(d - 1):
        # forward the newest fully-arrived block; its slot index is the
        # same on both ends of the hop, so src and dst refs agree
        blk = jax.lax.rem(my_id + d - step, d) if step else my_id
        rdma = pltpu.make_async_remote_copy(
            src_ref=out_ref.at[pl.ds(blk, 1)],
            dst_ref=out_ref.at[pl.ds(blk, 1)],
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=(right,),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        # .wait() = my send drained AND the matching block from the left
        # (slot (my - 1 - step) mod d) has landed — the block forwarded
        # next step
        rdma.wait()


@functools.lru_cache(maxsize=None)
def _ring_call(d: int, b: int, w: int, axis: str):
    """The pallas_call for a ``[b, w]`` uint32 block on a ``d``-ring."""
    return pl.pallas_call(
        functools.partial(_ring_kernel, axis=axis, d=d),
        out_shape=jax.ShapeDtypeStruct((d, b, w), jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            has_side_effects=True, collective_id=0
        ),
    )


def ring_all_gather(block: jax.Array, *, axis: str, axis_size: int) -> jax.Array:
    """All-gather one ``[b, w]`` uint32 block over the ``axis`` ring via
    remote DMA: returns ``[axis_size, b, w]`` with slot ``i`` holding
    device ``i``'s block.  Must run inside ``shard_map`` over ``axis``."""
    b, w = block.shape
    return _ring_call(axis_size, b, w, axis)(block)


def gather_parts(
    leaves: Sequence[jax.Array], *, axis: str, axis_size: int
) -> Tuple[jax.Array, ...]:
    """All-gather every per-part state leaf over the ``axis`` ring in ONE
    packed remote-DMA stream.

    Each leaf is ``[b, ...]`` (this device's block of part rows, all
    4-byte dtypes).  Leaves are flattened per row, bitcast to uint32,
    concatenated into one ``[b, W]`` matrix (padded to lane/sublane
    multiples), sent around the ring once, then split and bitcast back —
    so a merge's sample tile, counts, and per-mode sub-state cross the
    interconnect as a single DMA per hop.  Returns the gathered leaves
    with leading axis ``axis_size * b`` (device-major part order, matching
    the XLA ``all_gather`` + reshape layout).
    """
    b = leaves[0].shape[0]
    cols = []
    widths = []
    for leaf in leaves:
        if np.dtype(leaf.dtype).itemsize != 4:
            raise ValueError(
                f"gather_parts packs 4-byte leaves only, got {leaf.dtype}"
            )
        flat = leaf.reshape(b, -1)
        if flat.dtype != jnp.uint32:
            flat = jax.lax.bitcast_convert_type(flat, jnp.uint32)
        cols.append(flat)
        widths.append(flat.shape[1])
    packed = jnp.concatenate(cols, axis=1) if len(cols) > 1 else cols[0]
    w_tot = packed.shape[1]
    w_pad = -(-w_tot // _LANES) * _LANES
    b_pad = -(-b // _SUBLANES) * _SUBLANES
    if w_pad != w_tot or b_pad != b:
        packed = jnp.pad(packed, ((0, b_pad - b), (0, w_pad - w_tot)))
    gathered = ring_all_gather(packed, axis=axis, axis_size=axis_size)
    flat_g = gathered[:, :b].reshape(axis_size * b, w_pad)
    out = []
    off = 0
    for leaf, width in zip(leaves, widths):
        part = flat_g[:, off : off + width]
        off += width
        if leaf.dtype != jnp.uint32:
            part = jax.lax.bitcast_convert_type(part, leaf.dtype)
        out.append(part.reshape((axis_size * b,) + leaf.shape[1:]))
    return tuple(out)
