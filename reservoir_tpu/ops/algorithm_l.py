"""Vmapped Algorithm-L reservoir sampling on device (SURVEY §7.2 M1).

The reference's single mutable sampler (``RandomElements``,
``Sampler.scala:196-332``) becomes a pure pytree of ``[R, ...]`` arrays — R
independent reservoirs updated in lockstep by functional transforms:

- per-element hot loop (``Sampler.scala:248-259``)  ->  tile-batched
  :func:`update`: each reservoir consumes a ``[B]`` slice of its stream per
  device step;
- skip-jump bulk path (``Sampler.scala:261-287``)   ->  the acceptance
  ``while_loop`` jumps straight to accepted positions; a tile containing no
  acceptance costs one compare per reservoir, and *skipped elements are never
  gathered* — the Algorithm-L structural win, vectorized;
- mutable ``rand``/``W``/``nextSampleCount`` fields (``:199-205``)  ->
  counter-based draws keyed on the absolute accept index
  (:mod:`reservoir_tpu.ops.rng`), log-space ``W`` (SURVEY §7.3).

Tile-split invariance (the ``sample == sampleAll`` contract,
``SamplerTest.scala:117-142``): because draws are keyed by absolute index,
``update`` over any partition of the stream — element-at-a-time, fixed tiles,
ragged ``valid`` lengths — yields bit-identical state.  Tested in
``tests/test_device_algl.py``.

Semantics invariants preserved (SURVEY §2.2): fill phase stores the first k
in arrival order; eviction overwrites a uniform random slot; ``result`` with
count < k truncates to arrival order; ``map`` is applied on accept only.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from .rng import accept_draws_words, key_words, uniforms

__all__ = ["ReservoirState", "init", "update", "update_steady", "result", "merge"]


class ReservoirState(NamedTuple):
    """Pure state of R lockstep reservoirs (the device analog of
    ``RandomElements``' mutable fields, ``Sampler.scala:199-205``).

    Attributes:
      samples: ``[R, k]``   stored samples (post-``map``).
      count:   ``[R]`` int  elements consumed per reservoir.
      nxt:     ``[R]`` int  absolute 1-based index of the next acceptance;
               saturates at dtype max (sampling effectively stops there —
               use int64/x64 for streams longer than 2^31 per reservoir).
      log_w:   ``[R]`` f32  log of Algorithm L's W.
      key:     ``[R]``      per-reservoir PRNG keys (split once at init).
    """

    samples: jax.Array
    count: jax.Array
    nxt: jax.Array
    log_w: jax.Array
    key: jax.Array

    @property
    def num_reservoirs(self) -> int:
        return self.samples.shape[0]

    @property
    def k(self) -> int:
        return self.samples.shape[1]


def _advance(log_w: jax.Array, nxt: jax.Array, key: jax.Array, idx, k: int):
    """:func:`_advance_words` on a typed jax key."""
    k1, k2 = key_words(key)
    return _advance_words(log_w, nxt, k1, k2, idx, k)


def _advance_words(
    log_w: jax.Array, nxt: jax.Array, k1: jax.Array, k2: jax.Array, idx, k: int
):
    """Algorithm-L skip recomputation (``Sampler.scala:228-236``) using the
    draws assigned to accept-index ``idx``.

    ``W *= u1^(1/k)`` in log-space; ``next += floor(log(u2)/log(1-W)) + 1``
    with saturating integer arithmetic (no wraparound past dtype max).

    Raw-key-words form, elementwise over lanes — the *same trace* runs inside
    the XLA vmap path and the Pallas kernel, which is what makes the two
    bit-identical (``tests/test_pallas_algl.py``).
    """
    dtype = nxt.dtype
    maxval = np.iinfo(dtype).max
    slot, u1, u2 = accept_draws_words(k1, k2, idx, k)
    log_w = log_w + jnp.log(u1) / k
    w = jnp.exp(log_w)
    # w rounding to exactly 1.0 gives log1p(-1) = -inf -> skip 0; fine.
    skip_f = jnp.floor(jnp.log(u2) / jnp.log1p(-w))
    # clamp before int cast: huge float -> dtype max would be UB-ish
    skip = jnp.minimum(skip_f, float(maxval // 2)).astype(dtype)
    headroom = maxval - skip - 1
    nxt = jnp.where(nxt > headroom, dtype.type(maxval), nxt + skip + 1)
    return slot, log_w, nxt


def init(
    key: jax.Array,
    num_reservoirs: int,
    k: int,
    sample_dtype: Any = jnp.int32,
    count_dtype: Any = jnp.int32,
) -> ReservoirState:
    """Create R empty reservoirs (ctor path, ``Sampler.scala:196-207``).

    Device buffers are statically shaped at ``[R, k]`` — the ``preAllocate``
    mode of the reference is the only mode XLA admits.
    """
    count_dtype = jnp.dtype(count_dtype)
    keys = jr.split(key, num_reservoirs)

    def one(key_r):
        log_w0 = jnp.zeros((), jnp.float32)
        nxt0 = jnp.asarray(k, count_dtype)
        # initial W/next draw, keyed on index 0 (construction-time advance,
        # Sampler.scala:207)
        _, log_w, nxt = _advance(log_w0, nxt0, key_r, jnp.asarray(0, count_dtype), k)
        return log_w, nxt

    log_w, nxt = jax.vmap(one)(keys)
    return ReservoirState(
        samples=jnp.zeros((num_reservoirs, k), sample_dtype),
        count=jnp.zeros((num_reservoirs,), count_dtype),
        nxt=nxt,
        log_w=log_w,
        key=keys,
    )


def _accept_loop(
    samples: jax.Array,
    count: jax.Array,
    nxt: jax.Array,
    log_w: jax.Array,
    key: jax.Array,
    batch: jax.Array,
    end: jax.Array,
    k: int,
    map_fn: Optional[Callable],
):
    """Process every acceptance landing in ``(count, end]`` for one reservoir.

    The vmapped ``while_loop`` runs until the slowest lane is done; lanes with
    no acceptance in the tile cost one compare (the hot-path property,
    ``Sampler.scala:257``).
    """

    def cond(carry):
        _, nxt_c, _ = carry
        return nxt_c <= end

    def body(carry):
        samples_c, nxt_c, log_w_c = carry
        pos = (nxt_c - count - 1).astype(jnp.int32)  # local index in [0, B)
        elem = batch[pos]  # OOB-clamped gather is discarded for done lanes
        slot, log_w_n, nxt_n = _advance(log_w_c, nxt_c, key, nxt_c, k)
        value = map_fn(elem) if map_fn is not None else elem
        samples_n = samples_c.at[slot].set(jnp.asarray(value, samples_c.dtype))
        return samples_n, nxt_n, log_w_n

    samples, nxt, log_w = jax.lax.while_loop(cond, body, (samples, nxt, log_w))
    return samples, nxt, log_w


def _update_one(
    state_samples,
    state_count,
    state_nxt,
    state_log_w,
    state_key,
    batch,
    valid,
    k: int,
    map_fn: Optional[Callable],
    fill: bool,
):
    """Single-reservoir tile update (vmapped over R by :func:`update`)."""
    count_dtype = state_count.dtype
    bsz = batch.shape[0]
    end = state_count + valid.astype(count_dtype)

    samples = state_samples
    if fill:
        # fill phase (Sampler.scala:253-255): element with absolute index
        # idx <= k goes to slot idx-1, in arrival order.  map applies on
        # accept; fill elements are all accepted.
        idx = state_count + jnp.arange(1, bsz + 1, dtype=count_dtype)
        in_tile = jnp.arange(bsz) < valid
        fill_mask = (idx <= k) & in_tile
        dest = jnp.where(fill_mask, (idx - 1).astype(jnp.int32), k)  # k -> dropped
        values = map_fn(batch) if map_fn is not None else batch
        samples = samples.at[dest].set(
            jnp.asarray(values, samples.dtype), mode="drop"
        )

    samples, nxt, log_w = _accept_loop(
        samples,
        state_count,
        state_nxt,
        state_log_w,
        state_key,
        batch,
        end,
        k,
        map_fn,
    )
    return samples, end, nxt, log_w


def _update(
    state: ReservoirState,
    batch: jax.Array,
    valid: Optional[jax.Array],
    map_fn: Optional[Callable],
    fill: bool,
) -> ReservoirState:
    k = state.k
    if valid is None and not fill:
        # Full steady tiles: broadcast a scalar down the vmap instead of
        # materializing a [R] constant — keeps sharding propagation trivial.
        valid_arg = jnp.asarray(batch.shape[1], jnp.int32)
        in_axes = (0, 0, 0, 0, 0, 0, None)
    elif valid is None:
        # Fill-capable full tiles get a per-lane valid array: the scalar
        # variant makes XLA compile the masked fill scatter ~20x slower on
        # TPU (measured 226ms vs 12.6ms on a [1024,1024] tile, 2026-07-29).
        # Created inside the trace, so mesh sharding still propagates.
        valid_arg = jnp.full((batch.shape[0],), batch.shape[1], jnp.int32)
        in_axes = (0, 0, 0, 0, 0, 0, 0)
    else:
        valid_arg = valid
        in_axes = (0, 0, 0, 0, 0, 0, 0)
    samples, count, nxt, log_w = jax.vmap(
        functools.partial(_update_one, k=k, map_fn=map_fn, fill=fill),
        in_axes=in_axes,
    )(state.samples, state.count, state.nxt, state.log_w, state.key, batch, valid_arg)
    return ReservoirState(samples, count, nxt, log_w, state.key)


def update(
    state: ReservoirState,
    batch: jax.Array,
    valid: Optional[jax.Array] = None,
    map_fn: Optional[Callable] = None,
) -> ReservoirState:
    """Consume one ``[R, B]`` tile: reservoir r takes ``batch[r, :valid[r]]``.

    Pure function — jit/vmap/shard_map freely.  ``valid`` (default: full
    tiles) supports ragged feeds; padding elements are never sampled.
    ``map_fn`` must be traceable; it is applied to accepted elements (tile-
    vectorized during fill).
    """
    return _update(state, batch, valid, map_fn, fill=True)


def update_steady(
    state: ReservoirState,
    batch: jax.Array,
    valid: Optional[jax.Array] = None,
    map_fn: Optional[Callable] = None,
) -> ReservoirState:
    """:func:`update` minus the fill-phase scatter — the steady-state fast
    path once every reservoir holds k elements (callers check ``count >= k``;
    the engine does this automatically).  Skipping the masked fill scatter
    saves a [B]-wide scatter per reservoir per tile."""
    return _update(state, batch, valid, map_fn, fill=False)


def merge_samples(
    samples_a: jax.Array,
    count_a: jax.Array,
    samples_b: jax.Array,
    count_b: jax.Array,
    key: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Exact result-level merge of two reservoir sets over disjoint streams.

    Reservoir r of the output is a uniform ``min(k, nA+nB)``-subset of the
    union of the two underlying streams: draw ``j ~ Hypergeometric(nA+nB,
    nA, k)`` by a k-step without-replacement scan, then take uniform random
    j / (k-j) subsets of the two reservoirs (valid because each input is
    itself a uniform subset of its stream).  This is the distributed
    one-logical-stream mode (SURVEY §5 "long-context" row): shards sample
    independently, merges ride collectives; pairs compose into tree folds.

    Args are ``(samples [R, k], count [R])`` pairs as produced by sampling —
    entries past ``min(count, k)`` are ignored.  Returns the merged pair;
    merged size is ``min(count_a + count_b, k)``.  The merge is *terminal* —
    it yields a sample, not a resumable Algorithm-L state (``W``/``nxt`` of
    a merged history are not reconstructible); keep per-shard states live to
    continue streaming.

    Counts enter the pick probabilities as f32: exact below 2^24 elements
    per shard pair, O(2^-24)-biased beyond.
    """
    k = samples_a.shape[1]

    def one(s_a, c_a, s_b, c_b, key_r):
        sz_a = jnp.minimum(c_a, k)
        sz_b = jnp.minimum(c_b, k)
        total = c_a + c_b
        m = jnp.minimum(total, k).astype(jnp.int32)

        def step(carry, t):
            rem_a, rem_b, j_a = carry
            u = _uniform01(key_r, t)
            denom = (rem_a + rem_b).astype(jnp.float32)
            pick_a = (u * denom < rem_a.astype(jnp.float32)) & (rem_a > 0)
            pick_a = pick_a | (rem_b <= 0)
            active = t < m
            take_a = active & pick_a
            take_b = active & ~pick_a
            return (
                rem_a - take_a.astype(rem_a.dtype),
                rem_b - take_b.astype(rem_b.dtype),
                j_a + take_a.astype(jnp.int32),
            ), None

        (rem_a, rem_b, j_a), _ = jax.lax.scan(
            step, (c_a, c_b, jnp.asarray(0, jnp.int32)), jnp.arange(k)
        )
        # uniform j_a-subset of A and (m - j_a)-subset of B via masked
        # argsort; draw indices k and k+1 are disjoint from the scan's t < k
        perm_a = _masked_perm(jr.fold_in(key_r, k), k, sz_a)
        perm_b = _masked_perm(jr.fold_in(key_r, k + 1), k, sz_b)
        pos = jnp.arange(k)
        from_a = pos < j_a
        idx = jnp.where(from_a, perm_a[pos], perm_b[jnp.maximum(pos - j_a, 0)])
        merged = jnp.where(from_a, s_a[idx], s_b[idx])
        merged = jnp.where(pos < m, merged, jnp.zeros((), s_a.dtype))
        return merged, total

    samples, count = jax.vmap(one)(
        samples_a, count_a, samples_b, count_b,
        jr.split(key, samples_a.shape[0]),
    )
    return samples, count


def merge(
    state_a: ReservoirState, state_b: ReservoirState, key: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """State-level convenience wrapper over :func:`merge_samples`; returns
    ``(samples [R, k], size [R], count [R])``."""
    samples, count = merge_samples(
        state_a.samples, state_a.count, state_b.samples, state_b.count, key
    )
    size = jnp.minimum(count, state_a.k).astype(count.dtype)
    return samples, size, count


def _uniform01(key: jax.Array, idx) -> jax.Array:
    return uniforms(key, idx, offset=0.5)


def _masked_perm(key: jax.Array, k: int, size) -> jax.Array:
    """A random permutation of ``[0, size)`` padded into k slots: draw k
    uniforms, push invalid slots to +inf, argsort."""
    u = jr.uniform(key, (k,))
    u = jnp.where(jnp.arange(k) < size, u, jnp.inf)
    return jnp.argsort(u).astype(jnp.int32)


def result(state: ReservoirState) -> Tuple[jax.Array, jax.Array]:
    """Device-side result: ``(samples [R, k], size [R])`` where
    ``size = min(count, k)`` (truncation contract, ``Sampler.scala:318-331``).
    Host wrappers slice ``samples[r, :size[r]]``; entries beyond ``size`` are
    zeros, never sampled data."""
    size = jnp.minimum(state.count, state.k).astype(state.count.dtype)
    mask = jnp.arange(state.k)[None, :] < size[:, None]
    return jnp.where(mask, state.samples, jnp.zeros_like(state.samples)), size
