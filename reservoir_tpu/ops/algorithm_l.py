"""Vmapped Algorithm-L reservoir sampling on device (SURVEY §7.2 M1).

The reference's single mutable sampler (``RandomElements``,
``Sampler.scala:196-332``) becomes a pure pytree of ``[R, ...]`` arrays — R
independent reservoirs updated in lockstep by functional transforms:

- per-element hot loop (``Sampler.scala:248-259``)  ->  tile-batched
  :func:`update`: each reservoir consumes a ``[B]`` slice of its stream per
  device step;
- skip-jump bulk path (``Sampler.scala:261-287``)   ->  the acceptance
  ``while_loop`` jumps straight to accepted positions; a tile containing no
  acceptance costs one compare per reservoir, and *skipped elements are never
  gathered* — the Algorithm-L structural win, vectorized;
- mutable ``rand``/``W``/``nextSampleCount`` fields (``:199-205``)  ->
  counter-based draws keyed on the absolute accept index
  (:mod:`reservoir_tpu.ops.rng`), log-space ``W`` (SURVEY §7.3).

Tile-split invariance (the ``sample == sampleAll`` contract,
``SamplerTest.scala:117-142``): because draws are keyed by absolute index,
``update`` over any partition of the stream — element-at-a-time, fixed tiles,
ragged ``valid`` lengths — yields bit-identical state.  Tested in
``tests/test_device_algl.py``.

Semantics invariants preserved (SURVEY §2.2): fill phase stores the first k
in arrival order; eviction overwrites a uniform random slot; ``result`` with
count < k truncates to arrival order; ``map`` is applied on accept only.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from . import u64e
from .rng import accept_draws_pair, accept_draws_words, key_words

__all__ = [
    "ReservoirState",
    "WIDE",
    "init",
    "update",
    "update_steady",
    "update_gated",
    "result",
    "merge",
]

#: ``count_dtype`` sentinel: carry ``count``/``nxt`` as emulated-uint64
#: uint32 pairs (:mod:`reservoir_tpu.ops.u64e`) — streams past 2^31
#: elements per reservoir with x64 OFF (VERDICT r2 item 5; the reference's
#: ``count: Long``, ``Sampler.scala:203``).  Wide states take the XLA path
#: (the Pallas kernel's ``supports()`` declines non-int32 counters).
WIDE = "wide"


class ReservoirState(NamedTuple):
    """Pure state of R lockstep reservoirs (the device analog of
    ``RandomElements``' mutable fields, ``Sampler.scala:199-205``).

    Attributes:
      samples: ``[R, k]``   stored samples (post-``map``).
      count:   ``[R]`` int  elements consumed per reservoir — or
               ``[R, 2]`` uint32 (lo, hi) emulated-uint64 planes when the
               state was built with ``count_dtype=WIDE``.
      nxt:     ``[R]`` int (or ``[R, 2]`` wide)  absolute 1-based index of
               the next acceptance; narrow dtypes saturate at dtype max
               (sampling effectively stops there — use ``WIDE`` for
               streams longer than 2^31 per reservoir without x64).
      log_w:   ``[R]`` f32  log of Algorithm L's W.
      key:     ``[R]``      per-reservoir PRNG keys (split once at init).
    """

    samples: jax.Array
    count: jax.Array
    nxt: jax.Array
    log_w: jax.Array
    key: jax.Array

    @property
    def num_reservoirs(self) -> int:
        return self.samples.shape[0]

    @property
    def k(self) -> int:
        return self.samples.shape[1]

    @property
    def wide(self) -> bool:
        """Whether counters are emulated-uint64 planes (``count_dtype=WIDE``)."""
        return self.count.ndim == 2


def _advance(log_w: jax.Array, nxt: jax.Array, key: jax.Array, idx, k: int):
    """:func:`_advance_words` on a typed jax key."""
    k1, k2 = key_words(key)
    return _advance_words(log_w, nxt, k1, k2, idx, k)


def _advance_words(
    log_w: jax.Array, nxt: jax.Array, k1: jax.Array, k2: jax.Array, idx, k: int
):
    """Algorithm-L skip recomputation (``Sampler.scala:228-236``) using the
    draws assigned to accept-index ``idx``.

    ``W *= u1^(1/k)`` in log-space; ``next += floor(log(u2)/log(1-W)) + 1``
    with saturating integer arithmetic (no wraparound past dtype max).

    Raw-key-words form, elementwise over lanes — the *same trace* runs inside
    the XLA vmap path and the Pallas kernel, which is what makes the two
    bit-identical (``tests/test_pallas_algl.py``).
    """
    dtype = nxt.dtype
    maxval = np.iinfo(dtype).max
    slot, u1, u2 = accept_draws_words(k1, k2, idx, k)
    log_w = log_w + jnp.log(u1) / k
    w = jnp.exp(log_w)
    # w rounding to exactly 1.0 gives log1p(-1) = -inf -> skip 0; fine.
    skip_f = jnp.floor(jnp.log(u2) / jnp.log1p(-w))
    # clamp before int cast: huge float -> dtype max would be UB-ish
    skip = jnp.minimum(skip_f, float(maxval // 2)).astype(dtype)
    headroom = maxval - skip - 1
    nxt = jnp.where(nxt > headroom, dtype.type(maxval), nxt + skip + 1)
    return slot, log_w, nxt


def _advance_pair(
    log_w: jax.Array,
    nxt: jax.Array,
    k1: jax.Array,
    k2: jax.Array,
    idx_hi: jax.Array,
    idx_lo: jax.Array,
    k: int,
):
    """:func:`_advance_words` for WIDE (emulated-uint64) counters.

    ``nxt`` is a ``[..., 2]`` uint32 pair; draws are keyed on the
    ``(idx_hi, idx_lo)`` absolute index — bit-identical to the int64 path
    for the same logical index (same Threefry block), and the skip
    arithmetic (`f32 -> hi/lo split`) is exact, so wide and int64 states
    evolve bit-identically (``tests/test_wide_count.py``).
    """
    slot, u1, u2 = accept_draws_pair(k1, k2, idx_hi, idx_lo, k)
    log_w = log_w + jnp.log(u1) / k
    w = jnp.exp(log_w)
    skip_f = jnp.floor(jnp.log(u2) / jnp.log1p(-w))
    # clamp below 2^62: headroom for the uint64 adds (a skip that large is
    # unreachable anyway — it exceeds any feedable stream)
    skip_f = jnp.minimum(skip_f, float(2.0**62))
    nxt = u64e.add_f32(u64e.add_u32(nxt, jnp.uint32(1)), skip_f)
    return slot, log_w, nxt


def init(
    key: jax.Array,
    num_reservoirs: int,
    k: int,
    sample_dtype: Any = jnp.int32,
    count_dtype: Any = jnp.int32,
) -> ReservoirState:
    """Create R empty reservoirs (ctor path, ``Sampler.scala:196-207``).

    Device buffers are statically shaped at ``[R, k]`` — the ``preAllocate``
    mode of the reference is the only mode XLA admits.  ``count_dtype=WIDE``
    selects emulated-uint64 counters (no x64 needed; see :data:`WIDE`).
    """
    keys = jr.split(key, num_reservoirs)
    if isinstance(count_dtype, str) and count_dtype == WIDE:

        def one_wide(key_r):
            log_w0 = jnp.zeros((), jnp.float32)
            nxt0 = u64e.from_int(k)
            zero = jnp.zeros((), jnp.uint32)
            k1, k2 = key_words(key_r)
            _, log_w, nxt = _advance_pair(log_w0, nxt0, k1, k2, zero, zero, k)
            return log_w, nxt

        log_w, nxt = jax.vmap(one_wide)(keys)
        return ReservoirState(
            samples=jnp.zeros((num_reservoirs, k), sample_dtype),
            count=u64e.from_int(0, (num_reservoirs,)),
            nxt=nxt,
            log_w=log_w,
            key=keys,
        )
    count_dtype = jnp.dtype(count_dtype)

    def one(key_r):
        log_w0 = jnp.zeros((), jnp.float32)
        nxt0 = jnp.asarray(k, count_dtype)
        # initial W/next draw, keyed on index 0 (construction-time advance,
        # Sampler.scala:207)
        _, log_w, nxt = _advance(log_w0, nxt0, key_r, jnp.asarray(0, count_dtype), k)
        return log_w, nxt

    log_w, nxt = jax.vmap(one)(keys)
    return ReservoirState(
        samples=jnp.zeros((num_reservoirs, k), sample_dtype),
        count=jnp.zeros((num_reservoirs,), count_dtype),
        nxt=nxt,
        log_w=log_w,
        key=keys,
    )


def _accept_loop(
    samples: jax.Array,
    count: jax.Array,
    nxt: jax.Array,
    log_w: jax.Array,
    key: jax.Array,
    batch: jax.Array,
    end: jax.Array,
    k: int,
    map_fn: Optional[Callable],
):
    """Process every acceptance landing in ``(count, end]`` for one reservoir.

    The vmapped ``while_loop`` runs until the slowest lane is done; lanes with
    no acceptance in the tile cost one compare (the hot-path property,
    ``Sampler.scala:257``).

    Wide (emulated-uint64) counters take the same loop with pair
    arithmetic: 64-bit compares/adds on uint32 planes, and tile-local
    positions via a low-word difference (always < B, so int32-exact).
    """
    wide = count.ndim == 1  # per-lane: narrow counters are scalars

    def cond(carry):
        _, nxt_c, _ = carry
        return u64e.le(nxt_c, end) if wide else nxt_c <= end

    def body(carry):
        samples_c, nxt_c, log_w_c = carry
        if wide:
            pos = u64e.diff_small(nxt_c, count) - 1  # local index in [0, B)
            elem = batch[pos]
            k1, k2 = key_words(key)
            slot, log_w_n, nxt_n = _advance_pair(
                log_w_c, nxt_c, k1, k2, u64e.hi(nxt_c), u64e.lo(nxt_c), k
            )
        else:
            pos = (nxt_c - count - 1).astype(jnp.int32)  # local index in [0, B)
            elem = batch[pos]  # OOB-clamped gather is discarded for done lanes
            slot, log_w_n, nxt_n = _advance(log_w_c, nxt_c, key, nxt_c, k)
        value = map_fn(elem) if map_fn is not None else elem
        samples_n = samples_c.at[slot].set(jnp.asarray(value, samples_c.dtype))
        return samples_n, nxt_n, log_w_n

    samples, nxt, log_w = jax.lax.while_loop(cond, body, (samples, nxt, log_w))
    return samples, nxt, log_w


def _update_one(
    state_samples,
    state_count,
    state_nxt,
    state_log_w,
    state_key,
    batch,
    valid,
    k: int,
    map_fn: Optional[Callable],
    fill: bool,
):
    """Single-reservoir tile update (vmapped over R by :func:`update`)."""
    wide = state_count.ndim == 1  # per-lane: [2] planes vs scalar
    bsz = batch.shape[0]
    if wide:
        end = u64e.add_u32(state_count, valid.astype(jnp.uint32))
    else:
        count_dtype = state_count.dtype
        end = state_count + valid.astype(count_dtype)

    samples = state_samples
    if fill:
        # fill phase (Sampler.scala:253-255): element with absolute index
        # idx <= k goes to slot idx-1, in arrival order.  map applies on
        # accept; fill elements are all accepted.
        in_tile = jnp.arange(bsz) < valid
        if wide:
            # fills only exist while count < k (small), so the low word
            # alone decides — guarded on hi == 0 and lo < k, which also
            # rules out low-word wraparound in the local index sum
            lo_idx = u64e.lo(state_count) + jnp.arange(
                1, bsz + 1, dtype=jnp.uint32
            )
            fill_mask = (
                (u64e.hi(state_count) == 0)
                & (u64e.lo(state_count) < k)
                & (lo_idx <= k)
                & in_tile
            )
            dest = jnp.where(fill_mask, (lo_idx - 1).astype(jnp.int32), k)
        else:
            idx = state_count + jnp.arange(1, bsz + 1, dtype=count_dtype)
            fill_mask = (idx <= k) & in_tile
            dest = jnp.where(fill_mask, (idx - 1).astype(jnp.int32), k)  # k -> dropped
        values = map_fn(batch) if map_fn is not None else batch
        samples = samples.at[dest].set(
            jnp.asarray(values, samples.dtype), mode="drop"
        )

    samples, nxt, log_w = _accept_loop(
        samples,
        state_count,
        state_nxt,
        state_log_w,
        state_key,
        batch,
        end,
        k,
        map_fn,
    )
    return samples, end, nxt, log_w


def _update(
    state: ReservoirState,
    batch: jax.Array,
    valid: Optional[jax.Array],
    map_fn: Optional[Callable],
    fill: bool,
) -> ReservoirState:
    k = state.k
    if valid is None and not fill:
        # Full steady tiles: broadcast a scalar down the vmap instead of
        # materializing a [R] constant — keeps sharding propagation trivial.
        valid_arg = jnp.asarray(batch.shape[1], jnp.int32)
        in_axes = (0, 0, 0, 0, 0, 0, None)
    elif valid is None:
        # Fill-capable full tiles get a per-lane valid array: the scalar
        # variant makes XLA compile the masked fill scatter ~20x slower on
        # TPU (measured 226ms vs 12.6ms on a [1024,1024] tile, 2026-07-29).
        # Created inside the trace, so mesh sharding still propagates.
        valid_arg = jnp.full((batch.shape[0],), batch.shape[1], jnp.int32)
        in_axes = (0, 0, 0, 0, 0, 0, 0)
    else:
        valid_arg = valid
        in_axes = (0, 0, 0, 0, 0, 0, 0)
    samples, count, nxt, log_w = jax.vmap(
        functools.partial(_update_one, k=k, map_fn=map_fn, fill=fill),
        in_axes=in_axes,
    )(state.samples, state.count, state.nxt, state.log_w, state.key, batch, valid_arg)
    return ReservoirState(samples, count, nxt, log_w, state.key)


def update(
    state: ReservoirState,
    batch: jax.Array,
    valid: Optional[jax.Array] = None,
    map_fn: Optional[Callable] = None,
) -> ReservoirState:
    """Consume one ``[R, B]`` tile: reservoir r takes ``batch[r, :valid[r]]``.

    Pure function — jit/vmap/shard_map freely.  ``valid`` (default: full
    tiles) supports ragged feeds; padding elements are never sampled.
    ``map_fn`` must be traceable; it is applied to accepted elements (tile-
    vectorized during fill).
    """
    return _update(state, batch, valid, map_fn, fill=True)


def update_steady(
    state: ReservoirState,
    batch: jax.Array,
    valid: Optional[jax.Array] = None,
    map_fn: Optional[Callable] = None,
) -> ReservoirState:
    """:func:`update` minus the fill-phase scatter — the steady-state fast
    path once every reservoir holds k elements (callers check ``count >= k``;
    the engine does this automatically).  Skipping the masked fill scatter
    saves a [B]-wide scatter per reservoir per tile."""
    return _update(state, batch, valid, map_fn, fill=False)


def _update_gated_one(
    samples, count, nxt, log_w, key, row, nvalid, advance, k: int,
    map_fn: Optional[Callable],
):
    """Single-reservoir gated apply (vmapped over R by :func:`update_gated`).

    ``row[:nvalid]`` holds exactly the CANDIDATES of this reservoir's next
    ``advance`` logical elements, in stream order: first the fill-phase
    prefix (absolute indices ``count+1 .. min(k, count+advance)``), then
    every Algorithm-L acceptance in ``(count, count+advance]``.  Skipped
    elements were never shipped — the host gate proved (by running THIS
    recursion) that no acceptance lands on them.
    """
    bg = row.shape[0]
    # fill prefix: the first f shipped elements land in slots
    # count..count+f-1, exactly the ungated fill scatter's destinations
    f = jnp.clip(jnp.asarray(k, count.dtype) - count, 0, advance).astype(
        jnp.int32
    )
    lane = jnp.arange(bg, dtype=jnp.int32)
    dest = jnp.where(lane < f, count.astype(jnp.int32) + lane, k)
    values = map_fn(row) if map_fn is not None else row
    samples = samples.at[dest].set(
        jnp.asarray(values, samples.dtype), mode="drop"
    )

    def cond(carry):
        return carry[3] < nvalid

    def body(carry):
        samples_c, nxt_c, log_w_c, j = carry
        elem = row[j]
        # the j-th candidate IS the acceptance at absolute index nxt —
        # identical draws (same Threefry blocks) to the ungated loop
        slot, log_w_n, nxt_n = _advance(log_w_c, nxt_c, key, nxt_c, k)
        value = map_fn(elem) if map_fn is not None else elem
        samples_n = samples_c.at[slot].set(jnp.asarray(value, samples_c.dtype))
        return samples_n, nxt_n, log_w_n, j + 1

    samples, nxt, log_w, _ = jax.lax.while_loop(
        cond, body, (samples, nxt, log_w, f)
    )
    return samples, count + advance.astype(count.dtype), nxt, log_w


def update_gated(
    state: ReservoirState,
    batch: jax.Array,
    nvalid: jax.Array,
    advance: jax.Array,
    map_fn: Optional[Callable] = None,
) -> ReservoirState:
    """Consume one PRE-GATED ``[R, Bg]`` candidate tile (ISSUE 8).

    The ingest-side skip-ahead gate (:mod:`reservoir_tpu.stream.gate`) runs
    this module's own skip recursion host-side and ships only the elements
    that can win: reservoir ``r`` advances by ``advance[r]`` logical
    elements of which only the ``nvalid[r]`` candidates in
    ``batch[r, :nvalid[r]]`` were shipped (fill-phase prefix + every
    acceptance, in order).  Bit-identical to :func:`update` over the full
    tiles by construction — the acceptance draws are keyed on the same
    absolute indices, skipped elements consume no draws either way — and
    pinned by ``tests/test_gate.py``.  Narrow (non-WIDE) counters only.
    """
    if state.wide:
        raise ValueError("update_gated requires narrow (non-WIDE) counters")
    k = state.k
    samples, count, nxt, log_w = jax.vmap(
        functools.partial(_update_gated_one, k=k, map_fn=map_fn)
    )(
        state.samples, state.count, state.nxt, state.log_w, state.key,
        batch, nvalid, advance,
    )
    return ReservoirState(samples, count, nxt, log_w, state.key)


def _wide_size(count: jax.Array, k: int) -> jax.Array:
    """``min(count, k)`` as int32 for WIDE ``[..., 2]`` uint32-plane counts
    (k always fits int32) — the one clamp shared by every wide consumer."""
    lo_w = u64e.lo(count)
    return jnp.where(
        (u64e.hi(count) > 0) | (lo_w >= k), jnp.int32(k),
        lo_w.astype(jnp.int32),
    )


def merge_samples(
    samples_a: jax.Array,
    count_a: jax.Array,
    samples_b: jax.Array,
    count_b: jax.Array,
    key: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Exact result-level merge of two reservoir sets over disjoint streams.

    Reservoir r of the output is a uniform ``min(k, nA+nB)``-subset of the
    union of the two underlying streams: draw ``j ~ Hypergeometric(nA+nB,
    nA, k)`` by a k-step without-replacement scan, then take uniform random
    j / (k-j) subsets of the two reservoirs (valid because each input is
    itself a uniform subset of its stream).  This is the distributed
    one-logical-stream mode (SURVEY §5 "long-context" row): shards sample
    independently, merges ride collectives; pairs compose into tree folds.

    Args are ``(samples [R, k], count [R])`` pairs as produced by sampling —
    entries past ``min(count, k)`` are ignored.  Returns the merged pair;
    merged size is ``min(count_a + count_b, k)``.  The merge is *terminal* —
    it yields a sample, not a resumable Algorithm-L state (``W``/``nxt`` of
    a merged history are not reconstructible); keep per-shard states live to
    continue streaming.

    Pick probabilities use EXACT integer arithmetic (:func:`_randint_exact`):
    draw ``r`` uniform in ``[0, rem_a + rem_b)`` and take from A iff
    ``r < rem_a`` — exact at any magnitude the count dtype holds (the former
    f32 compare was O(2^-24)-biased past 2^24 elements per shard pair).

    Count dtypes and exactness domains:

    - int32 counts: internal arithmetic is widened to uint32, so the merge
      is exact for any *combined* total < 2^32 (two int32 inputs can never
      exceed it); the returned count is uint32 so the total cannot wrap.
      Tree folds of uint32 counts stay exact while each pair's combined
      total is < 2^32 — beyond that, use ``count_dtype="wide"``.
    - int64 counts (x64 on): exact at any magnitude, returned as int64.
    - WIDE counts (``[R, 2]`` uint32 planes, x64 off): the hypergeometric
      scan runs on emulated-uint64 planes (:mod:`..ops.u64e`, 64-bit
      rejection sampling via :func:`_randint_exact_u64e`) — exact at any
      magnitude, returned as wide planes.  This is the distributed-merge
      endgame for >2^31-per-reservoir streams (``Sampler.scala:203``'s
      ``Long`` contract, without global x64).
    """
    k = samples_a.shape[1]
    wide = count_a.ndim == 2 or count_b.ndim == 2
    if wide and not (count_a.ndim == 2 and count_b.ndim == 2):
        raise ValueError(
            "merge_samples: both counts must be WIDE [R, 2] planes or both "
            "narrow [R] — mixed-width merges are ambiguous; promote the "
            "narrow side with u64e.make(count, 0) first"
        )

    def _subset_gather(s_a, s_b, sz_a, sz_b, j_a, m, key_r):
        # uniform j_a-subset of A and (m - j_a)-subset of B via masked
        # argsort; draw indices k and k+1 are disjoint from the scan's t < k
        perm_a = _masked_perm(jr.fold_in(key_r, k), k, sz_a)
        perm_b = _masked_perm(jr.fold_in(key_r, k + 1), k, sz_b)
        pos = jnp.arange(k)
        from_a = pos < j_a
        idx = jnp.where(from_a, perm_a[pos], perm_b[jnp.maximum(pos - j_a, 0)])
        merged = jnp.where(from_a, s_a[idx], s_b[idx])
        return jnp.where(pos < m, merged, jnp.zeros((), s_a.dtype))

    def one(s_a, c_a, s_b, c_b, key_r):
        sz_a = jnp.minimum(c_a, k)
        sz_b = jnp.minimum(c_b, k)
        if jnp.dtype(c_a.dtype).itemsize == 8:
            # x64 path: int64 sums are exact at any reachable magnitude
            c_a_w, c_b_w = c_a, c_b
        else:
            # widen int32/uint32 internally: the sum of two int32 counts
            # can pass 2^31 (ADVICE r3 #1) but never 2^32
            c_a_w = c_a.astype(jnp.uint32)
            c_b_w = c_b.astype(jnp.uint32)
        total = c_a_w + c_b_w
        m = jnp.minimum(total, jnp.asarray(k, total.dtype)).astype(jnp.int32)
        kw1, kw2 = key_words(key_r)

        def step(carry, t):
            rem_a, rem_b, j_a = carry
            from .threefry import fold_in_words

            f1, f2 = fold_in_words(kw1, kw2, t)
            denom = jnp.maximum(rem_a + rem_b, jnp.asarray(1, total.dtype))
            r = _randint_exact(f1, f2, denom)
            # r uniform in [0, rem_a + rem_b) makes the edge guards of the
            # f32 version redundant: rem_a == 0 -> never picks A,
            # rem_b == 0 -> r < rem_a always
            pick_a = r < rem_a
            active = t < m
            take_a = active & pick_a
            take_b = active & ~pick_a
            return (
                rem_a - take_a.astype(rem_a.dtype),
                rem_b - take_b.astype(rem_b.dtype),
                j_a + take_a.astype(jnp.int32),
            ), None

        (rem_a, rem_b, j_a), _ = jax.lax.scan(
            step, (c_a_w, c_b_w, jnp.asarray(0, jnp.int32)), jnp.arange(k)
        )
        merged = _subset_gather(s_a, s_b, sz_a, sz_b, j_a, m, key_r)
        return merged, total

    def one_wide(s_a, c_a, s_b, c_b, key_r):
        # c_* are [2] uint32 planes per reservoir (vmapped over R)
        sz_a = _wide_size(c_a, k)
        sz_b = _wide_size(c_b, k)
        total = u64e.add64(c_a, c_b)
        m = _wide_size(total, k)
        kw1, kw2 = key_words(key_r)

        def step(carry, t):
            rem_a, rem_b, j_a = carry
            from .threefry import fold_in_words

            f1, f2 = fold_in_words(kw1, kw2, t)
            denom = u64e.add64(rem_a, rem_b)
            denom = jnp.where(u64e.is_zero(denom), u64e.from_int(1), denom)
            r = _randint_exact_u64e(f1, f2, denom)
            pick_a = u64e.lt(r, rem_a)
            active = t < m
            take_a = active & pick_a
            take_b = active & ~pick_a
            return (
                u64e.sub_u32(rem_a, take_a.astype(jnp.uint32)),
                u64e.sub_u32(rem_b, take_b.astype(jnp.uint32)),
                j_a + take_a.astype(jnp.int32),
            ), None

        (rem_a, rem_b, j_a), _ = jax.lax.scan(
            step, (c_a, c_b, jnp.asarray(0, jnp.int32)), jnp.arange(k)
        )
        merged = _subset_gather(s_a, s_b, sz_a, sz_b, j_a, m, key_r)
        return merged, total

    samples, count = jax.vmap(one_wide if wide else one)(
        samples_a, count_a, samples_b, count_b,
        jr.split(key, samples_a.shape[0]),
    )
    return samples, count


def merge(
    state_a: ReservoirState, state_b: ReservoirState, key: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """State-level convenience wrapper over :func:`merge_samples`; returns
    ``(samples [R, k], size [R], count [R])`` (``size`` is int32 for wide
    states; ``count`` keeps the states' width)."""
    samples, count = merge_samples(
        state_a.samples, state_a.count, state_b.samples, state_b.count, key
    )
    k = state_a.k
    if count.ndim == 2:
        size = _wide_size(count, k)
    else:
        size = jnp.minimum(count, k).astype(
            jnp.int32 if count.dtype == jnp.uint32 else count.dtype
        )
    return samples, size, count


def _randint_exact(f1: jax.Array, f2: jax.Array, denom: jax.Array) -> jax.Array:
    """EXACT uniform integer in ``[0, denom)`` for folded key ``(f1, f2)``.

    Rejection over fresh 32-bit draws (64-bit when ``denom`` is an int64 —
    which implies x64 is on): accept a draw below the largest multiple of
    ``denom`` in the word space, then reduce mod ``denom``.  Expected
    attempts < 2 (worst case ``denom`` near the half-space); each attempt
    ``a`` hashes block ``(1, a)`` of the folded key — disjoint from the
    ``(0, j)`` blocks every other consumer draws (:func:`..threefry.bits_words`).

    This replaces the former f32 ``u * denom < rem`` compare whose count
    arithmetic was O(2^-24)-biased past 2^24 elements (VERDICT r2 item 7):
    integer compares are exact at any magnitude the count dtype holds.
    ``denom`` must be >= 1 (callers mask inactive lanes).
    """
    from .threefry import threefry2x32

    wide = jnp.dtype(denom.dtype).itemsize == 8
    one_blk = jnp.ones_like(jnp.asarray(f1, jnp.uint32))
    if wide:
        ud = denom.astype(jnp.uint64)
        space_mod = ((jnp.uint64(0xFFFFFFFFFFFFFFFF) % ud) + 1) % ud
    else:
        ud = denom.astype(jnp.uint32)
        space_mod = ((jnp.uint32(0xFFFFFFFF) % ud) + 1) % ud
    # accept bits < 2^w - (2^w mod denom); space_mod == 0 (denom a power of
    # two dividing the space) accepts everything
    thresh = jnp.zeros_like(space_mod) - space_mod

    def draw(a):
        b0, b1 = threefry2x32(f1, f2, one_blk, one_blk * jnp.uint32(0) + a)
        if wide:
            return (b0.astype(jnp.uint64) << 32) | b1.astype(jnp.uint64)
        return b0 ^ b1

    def cond(carry):
        _, bits = carry
        return ~((space_mod == 0) | (bits < thresh))

    def body(carry):
        a, _ = carry
        return a + jnp.uint32(1), draw(a + jnp.uint32(1))

    _, bits = jax.lax.while_loop(
        cond, body, (jnp.uint32(0), draw(jnp.uint32(0)))
    )
    return (bits % ud).astype(denom.dtype)


def _randint_exact_u64e(
    f1: jax.Array, f2: jax.Array, denom: jax.Array
) -> jax.Array:
    """:func:`_randint_exact` on emulated-uint64 planes (x64 off).

    ``denom`` is ``[..., 2]`` uint32 planes, >= 1.  Same rejection scheme:
    accept a fresh 64-bit draw below ``2^64 - (2^64 mod denom)``, reduce
    mod ``denom`` — both computed exactly with :func:`..ops.u64e.mod64`
    restoring division (``2^64 mod d == (2^64 - d) mod d``, and ``2^64 - d``
    is the wrapping negation of ``d``).  Draw ``a`` hashes block ``(1, a)``
    of the folded key, bit-identical block layout to the narrow paths.
    """
    from .threefry import threefry2x32

    zero = jnp.zeros_like(denom)
    space_mod = u64e.mod64(u64e.sub64(zero, denom), denom)
    accept_all = u64e.is_zero(space_mod)
    thresh = u64e.sub64(zero, space_mod)
    one_blk = jnp.ones_like(jnp.asarray(f1, jnp.uint32))

    def draw(a):
        b0, b1 = threefry2x32(f1, f2, one_blk, one_blk * jnp.uint32(0) + a)
        return u64e.make(b1, b0)

    def cond(carry):
        _, bits = carry
        return ~(accept_all | u64e.lt(bits, thresh))

    def body(carry):
        a, _ = carry
        return a + jnp.uint32(1), draw(a + jnp.uint32(1))

    _, bits = jax.lax.while_loop(
        cond, body, (jnp.uint32(0), draw(jnp.uint32(0)))
    )
    return u64e.mod64(bits, denom)


def _masked_perm(key: jax.Array, k: int, size) -> jax.Array:
    """A random permutation of ``[0, size)`` padded into k slots: draw k
    uniforms, push invalid slots to +inf, argsort."""
    u = jr.uniform(key, (k,))
    u = jnp.where(jnp.arange(k) < size, u, jnp.inf)
    return jnp.argsort(u).astype(jnp.int32)


def result(state: ReservoirState) -> Tuple[jax.Array, jax.Array]:
    """Device-side result: ``(samples [R, k], size [R])`` where
    ``size = min(count, k)`` (truncation contract, ``Sampler.scala:318-331``).
    Host wrappers slice ``samples[r, :size[r]]``; entries beyond ``size`` are
    zeros, never sampled data.  ``size`` is int32 for wide states (k is
    always < 2^31)."""
    if state.wide:
        size = _wide_size(state.count, state.k)
    else:
        size = jnp.minimum(state.count, state.k).astype(state.count.dtype)
    mask = jnp.arange(state.k)[None, :] < size[:, None]
    return jnp.where(mask, state.samples, jnp.zeros_like(state.samples)), size
