"""Pallas TPU kernel for weighted A-ExpJ tile updates (M4b).

Same motivation as the Algorithm-L kernel (:mod:`.algorithm_l_pallas`): the
XLA vmap path carries ``samples [R, k]`` + ``lkeys [R, k]`` through a batched
``while_loop``, paying a full per-lane carry select (~5 × R × k × 4 bytes of
HBM traffic) per acceptance round.  Here the reservoir block lives in VMEM
for the whole tile and acceptances mutate it in place.

Grid-pipelined batch streaming (the r7 roofline restructure, mirroring the
Algorithm-L kernel's 2-D grid): the grid is ``(row-block, batch-chunk)``.
The ``[block_r, k]`` samples+lkeys blocks and the scalar carries stay
VMEM-resident across the whole batch axis, while the element and weight
tiles stream HBM→VMEM one ``[block_r, chunk_b]`` chunk at a time — Mosaic
double-buffers that input stream against the previous chunk's acceptance
loop.  Bit-equivalence with the XLA path across every chunk decomposition
is by construction, not by luck: draws are counter-keyed Threefry channels
at *absolute* stream indices, and the weight prefix sum uses the shared
blocked association of :mod:`.prefix` — each chunk continues the scan from
a carried scalar, reproducing the full-tile partial sums bit-for-bit as
long as ``chunk_b`` is a multiple of ``prefix.CUMSUM_BLOCK``
(:func:`~reservoir_tpu.ops.blocking.resolve_chunk` falls back to the
single-chunk grid otherwise).  The acceptance ``while_loop`` carries
``(xw, base)`` across chunks in the tile-global frame; the end-of-tile
``xw`` rebase happens only in the last chunk.

Unlike the Algorithm-L kernel this one is **fill-capable**: weighted fill
cannot be proven over from a host-side element count (zero-weight items
advance ``count`` without taking a slot — the zero-weight contract of
:mod:`.weighted`), so the engine can never dispatch a steady-only weighted
kernel safely.  The fill scatter is a k-step in-VMEM loop instead, run per
chunk only while some reservoir in the row-block still has empty slots.

Bit-equivalence with :func:`reservoir_tpu.ops.weighted.update` on full tiles
is pinned in interpret mode by ``tests/test_pallas_weighted.py`` (including
chunk boundaries splitting acceptance chains and zero-weight runs) and on
hardware by ``tests/test_pallas_device.py``.

Scope (engine dispatch via :func:`supports`): full tiles (no ``valid``),
identity ``map_fn``, int32 counters, int32/float32/uint32 samples, float32
weights.  Any R: a partial last row-block pads with zero-weight inert
lanes and is sliced off after the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .prefix import CUMSUM_BLOCK, lane_cumsum, lane_cumsum_carry
from .rng import key_words, uniform_from_bits
from .threefry import counter_bits
from .weighted import WeightedState, _NEG_INF, _draw_xw

__all__ = ["supports", "update_pallas", "pick_block_r"]

_F32_MIN = float(jnp.finfo(jnp.float32).min)


def pick_block_r(num_reservoirs: int, k: int, tile_b: int) -> int:
    """VMEM-aware row-block from the shared per-kernel byte-budget table
    (:data:`~reservoir_tpu.ops.blocking.KERNEL_VMEM`)."""
    from .blocking import kernel_block_r

    return kernel_block_r("weighted", num_reservoirs, k, tile_b)


def supports(
    state: WeightedState,
    valid,
    map_fn,
    block_r=None,
    batch: "jax.Array | None" = None,
) -> bool:
    """True iff this kernel can take the tile (else: XLA path).  Any R —
    a partial last row-block pads with zero-weight inert lanes."""
    return (
        valid is None
        and map_fn is None
        and state.count.dtype == jnp.int32
        and state.samples.dtype in (jnp.int32, jnp.float32, jnp.uint32)
        and (batch is None or batch.dtype == state.samples.dtype)
    )


def _row_gather_bits(onehot, value_bits):
    """Exact one-hot row gather: sum of int32 bit patterns (cf. the
    Algorithm-L kernel's gather — a float sum would drop -0.0 sign bits)."""
    return jnp.sum(jnp.where(onehot, value_bits, 0), axis=1, keepdims=True)


def _kernel(
    samples_ref,
    lkeys_ref,
    count_ref,
    xw_ref,
    key_ref,
    elems_ref,
    weights_ref,
    out_samples_ref,
    out_lkeys_ref,
    out_xw_ref,
    base_ref,
    cwsum_ref,
    *,
    k: int,
    chunk_b: int,
    n_chunks: int,
):
    """One grid cell = one ``[block_r]`` row-block × one ``[chunk_b]``
    batch chunk.

    Mirrors ``weighted._update_one`` (fill=True, full tile) exactly, with
    per-reservoir scalars as ``[block_r, 1]`` columns and the membership
    scatter/gathers as one-hot masked reductions.  The state blocks and
    the three scalar carries are VMEM-resident across the chunk axis
    (their index maps ignore the chunk dimension); chunk 0 seeds them
    behind a ``pl.when``:

    - ``out_xw_ref``: the un-rebased jump accumulator — the XLA
      ``while_loop``'s ``xw`` carry.  The tile-end rebase
      ``xw -= total_w - base`` runs only in the last chunk.
    - ``base_ref``: the prefix-weight base of the last acceptance, in the
      TILE-global frame (chunk 0 seeds 0.0, matching the XLA ``base0``).
    - ``cwsum_ref``: the blocked prefix-sum fold carry
      (:func:`~reservoir_tpu.ops.prefix.lane_cumsum_carry`), so each
      chunk's ``cw`` values are the tile-global partial sums bit-for-bit.
    """
    count = count_ref[:, :]  # [r, 1] int32 (pre-tile count)
    j = pl.program_id(1)
    base_off = j * jnp.int32(chunk_b)  # this chunk's offset in the tile
    k1 = key_ref[:, 0:1]
    k2 = key_ref[:, 1:2]
    block_r = count.shape[0]

    lane_b = jax.lax.broadcasted_iota(jnp.int32, (block_r, chunk_b), 1)
    lane_k = jax.lax.broadcasted_iota(jnp.int32, (block_r, k), 1)

    # chunk 0 seeds the VMEM-resident carries; later chunks mutate in place
    @pl.when(j == 0)
    def _seed_carry():
        out_samples_ref[:, :] = samples_ref[:, :]
        out_lkeys_ref[:, :] = lkeys_ref[:, :]
        out_xw_ref[:, :] = xw_ref[:, :]
        base_ref[:, :] = jnp.zeros((block_r, 1), jnp.float32)
        cwsum_ref[:, :] = jnp.zeros((block_r, 1), jnp.float32)

    wf = weights_ref[:, :]  # [r, chunk] f32
    positive = wf > 0.0
    # tile-global partial sums: the carried scalar continues the blocked
    # fold exactly where the previous chunk left it (the + 0.0 of chunk
    # 0's first block is the identity for nonnegative-weight sums)
    cw, cw_carry = lane_cumsum_carry(wf, cwsum_ref[:, :])
    cwsum_ref[:, :] = cw_carry
    total_w = cw[:, chunk_b - 1 : chunk_b]  # [r, 1] global through chunk j
    n_filled = jnp.sum(
        (out_lkeys_ref[:, :] > _NEG_INF).astype(jnp.int32),
        axis=1,
        keepdims=True,
    )
    need = jnp.maximum(k - n_filled, 0)  # [r, 1] slots still empty
    prank = lane_cumsum(positive.astype(jnp.int32))  # [r, chunk] 1-based
    idx_abs = count + base_off + lane_b + 1  # [r, chunk] absolute 1-based

    # ---- fill phase (positive items take the next free slots in order) ----
    w0_fill, _, _ = counter_bits(k1, k2, idx_abs, 3)
    u_fill = uniform_from_bits(w0_fill)
    lk_fill = jnp.where(
        positive,
        jnp.log(u_fill) / jnp.maximum(wf, jnp.float32(1e-45)),
        _NEG_INF,
    )
    lk_fill = jnp.maximum(lk_fill, jnp.float32(_F32_MIN))
    fill_mask = positive & (prank <= need)
    dest = jnp.where(fill_mask, n_filled + prank - 1, k)  # k -> dropped

    elem_bits_all = jax.lax.bitcast_convert_type(elems_ref[:, :], jnp.int32)
    lk_bits_all = jax.lax.bitcast_convert_type(lk_fill, jnp.int32)

    def fill_slot(s, _):
        col = dest == s  # [r, chunk]; at most one lane per row
        wrote = jnp.any(col, axis=1, keepdims=True)  # [r, 1]
        e_bits = _row_gather_bits(col, elem_bits_all)
        l_bits = _row_gather_bits(col, lk_bits_all)
        slot_mask = (lane_k == s) & wrote
        out_samples_ref[:, :] = jnp.where(
            slot_mask,
            jax.lax.bitcast_convert_type(
                e_bits, out_samples_ref.dtype
            ),
            out_samples_ref[:, :],
        )
        out_lkeys_ref[:, :] = jnp.where(
            slot_mask,
            jax.lax.bitcast_convert_type(l_bits, jnp.float32),
            out_lkeys_ref[:, :],
        )
        return 0

    # steady state (need == 0 in every lane) makes every fill_mask empty —
    # skip the k-iteration scatter outright; bit-equivalence is untouched
    # because the guarded writes would all be masked no-ops
    @pl.when(jnp.any(need > 0))
    def _run_fill():
        jax.lax.fori_loop(0, k, fill_slot, 0)

    # fill completing inside this chunk draws the first jump, keyed on
    # index k (the same constant-keyed draw the XLA path makes in the tile
    # where its fill completes)
    n_pos = prank[:, chunk_b - 1 : chunk_b]
    completes = (n_filled < k) & (n_filled + n_pos >= k)
    _, _, w2_init = counter_bits(
        k1, k2, jnp.full_like(count, k), 3
    )
    u3_init = uniform_from_bits(w2_init)
    min_lk = jnp.min(out_lkeys_ref[:, :], axis=1, keepdims=True)
    xw = jnp.where(completes, _draw_xw(u3_init, min_lk), out_xw_ref[:, :])

    # ---- acceptance scan (weighted._update_one's while_loop) --------------
    j0 = jnp.sum(
        (prank < need).astype(jnp.int32), axis=1, keepdims=True
    )  # searchsorted(prank, need, 'left'), chunk-local
    start = jnp.where(need > 0, jnp.minimum(j0 + 1, chunk_b), 0)
    cw_bits = jax.lax.bitcast_convert_type(cw, jnp.int32)
    base0_bits = _row_gather_bits(lane_b == (start - 1), cw_bits)
    # start == 0 (fill already complete): continue from the carried
    # tile-global base; chunk 0 carries the XLA base0 of 0.0
    base0 = jnp.where(
        start > 0,
        jax.lax.bitcast_convert_type(base0_bits, jnp.float32),
        base_ref[:, :],
    )

    def next_j(base, xw_c, cur):
        # first positive lane at or past cur reaching the jump target —
        # the same integer min as ops.weighted.next_j (NaN-free under the
        # shared prefix sum's ulp dips; see the comment there)
        x = base + xw_c  # [r, 1]
        mask = positive & (cw >= x) & (lane_b >= cur)
        return jnp.min(
            jnp.where(mask, lane_b, chunk_b), axis=1, keepdims=True
        )

    def cond(carry):
        xw_c, base, cur = carry
        return jnp.any(next_j(base, xw_c, cur) < chunk_b)

    def body(carry):
        xw_c, base, cur = carry
        j_l = next_j(base, xw_c, cur)  # [r, 1] chunk-local lane
        active = j_l < chunk_b
        onehot_j = lane_b == j_l  # empty when j_l == chunk_b
        w_c = jnp.sum(jnp.where(onehot_j, wf, 0.0), axis=1, keepdims=True)
        # next_j only returns positive-weight lanes, so active lanes use
        # the raw weight — bit-identical to the XLA path even for subnormal
        # weights; inactive lanes get 1.0 purely to avoid masked NaNs that
        # would trip jax_debug_nans
        w_safe = jnp.where(active, w_c, 1.0)
        e_bits = _row_gather_bits(onehot_j, elem_bits_all)
        idx = count + base_off + 1 + j_l
        _, w1_a, w2_a = counter_bits(k1, k2, idx, 3)
        u1 = uniform_from_bits(w1_a)
        u2 = uniform_from_bits(w2_a)
        lkeys_c = out_lkeys_ref[:, :]
        lt = jnp.min(lkeys_c, axis=1, keepdims=True)
        lt_safe = jnp.where(active, lt, 0.0)
        t = jnp.exp(w_safe * lt_safe)
        r2 = t + u1 * (1.0 - t)
        lkey_new = jnp.maximum(
            jnp.log(r2) / w_safe, jnp.float32(_F32_MIN)
        )
        # argmin with first-match tie-breaking (jnp.argmin semantics)
        is_min = lkeys_c == lt
        first_min = is_min & (lane_cumsum(is_min.astype(jnp.int32)) == 1)
        write = first_min & active
        out_samples_ref[:, :] = jnp.where(
            write,
            jax.lax.bitcast_convert_type(e_bits, out_samples_ref.dtype),
            out_samples_ref[:, :],
        )
        out_lkeys_ref[:, :] = jnp.where(write, lkey_new, out_lkeys_ref[:, :])
        min_after = jnp.min(out_lkeys_ref[:, :], axis=1, keepdims=True)
        xw_n = _draw_xw(u2, min_after)
        base_j_bits = _row_gather_bits(onehot_j, cw_bits)
        base_j = jax.lax.bitcast_convert_type(base_j_bits, jnp.float32)
        return (
            jnp.where(active, xw_n, xw_c),
            jnp.where(active, base_j, base),
            jnp.where(active, j_l + 1, cur),
        )

    xw, base, _cur = jax.lax.while_loop(cond, body, (xw, base0, start))
    out_xw_ref[:, :] = xw
    base_ref[:, :] = base

    # last chunk: carry the unconsumed jump across the tile boundary —
    # total_w here is the TILE-global weight sum, base the global prefix
    # at the last acceptance, both bit-identical to the XLA full-tile pass
    @pl.when(j == n_chunks - 1)
    def _rebase():
        out_xw_ref[:, :] = xw - (total_w - base)


def update_pallas(
    state: WeightedState,
    elems: jax.Array,
    weights: jax.Array,
    *,
    block_r=None,
    chunk_b: "int | None" = None,
    interpret: bool = False,
) -> WeightedState:
    """Full-tile weighted update, bit-identical to
    :func:`reservoir_tpu.ops.weighted.update` on full tiles.

    ``elems``/``weights`` are ``[R, B]``; requires :func:`supports`.
    ``interpret=True`` runs the Mosaic interpreter (CPU equivalence tests).
    Geometry knobs (see :mod:`.autotune` for the persistent per-device
    cache):

    - ``block_r``: reservoir rows per grid cell (``None`` = VMEM-aware
      auto-size, :func:`pick_block_r`); any R is accepted.
    - ``chunk_b``: batch-streaming chunk — the tile's batch axis is split
      into ``B // chunk_b`` grid cells whose HBM→VMEM loads Mosaic
      double-buffers against the previous chunk's acceptance loop.
      ``None``/0, a non-divisor of B, or a non-multiple of
      ``prefix.CUMSUM_BLOCK`` (the shared cumsum association's block) =
      whole tile in one cell.
    """
    R, k = state.samples.shape
    B = elems.shape[1]
    if elems.shape[0] != R or weights.shape != elems.shape:
        raise ValueError(
            f"elems {elems.shape} / weights {weights.shape} must be "
            f"[{R}, B] tiles"
        )
    if not supports(state, None, None, block_r, elems):
        raise ValueError(
            "update_pallas: unsupported config (need int32 counters, "
            f"int32/float32/uint32 samples, elems dtype == samples dtype); "
            "use ops.weighted.update"
        )
    from .blocking import resolve_chunk

    chunk_b = resolve_chunk(B, chunk_b, multiple_of=CUMSUM_BLOCK)
    if block_r is None:
        block_r = pick_block_r(R, k, chunk_b)
    R_orig = R
    if R % block_r != 0:
        from .blocking import pad_rows, shrink_block_to

        block_r = shrink_block_to(R, block_r)
        pad = (-R) % block_r
        if pad:
            # pad lanes replicate the last reservoir but see ZERO weights:
            # A-ExpJ never accepts weight-0 elements, so they are inert
            state = WeightedState(
                *pad_rows(pad, *state)
            )
            (elems,) = pad_rows(pad, elems)
            weights = jnp.concatenate(
                [jnp.asarray(weights, jnp.float32),
                 jnp.zeros((pad, B), jnp.float32)]
            )
            R += pad
    kd1, kd2 = key_words(state.key)  # [R] uint32 each
    key_data = jnp.stack([kd1, kd2], axis=1)  # [R, 2]

    # state blocks + carries: row-block i, chunk-invariant (VMEM-resident
    # across the inner grid axis, written back once per row-block)
    col = lambda i, j: (i, 0)  # noqa: E731
    col_spec = lambda w: pl.BlockSpec(  # noqa: E731
        (block_r, w), col, memory_space=pltpu.VMEM
    )
    # the streamed inputs: chunk j of row-block i — the only blocks whose
    # index varies along the inner grid axis, so Mosaic's pipeline
    # double-buffers exactly these HBM->VMEM streams
    stream_spec = pl.BlockSpec(
        (block_r, chunk_b), lambda i, j: (i, j), memory_space=pltpu.VMEM
    )

    out_samples, out_lkeys, out_xw, _base, _cwsum = pl.pallas_call(
        functools.partial(
            _kernel, k=k, chunk_b=chunk_b, n_chunks=B // chunk_b
        ),
        grid=(R // block_r, B // chunk_b),
        in_specs=[
            col_spec(k),
            col_spec(k),
            col_spec(1),
            col_spec(1),
            col_spec(2),
            stream_spec,
            stream_spec,
        ],
        out_specs=(
            col_spec(k), col_spec(k), col_spec(1), col_spec(1), col_spec(1),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((R, k), state.samples.dtype),
            jax.ShapeDtypeStruct((R, k), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            # cross-chunk carries (acceptance base, cumsum fold) — outputs
            # only so Mosaic keeps them VMEM-resident across the grid's
            # inner axis; discarded after the call
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ),
        interpret=interpret,
    )(
        state.samples,
        state.lkeys,
        state.count.reshape(R, 1),
        state.xw.reshape(R, 1),
        key_data,
        elems,
        jnp.asarray(weights, jnp.float32),
    )
    if R != R_orig:  # drop the inert pad lanes
        out_samples = out_samples[:R_orig]
        out_lkeys = out_lkeys[:R_orig]
        out_xw = out_xw[:R_orig]
        state = jax.tree.map(lambda x: x[:R_orig], state)
    return WeightedState(
        samples=out_samples,
        lkeys=out_lkeys,
        count=state.count + jnp.asarray(B, state.count.dtype),
        xw=out_xw.reshape(R_orig),
        key=state.key,
    )
