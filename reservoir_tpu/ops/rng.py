"""Counter-based RNG draws for the device kernels.

The reference threads one sequential ``scala.util.Random`` through the hot
path (``Sampler.scala:199, 228-236``), which is why its determinism tests must
force RNG state by reflection (``SamplerTest.scala:16-54``).  Here every
acceptance event draws from a key derived *by counter* — ``fold_in(key, idx)``
where ``idx`` is the absolute 1-based stream index of the accepted element.

That single design choice buys the framework's central invariant for free:
the draws consumed by an acceptance depend only on (reservoir key, absolute
index), never on how the stream was batched.  Feeding one element at a time,
tiles of 1024, or any ragged split produces bit-identical reservoirs — the
TPU-native analog of the reference's ``sample == sampleAll`` contract
(``SamplerTest.scala:117-142``), with no reflection needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.random as jr

from .threefry import counter_bits, counter_bits_pair

__all__ = [
    "accept_draws",
    "accept_draws_words",
    "accept_draws_pair",
    "key_words",
    "uniform_from_bits",
    "uniforms",
]

_INV_2_24 = float(2.0**-24)


def key_words(key: jax.Array):
    """Raw uint32 word pair of a typed jax key (what crosses into Pallas)."""
    data = jr.key_data(key)
    return data[..., 0], data[..., 1]


def uniform_from_bits(bits: jax.Array, offset: float = 1.0) -> jax.Array:
    """Map uint32 words onto the 24-bit-mantissa f32 uniform grid (exact in
    f32).  ``offset=1.0`` gives ``(0, 1]`` (log-safe: ``log(u)`` finite);
    ``offset=0.5`` gives the open interval ``(0, 1)``.  Single owner of the
    bits->uniform idiom for every device kernel.

    The cast routes through int32 (exact: the shifted value is < 2^24)
    because Mosaic has no uint32->f32 lowering."""
    return ((bits >> 8).astype(jnp.int32).astype(jnp.float32) + offset) * _INV_2_24


def uniforms(key: jax.Array, idx, shape=(), offset: float = 1.0) -> jax.Array:
    """``shape`` uniforms for the counter-derived key ``fold_in(key, idx)``.

    Backed by :mod:`reservoir_tpu.ops.threefry` (bit-identical to
    ``jr.bits(jr.fold_in(key, idx), shape, uint32)`` — pinned by
    ``tests/test_threefry.py``); only scalar-or-``(n,)`` shapes are needed by
    the kernels.
    """
    if len(shape) > 1:
        raise ValueError(f"uniforms supports scalar or (n,) shapes, got {shape}")
    k1, k2 = key_words(key)
    n = 1 if shape == () else int(shape[0])
    words = counter_bits(k1, k2, idx, n)
    stacked = words[0] if shape == () else jnp.stack(words)
    return uniform_from_bits(stacked, offset)


def accept_draws(key: jax.Array, idx: jax.Array, k: int):
    """Draws consumed by the acceptance at absolute stream index ``idx``.

    Returns ``(slot, u1, u2)``:

    - ``slot``: uniform in ``[0, k)`` — the reservoir slot to overwrite
      (``Sampler.scala:244``).  Modulo reduction of 32 random bits: *exact*
      for power-of-two ``k``, bias ``< k/2^32`` otherwise.
    - ``u1``, ``u2``: float32 uniforms in ``(0, 1]`` (24-bit mantissa grid,
      exact in f32) feeding the Algorithm-L ``W``/skip update
      (``Sampler.scala:228-236``).  The half-open-at-zero range keeps
      ``log(u)`` finite.

    Shared bit-for-bit between the XLA vmap kernel and the Pallas kernel via
    :func:`reservoir_tpu.ops.threefry.counter_bits`.
    """
    k1, k2 = key_words(key)
    return accept_draws_words(k1, k2, idx, k)


def accept_draws_words(k1: jax.Array, k2: jax.Array, idx: jax.Array, k: int):
    """:func:`accept_draws` on raw uint32 key words, elementwise over ``idx``
    lanes — the form shared with the Pallas kernel (typed keys cannot cross a
    ``pallas_call`` boundary).  64-bit ``idx`` keeps fresh draws past 2^32
    (see :func:`reservoir_tpu.ops.threefry.fold_in_words`)."""
    w0, w1, w2 = counter_bits(k1, k2, idx, 3)
    u1 = uniform_from_bits(w0)
    u2 = uniform_from_bits(w1)
    slot = (w2 % jnp.uint32(k)).astype(jnp.int32)
    return slot, u1, u2


def accept_draws_pair(
    k1: jax.Array, k2: jax.Array, idx_hi: jax.Array, idx_lo: jax.Array, k: int
):
    """:func:`accept_draws_words` for an absolute index carried as
    ``(hi, lo)`` uint32 words (emulated-uint64 counters,
    :mod:`reservoir_tpu.ops.u64e`) — bit-identical to the int64 path for
    the same logical index."""
    w0, w1, w2 = counter_bits_pair(k1, k2, idx_hi, idx_lo, 3)
    u1 = uniform_from_bits(w0)
    u2 = uniform_from_bits(w1)
    slot = (w2 % jnp.uint32(k)).astype(jnp.int32)
    return slot, u1, u2
