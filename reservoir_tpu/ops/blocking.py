"""Row-block sizing shared by the Pallas kernels.

Mosaic grid cells run sequentially on the TensorCore, so per-cell overhead
is amortized by wider reservoir row-blocks — but each cell's working set
(state block + batch block + elementwise temps) must fit VMEM.  Measured on
v5e (BENCH.md sweep, 2026-07-30): the distinct config gains 2.1x going from
block 8 to 128; the weighted config gains 1.2x from 64 to 128 and fails to
allocate at 256.  ``pick_block_r`` returns the largest power-of-2 divisor
of R that stays under both the measured cap (128) and a per-kernel VMEM
budget, from a caller-supplied bytes-per-row estimate.
"""

from __future__ import annotations

__all__ = ["pick_block_r", "pad_rows", "shrink_block_to"]


def shrink_block_to(num_reservoirs: int, block_r: int) -> int:
    """Largest power of two <= R when R is smaller than the block."""
    if num_reservoirs >= block_r:
        return block_r
    return 1 << max(0, num_reservoirs.bit_length() - 1)


def pad_rows(pad: int, *arrays):
    """Pad the leading (reservoir) axis of each array by replicating its
    last row ``pad`` times — the any-R grid trick: pad lanes carry a valid
    (copied) state, compute in lockstep with their block, and are sliced
    off after the kernel.  Callers make pad lanes *inert* where it matters
    (zero weights, ``nxt`` past the tile) so they also do no wasted work.
    """
    import jax.numpy as jnp

    return tuple(
        jnp.concatenate([a, jnp.repeat(a[-1:], pad, axis=0)]) for a in arrays
    )

_MAX_BLOCK_R = 128
# half of v5e's ~16 MiB VMEM, leaving the rest for Mosaic's own temporaries
# and double-buffering; block 256 at the weighted bench shape (~8.4 MB by
# its estimate) is the measured allocation failure this budget excludes
_VMEM_BUDGET_BYTES = 6 * 1024 * 1024


def pick_block_r(num_reservoirs: int, row_bytes: int, min_block: int) -> int:
    """Largest power-of-2 divisor of R with ``block * row_bytes`` under the
    VMEM budget, capped at ``_MAX_BLOCK_R``.  ``row_bytes`` is the kernel's
    estimate of per-reservoir-row VMEM traffic (state + batch + temps).

    Never returns below ``min_block`` (the kernel's declared minimum grid
    block, which ``supports()`` guarantees divides R): a huge-tile shape
    whose budget math would suggest a sub-minimum block gets exactly the
    fixed block the kernel ran with before auto-sizing existed — the VMEM
    budget only ever *widens* blocks, it cannot un-meet the gate.
    """
    b = min_block
    while (
        b < _MAX_BLOCK_R
        and num_reservoirs % (b * 2) == 0
        and (b * 2) * row_bytes <= _VMEM_BUDGET_BYTES
    ):
        b *= 2
    return b
