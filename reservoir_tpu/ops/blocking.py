"""Chunked-grid geometry scaffolding shared by all three Pallas kernels.

Every kernel in this package runs the same 2-D grid architecture: a
``[block_r]`` row-block of reservoirs stays VMEM-resident while the batch
(and weights, for A-ExpJ) streams through in ``chunk_b``-wide chunks that
Mosaic's grid pipeline double-buffers.  This module owns the two geometry
decisions the kernels share:

- :func:`pick_block_r` / :func:`kernel_block_r` — VMEM-aware row-block
  sizing from a per-kernel bytes-per-row model (:data:`KERNEL_VMEM`);
- :func:`resolve_chunk` — batch-chunk validation (invalid chunks silently
  fall back to the whole-tile single-chunk grid, never to an error or a
  different result).

Mosaic grid cells run sequentially on the TensorCore, so per-cell overhead
is amortized by wider reservoir row-blocks — but each cell's working set
(state block + batch chunk + elementwise temps) must fit VMEM.  The
measured row-block sweep behind the cap and the per-kernel minimums lives
in BENCH.md ("Row-block sizing").
"""

from __future__ import annotations

__all__ = [
    "KERNEL_VMEM",
    "kernel_block_r",
    "pick_block_r",
    "pad_rows",
    "resolve_chunk",
    "shrink_block_to",
]


def shrink_block_to(num_reservoirs: int, block_r: int) -> int:
    """Largest power of two <= R when R is smaller than the block."""
    if num_reservoirs >= block_r:
        return block_r
    return 1 << max(0, num_reservoirs.bit_length() - 1)


def pad_rows(pad: int, *arrays):
    """Pad the leading (reservoir) axis of each array by replicating its
    last row ``pad`` times — the any-R grid trick: pad lanes carry a valid
    (copied) state, compute in lockstep with their block, and are sliced
    off after the kernel.  Callers make pad lanes *inert* where it matters
    (zero weights, ``nxt`` past the tile) so they also do no wasted work.
    """
    import jax.numpy as jnp

    return tuple(
        jnp.concatenate([a, jnp.repeat(a[-1:], pad, axis=0)]) for a in arrays
    )


def resolve_chunk(
    tile_b: int, chunk_b: "int | None", multiple_of: int = 1
) -> int:
    """The batch-streaming chunk the grid actually runs: ``chunk_b`` when it
    is a proper divisor of the tile width (and a multiple of
    ``multiple_of`` — the weighted kernel's cumsum-association constraint,
    :data:`~reservoir_tpu.ops.prefix.CUMSUM_BLOCK`), else the whole tile in
    one grid cell.  An invalid chunk must cost speed, never a crash or a
    different result."""
    if not chunk_b or chunk_b <= 0 or chunk_b >= tile_b:
        return tile_b
    if tile_b % chunk_b != 0 or chunk_b % multiple_of != 0:
        return tile_b
    return chunk_b


_MAX_BLOCK_R = 128
# half of v5e's ~16 MiB VMEM, leaving the rest for Mosaic's own temporaries
# and double-buffering; block 256 at the weighted bench shape (~8.4 MB by
# its estimate) is the measured allocation failure this budget excludes
_VMEM_BUDGET_BYTES = 6 * 1024 * 1024

#: Per-kernel VMEM models: ``(row_bytes(k, chunk_b), min_block)``.
#: ``row_bytes`` estimates the VMEM bytes one reservoir row keeps live in a
#: grid cell (k-wide state planes in + out, chunk-wide batch planes and
#: elementwise temps, 4 bytes each); ``min_block`` is the smallest row-block
#: the kernel's grid was ever measured/gated at — auto-sizing only ever
#: widens from it.
KERNEL_VMEM = {
    # algl: ~2 k-wide planes (samples in + out) + ~4 chunk-wide planes
    # (batch + gather temps)
    "algl": (lambda k, chunk_b: (2 * k + 4 * chunk_b) * 4, 64),
    # weighted: ~4 k-wide planes (samples + lkeys, in + out) + ~8
    # chunk-wide planes (elems, weights, cumsum, rank, RNG words, masks)
    "weighted": (lambda k, chunk_b: (4 * k + 8 * chunk_b) * 4, 64),
    # distinct: ~9 k-wide planes (4 state planes in + 5 out) + ~8
    # chunk-wide planes (2 value planes + scrambled hashes + masks)
    "distinct": (lambda k, chunk_b: (9 * k + 8 * chunk_b) * 4, 8),
}


def kernel_block_r(
    kernel: str, num_reservoirs: int, k: int, chunk_b: int
) -> int:
    """VMEM-aware row-block for ``kernel`` at this ``(k, chunk_b)`` cell
    shape — the one sizing rule all three kernels share."""
    row_bytes_fn, min_block = KERNEL_VMEM[kernel]
    return pick_block_r(num_reservoirs, row_bytes_fn(k, chunk_b), min_block)


def pick_block_r(num_reservoirs: int, row_bytes: int, min_block: int) -> int:
    """Largest power-of-2 divisor of R with ``block * row_bytes`` under the
    VMEM budget, capped at ``_MAX_BLOCK_R``.  ``row_bytes`` is the kernel's
    estimate of per-reservoir-row VMEM traffic (state + batch + temps).

    Never returns below ``min_block`` (the kernel's declared minimum grid
    block, which ``supports()`` guarantees divides R): a huge-tile shape
    whose budget math would suggest a sub-minimum block gets exactly the
    fixed block the kernel ran with before auto-sizing existed — the VMEM
    budget only ever *widens* blocks, it cannot un-meet the gate.
    """
    b = min_block
    while (
        b < _MAX_BLOCK_R
        and num_reservoirs % (b * 2) == 0
        and (b * 2) * row_bytes <= _VMEM_BUDGET_BYTES
    ):
        b *= 2
    return b
