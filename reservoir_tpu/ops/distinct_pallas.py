"""Pallas TPU kernel for distinct-mode (bottom-k) tile merges (M4c).

The XLA path (:mod:`.distinct`) pays an O((k+B) log(k+B)) multi-key
``lax.sort`` per tile regardless of how many elements could possibly enter
the reservoir.  But once the reservoir is warm, almost every element fails
the threshold compare — the same observation behind the reference's one-
compare hot loop (``Sampler.scala:403-408``) and the native host scan
(``_native/bottom_k.cc``).  This kernel keeps the sorted bottom-k resident
in VMEM and does per tile:

- scramble (same integer-exact :func:`~reservoir_tpu.ops.hashing.scramble64`
  — VPU-elementwise, no 64-bit lanes: (hi, lo) uint32 limb pairs);
- one lexicographic threshold compare per element (the hot path);
- an acceptance loop over the *distinct below-threshold values* only: each
  iteration selects the minimum candidate hash, dedups against the resident
  entries, inserts in sorted position by a k-wide shift, and masks every
  tile lane carrying the same (hash, value) — so within-tile duplicates
  cost one iteration total, not one each.

Grid-pipelined batch streaming (the r7 roofline restructure, mirroring the
Algorithm-L kernel's 2-D grid): the grid is ``(row-block, batch-chunk)``.
The sorted bottom-k block stays VMEM-resident across the whole batch axis
while the value planes stream HBM→VMEM one ``[block_r, chunk_b]`` chunk at
a time, double-buffered by Mosaic's grid pipeline against the previous
chunk's scramble + threshold compare.  State equality across every chunk
decomposition is by construction: the maintained bottom-k-of-distinct
summary is an order-insensitive pure function of the value set seen, so
feeding the tile chunk-by-chunk reaches exactly the sort-merge result —
the threshold compare and dedup loop operate per distinct below-threshold
value with no cross-chunk arithmetic to re-associate.

State equality with the XLA sort-merge path is exact: both maintain the
same canonical representation (entries sorted by (hash, value-bits)
ascending, (MAX, MAX)/0 padding, explicit size), and insertion position
counts (hash, value) lexicographically, so even 64-bit hash ties land
identically.  Sole caveat (shared with the native host scan): a value
whose scrambled hash is exactly (MAX, MAX) is never accepted by the
strict threshold compare, where the XLA path's pad-flag would keep it —
probability 2^-64, the documented bias class.  Pinned by
``tests/test_pallas_distinct.py`` in interpret mode (including chunk
boundaries splitting duplicate runs) and by the engine dispatch
equivalence tests.

Scope (engine dispatch via :func:`supports`): full tiles, identity
``map_fn``/default hash, int32 counters, narrow (4-byte) or wide (8-byte
bit-plane) keys.  Any R: a partial last row-block pads with replicated
inert lanes and is sliced off after the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import numpy as np

from .distinct import DistinctState
from .hashing import scramble64
from .prefix import lane_cumsum

__all__ = ["supports", "update_pallas", "pick_block_r"]


def pick_block_r(num_reservoirs: int, k: int, tile_b: int) -> int:
    """VMEM-aware row-block from the shared per-kernel byte-budget table
    (:data:`~reservoir_tpu.ops.blocking.KERNEL_VMEM`)."""
    from .blocking import kernel_block_r

    return kernel_block_r("distinct", num_reservoirs, k, tile_b)


def supports(
    state: DistinctState,
    valid,
    map_fn,
    block_r=None,
    batch=None,
) -> bool:
    """True iff this kernel can take the tile (else: XLA path).  Any R —
    a partial last row-block pads with replicated inert lanes."""
    return (
        valid is None
        and map_fn is None
        and state.count.dtype == jnp.int32
    )


def _sign_extend_hi(lo_bits):
    """uint32 hi plane of a sign-extended 4-byte value (the
    ``default_hash64`` embedding, shared with the XLA path)."""
    return (lo_bits.astype(jnp.int32) >> jnp.int32(31)).view(jnp.uint32)


def _lex_lt(ahi, alo, bhi, blo):
    """(ahi, alo) < (bhi, blo) as 64-bit lexicographic uint compare."""
    return (ahi < bhi) | ((ahi == bhi) & (alo < blo))


# Mosaic has no reductions over unsigned integers (NotImplementedError in
# lowering, observed on TPU 2026-07-30) — every uint32 reduction below goes
# through int32 bit patterns instead.

_SIGN = np.uint32(0x80000000)


def _umin_where(mask, x):
    """Masked per-row unsigned min of uint32 ``x`` (empty rows -> MAX):
    flip the sign bit so unsigned order becomes signed order, reduce in
    int32, flip back."""
    xs = jax.lax.bitcast_convert_type(x ^ _SIGN, jnp.int32)
    m = jnp.min(
        jnp.where(mask, xs, jnp.int32(0x7FFFFFFF)), axis=1, keepdims=True
    )
    return jax.lax.bitcast_convert_type(m, jnp.uint32) ^ _SIGN


def _usel(mask, x):
    """Gather the single masked lane of uint32 ``x`` per row (sum of int32
    bit patterns; exact because at most one lane is unmasked)."""
    xi = jax.lax.bitcast_convert_type(x, jnp.int32)
    s = jnp.sum(jnp.where(mask, xi, 0), axis=1, keepdims=True)
    return jax.lax.bitcast_convert_type(s, jnp.uint32)


def _kernel(
    values_ref,
    vhi_ref,  # value hi plane ([r, k]; in narrow mode a recomputed view)
    hhi_ref,
    hlo_ref,
    size_ref,
    salts_ref,
    bvlo_ref,
    bvhi_ref,
    out_values_ref,
    out_vhi_ref,
    out_hhi_ref,
    out_hlo_ref,
    out_size_ref,
    *,
    k: int,
):
    """One grid cell = one ``[block_r]`` row-block × one ``[chunk_b]``
    batch chunk.

    The resident bottom-k blocks (``out_*``, including ``out_size``) are
    VMEM-resident across the whole chunk axis — their index maps ignore
    the chunk dimension, so chunk ``j`` reads the carry chunk ``j-1`` left
    behind and only the last chunk's result is written back to HBM.
    Chunk 0 seeds the carry from the inputs behind a ``pl.when``.
    """
    block_r = size_ref.shape[0]
    j = pl.program_id(1)
    lane_k = jax.lax.broadcasted_iota(jnp.int32, (block_r, k), 1)

    # chunk 0 seeds the VMEM-resident carry; later chunks mutate in place.
    @pl.when(j == 0)
    def _seed_carry():
        out_values_ref[:, :] = values_ref[:, :]
        out_vhi_ref[:, :] = vhi_ref[:, :]
        out_hhi_ref[:, :] = hhi_ref[:, :]
        out_hlo_ref[:, :] = hlo_ref[:, :]
        out_size_ref[:, :] = size_ref[:, :]

    # scramble the chunk's (hi, lo) value planes under the per-lane salts
    bvhi = bvhi_ref[:, :]
    bvlo = bvlo_ref[:, :]
    bhhi, bhlo = scramble64(
        bvhi,
        bvlo,
        salts_ref[:, 0:1],
        salts_ref[:, 1:2],
        salts_ref[:, 2:3],
        salts_ref[:, 3:4],
    )

    # candidates: below the running threshold = the max retained hash when
    # full, (MAX, MAX) otherwise — i.e. simply the last entry of the sorted
    # block (padding IS (MAX, MAX))
    def threshold():
        last = lane_k == (k - 1)
        thi = _usel(last, out_hhi_ref[:, :])
        tlo = _usel(last, out_hlo_ref[:, :])
        return thi, tlo

    thi, tlo = threshold()
    cand = _lex_lt(bhhi, bhlo, thi, tlo)  # [r, chunk]

    # the while_loop carries the candidate mask as int32, not bool: Mosaic
    # cannot yield i1 vectors from scf loops (failed-to-legalize on TPU,
    # observed 2026-07-30)
    def cond(carry):
        cand_i, _ = carry
        return jnp.any(cand_i != 0)

    def body(carry):
        cand_i, size_c = carry
        cand_c = cand_i != 0
        active = jnp.any(cand_c, axis=1, keepdims=True)  # [r, 1]
        # minimum candidate hash, lexicographic over (hi, lo)
        mhi = _umin_where(cand_c, bhhi)
        is_mhi = cand_c & (bhhi == mhi)
        mlo = _umin_where(is_mhi, bhlo)
        hit = is_mhi & (bhlo == mlo)
        # first chunk lane carrying (mhi, mlo): its value bits
        first = hit & (lane_cumsum(hit.astype(jnp.int32)) == 1)
        vlo = _usel(first, bvlo_ref[:, :])
        vhi = _usel(first, bvhi_ref[:, :])
        # dedup: (hash, value) already resident?
        ehhi = out_hhi_ref[:, :]
        ehlo = out_hlo_ref[:, :]
        evlo = (
            jax.lax.bitcast_convert_type(out_values_ref[:, :], jnp.uint32)
            if out_values_ref.dtype != jnp.uint32
            else out_values_ref[:, :]
        )
        evhi = out_vhi_ref[:, :]
        same = (ehhi == mhi) & (ehlo == mlo) & (evlo == vlo) & (evhi == vhi)
        present = jnp.any(same, axis=1, keepdims=True)
        do_insert = active & ~present
        # insertion position: lexicographic rank of (hash, value) among
        # resident entries — identical to the XLA sort-merge layout,
        # including 64-bit hash ties
        ins_lt = _lex_lt(ehhi, ehlo, mhi, mlo) | (
            (ehhi == mhi)
            & (ehlo == mlo)
            & ((evhi < vhi) | ((evhi == vhi) & (evlo < vlo)))
        )
        pos = jnp.sum(ins_lt.astype(jnp.int32), axis=1, keepdims=True)
        # k-wide sorted insert: entries < pos stay, == pos take the new
        # entry, > pos shift right by one (last entry drops; lane 0 never
        # shifts, so roll's wraparound value is always masked)
        take_new = (lane_k == pos) & do_insert
        shift = (lane_k > pos) & do_insert
        for ref, new_col in (
            (out_hhi_ref, mhi),
            (out_hlo_ref, mlo),
            (out_vhi_ref, vhi),
        ):
            cur = ref[:, :]
            rolled = jnp.roll(cur, 1, axis=1)
            ref[:, :] = jnp.where(
                take_new, new_col.astype(cur.dtype),
                jnp.where(shift, rolled, cur),
            )
        cur = out_values_ref[:, :]
        rolled = jnp.roll(cur, 1, axis=1)
        if out_values_ref.dtype == jnp.uint32:
            new_v = vlo
        else:
            new_v = jax.lax.bitcast_convert_type(vlo, out_values_ref.dtype)
        out_values_ref[:, :] = jnp.where(
            take_new, new_v, jnp.where(shift, rolled, cur)
        )
        size_n = jnp.where(
            do_insert, jnp.minimum(size_c + 1, k), size_c
        )
        # retire every chunk lane carrying this (hash, value) — within-
        # chunk duplicates cost one iteration total (cross-chunk repeats
        # fail the tightened threshold or the dedup compare instead)
        consumed = (
            (bhhi == mhi) & (bhlo == mlo)
            & (bvhi_ref[:, :] == vhi) & (bvlo_ref[:, :] == vlo)
        )
        cand_n = cand_c & ~consumed
        # the threshold may have tightened; re-mask candidates
        thi_n, tlo_n = threshold()  # reads the just-updated out refs
        cand_n = cand_n & _lex_lt(bhhi, bhlo, thi_n, tlo_n)
        return cand_n.astype(jnp.int32), size_n

    _, size = jax.lax.while_loop(
        cond, body, (cand.astype(jnp.int32), out_size_ref[:, :])
    )
    out_size_ref[:, :] = size


def update_pallas(
    state: DistinctState,
    batch,
    *,
    block_r=None,
    chunk_b: "int | None" = None,
    interpret: bool = False,
) -> DistinctState:
    """Full-tile distinct merge, state-identical to
    :func:`reservoir_tpu.ops.distinct.update` on full tiles (default hash).

    ``batch`` is ``[R, B]`` (narrow) or an ``(hi, lo)`` uint32 plane pair
    (wide).  Requires :func:`supports`.  ``chunk_b`` streams the batch
    through the 2-D grid pipeline in ``B // chunk_b`` chunks (``None``/0
    or a non-divisor of B = whole tile in one cell); every decomposition
    is state-identical by construction.
    """
    R, k = state.values.shape
    wide = state.wide
    if wide and not isinstance(batch, tuple):
        raise ValueError("wide states take (hi, lo) uint32 plane pairs")
    if not supports(state, None, None, block_r, batch):
        raise ValueError(
            "update_pallas: unsupported config (need int32 counters, "
            "full tiles); use ops.distinct.update"
        )
    if wide:
        bvhi, bvlo = batch
        bvhi = bvhi.astype(jnp.uint32)
        bvlo = bvlo.astype(jnp.uint32)
        cvhi = state.value_hi
        cvalues = state.values
    else:
        b = batch
        bvlo = b.view(jnp.uint32) if b.dtype != jnp.uint32 else b
        bvhi = _sign_extend_hi(bvlo)
        from .distinct import _carried_hi

        cvhi = _carried_hi(state.values)
        cvalues = state.values
    B = bvlo.shape[1]
    from .blocking import resolve_chunk

    chunk_b = resolve_chunk(B, chunk_b)
    if block_r is None:
        block_r = pick_block_r(R, k, chunk_b)
    if bvlo.shape[0] != R:
        raise ValueError(f"batch has {bvlo.shape[0]} rows for {R} reservoirs")
    hash_hi, hash_lo = state.hash_hi, state.hash_lo
    size, salts = state.size, state.salts
    R_orig = R
    if R % block_r != 0:
        from .blocking import pad_rows, shrink_block_to

        block_r = shrink_block_to(R, block_r)
        pad = (-R) % block_r
        if pad:
            # pad lanes replicate the last reservoir and insert into their
            # own (discarded) copies — sliced off after the kernel
            (cvalues, cvhi, hash_hi, hash_lo, size, salts, bvlo, bvhi) = (
                pad_rows(
                    pad, cvalues, cvhi, hash_hi, hash_lo, size, salts,
                    bvlo, bvhi,
                )
            )
            R += pad

    col = lambda i, j: (i, 0)  # noqa: E731 — row-block i, chunk-invariant
    col_spec = lambda w: pl.BlockSpec(  # noqa: E731
        (block_r, w), col, memory_space=pltpu.VMEM
    )
    # the streamed value planes: chunk j of row-block i — the only blocks
    # whose index varies along the inner grid axis, so Mosaic's pipeline
    # double-buffers exactly these HBM->VMEM streams
    stream_spec = pl.BlockSpec(
        (block_r, chunk_b), lambda i, j: (i, j), memory_space=pltpu.VMEM
    )

    out_values, out_vhi, out_hhi, out_hlo, out_size = pl.pallas_call(
        functools.partial(_kernel, k=k),
        grid=(R // block_r, B // chunk_b),
        in_specs=[
            col_spec(k),
            col_spec(k),
            col_spec(k),
            col_spec(k),
            col_spec(1),
            col_spec(4),
            stream_spec,
            stream_spec,
        ],
        out_specs=(
            col_spec(k),
            col_spec(k),
            col_spec(k),
            col_spec(k),
            col_spec(1),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((R, k), state.values.dtype),
            jax.ShapeDtypeStruct((R, k), jnp.uint32),
            jax.ShapeDtypeStruct((R, k), jnp.uint32),
            jax.ShapeDtypeStruct((R, k), jnp.uint32),
            jax.ShapeDtypeStruct((R, 1), jnp.int32),
        ),
        interpret=interpret,
    )(
        cvalues,
        cvhi,
        hash_hi,
        hash_lo,
        size.reshape(R, 1),
        salts,
        bvlo,
        bvhi,
    )
    if R != R_orig:  # drop the inert pad lanes
        out_values = out_values[:R_orig]
        out_vhi = out_vhi[:R_orig]
        out_hhi = out_hhi[:R_orig]
        out_hlo = out_hlo[:R_orig]
        out_size = out_size[:R_orig]
    return DistinctState(
        values=out_values,
        hash_hi=out_hhi,
        hash_lo=out_hlo,
        size=out_size.reshape(R_orig),
        count=state.count + jnp.asarray(B, state.count.dtype),
        salts=state.salts,
        value_hi=out_vhi if wide else None,
    )
