"""Salted 64-bit scrambling for distinct-value (bottom-k) sampling.

The reference maps a user hash through a per-sampler-random scramble
``byteswap64(r1 ^ byteswap64(r0 ^ hash(elem)))`` (``Sampler.scala:385-396``)
so that the "k smallest hashes" criterion is independent of the user hash's
structure.  We need the same property, but computable on TPU, where 64-bit
integers are emulated and slow: the scramble here is a 6-round Feistel
permutation over a (hi, lo) pair of uint32 limbs, with the murmur3 32-bit
finalizer (`fmix32`) as the round function and two 64-bit salts injected
half-way — a 64-bit keyed permutation built entirely from uint32 ops that
vectorize on the VPU.

The functions are backend-agnostic (NumPy and jax.numpy share the ufunc
surface), so the CPU oracle and the device kernel use literally the same
code — distinct-mode selection is integer-only and therefore *bit-identical*
across oracle and device (unlike the float-driven Algorithm-L skip path).

Collision bias: identical to the reference — two distinct values colliding in
the 64-bit scrambled hash are treated as one (``Sampler.scala:396-408``);
probability ~ n^2 / 2^65.
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

__all__ = [
    "fmix32",
    "scramble64",
    "scramble64_int",
    "scramble64_array",
    "default_hash64",
    "draw_salts",
    "U32_MASK",
]

U32_MASK = 0xFFFFFFFF

# Distinct odd constants injected per Feistel round (first 6 decimals of
# well-known irrational constants, forced odd — nothing-up-my-sleeve numbers).
_ROUND_CONSTS = (
    0x9E3779B9,  # golden ratio
    0x85EBCA6B,  # murmur3 c1
    0xC2B2AE35,  # murmur3 c2
    0x27D4EB2F,  # xxhash prime
    0x165667B1,  # xxhash prime
    0x9E3779B1,  # golden ratio (odd variant)
)


def fmix32(x):
    """murmur3 32-bit finalizer — a full-avalanche permutation of uint32."""
    x = x ^ (x >> np.uint32(16))
    x = x * np.uint32(0x85EBCA6B)
    x = x ^ (x >> np.uint32(13))
    x = x * np.uint32(0xC2B2AE35)
    x = x ^ (x >> np.uint32(16))
    return x


def scramble64(hi, lo, r0_hi, r0_lo, r1_hi, r1_lo):
    """Keyed 64-bit permutation of ``(hi, lo)`` under salts ``r0``, ``r1``.

    Plays the role of the reference's double byteswap64 scramble
    (``Sampler.scala:396``): per-sampler salts make the ordering of scrambled
    hashes an independent uniform random order per sampler instance.

    All inputs are uint32 arrays/scalars (NumPy or jax.numpy); arithmetic is
    modular, so the two backends agree bit-for-bit.
    """
    hi = hi ^ r0_hi
    lo = lo ^ r0_lo
    for c in _ROUND_CONSTS[:3]:
        hi, lo = lo, hi ^ fmix32(lo + np.uint32(c))
    hi = hi ^ r1_hi
    lo = lo ^ r1_lo
    for c in _ROUND_CONSTS[3:]:
        hi, lo = lo, hi ^ fmix32(lo + np.uint32(c))
    return hi, lo


def default_hash64(value):
    """Default element hash: sign-extend an int32 array to a (hi, lo) pair.

    Matches the reference default ``_.hashCode().toLong`` (``Sampler.scala:75``)
    in spirit: an identity-like embedding — all mixing is done by
    :func:`scramble64`.  Works on NumPy and jax.numpy int32 arrays alike.
    """
    i32 = value.astype(np.int32)
    lo = i32.view(np.uint32) if isinstance(i32, np.ndarray) else i32.view("uint32")
    hi = (i32 >> np.int32(31)).view(np.uint32) if isinstance(i32, np.ndarray) else (
        i32 >> 31
    ).view("uint32")
    return hi, lo


def _split_u64(x: int) -> Tuple[int, int]:
    x &= (1 << 64) - 1
    return (x >> 32) & U32_MASK, x & U32_MASK


def _fmix32_int(x: int) -> int:
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & U32_MASK
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & U32_MASK
    x ^= x >> 16
    return x


def scramble64_int(value: int, salts: Tuple[int, int]) -> int:
    """Scalar Python-int form of :func:`scramble64` used by the CPU oracle.

    ``value`` is interpreted as a 64-bit pattern; returns the scrambled hash
    as a Python int in ``[0, 2^64)``.  Pure Python-int modular arithmetic —
    bit-identical to the array versions (asserted in ``tests/test_oracle.py``)
    but ~20x faster per call than NumPy uint32 scalar ops, which dominate the
    per-element distinct hot path otherwise.
    """
    hi, lo = _split_u64(int(value))
    r0_hi, r0_lo = _split_u64(salts[0])
    r1_hi, r1_lo = _split_u64(salts[1])
    hi ^= r0_hi
    lo ^= r0_lo
    for c in _ROUND_CONSTS[:3]:
        hi, lo = lo, hi ^ _fmix32_int((lo + c) & U32_MASK)
    hi ^= r1_hi
    lo ^= r1_lo
    for c in _ROUND_CONSTS[3:]:
        hi, lo = lo, hi ^ _fmix32_int((lo + c) & U32_MASK)
    return (hi << 32) | lo


def scramble64_array(values: np.ndarray, salts: Tuple[int, int]) -> np.ndarray:
    """Vectorized host scramble: int64/uint64 array -> uint64 scrambled hashes.

    The NumPy-array form of :func:`scramble64_int` for the oracle's bulk path;
    bit-identical to the scalar and device versions."""
    v = np.asarray(values)
    if v.dtype.kind not in "iu":
        raise ValueError(f"expected an integer array, got {v.dtype}")
    u = v.astype(np.int64, copy=False).view(np.uint64)
    hi = (u >> np.uint64(32)).astype(np.uint32)
    lo = (u & np.uint64(U32_MASK)).astype(np.uint32)
    r0_hi, r0_lo = _split_u64(salts[0])
    r1_hi, r1_lo = _split_u64(salts[1])
    with np.errstate(over="ignore"):
        shi, slo = scramble64(
            hi, lo,
            np.uint32(r0_hi), np.uint32(r0_lo),
            np.uint32(r1_hi), np.uint32(r1_lo),
        )
    return (shi.astype(np.uint64) << np.uint64(32)) | slo.astype(np.uint64)


def draw_salts(rng: np.random.Generator) -> Tuple[int, int]:
    """Per-instance salts, drawn once at construction (``Sampler.scala:385-388``)."""
    return int(rng.integers(0, 1 << 64, dtype=np.uint64)), int(
        rng.integers(0, 1 << 64, dtype=np.uint64)
    )


def as_scalar_hash(tile_hash_fn: Any):
    """One hash definition for both layers (VERDICT r1 item 6).

    A user hash for the device kernel is array-level:
    ``tile_hash_fn(values) -> (hi, lo)`` uint32 arrays.  Because this module
    is backend-agnostic (NumPy and jax.numpy share the ufunc surface), the
    same function runs on host arrays — this adapter derives the CPU
    oracle's scalar form (``value -> 64-bit int``, the
    ``Sampler.distinct`` hash shape, ``Sampler.scala:173``) by feeding a
    1-element array:

        tile_hash = lambda v: (v >> 16, v * 31)          # one definition
        api.distinct(k, hash_fn=as_scalar_hash(tile_hash))  # host layer
        ReservoirEngine(cfg, hash_fn=tile_hash)             # device layer
    """

    def scalar_hash(value) -> int:
        arr = np.asarray([value])
        hi, lo = tile_hash_fn(arr)
        return (int(np.uint32(hi[0])) << 32) | int(np.uint32(lo[0]))

    return scalar_hash
