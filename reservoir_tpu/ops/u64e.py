"""Emulated unsigned-64-bit integers on uint32 planes (no x64 needed).

Algorithm L's stream positions (``count``/``nxt``) saturate int32 past
~2.1e9 elements per reservoir, and the int64 escape hatch needs global
x64 (VERDICT r2 item 5).  Distinct mode already solved
64-bit-without-x64 with uint32 bit-planes for *values*
(``ops/distinct.py``); this module applies the same trick to *counters*:
a logical uint64 is a uint32 array with a trailing axis of 2 —
``[..., 0]`` the low word, ``[..., 1]`` the high word.

Only the operations the Algorithm-L hot path needs are provided; all are
elementwise over leading axes and Pallas-compatible (pure jnp bitwise/
compare ops).  The float path (``add_f32``) is exact for every step:
``floor(f * 2^-32)`` and the remainder are both exactly representable in
f32 (the remainder is a multiple of the f32 grid at the value's exponent),
so wide arithmetic is bit-identical to the int64 path fed the same f32
skip — pinned by ``tests/test_wide_count.py``.

Reference: ``Sampler.scala:203`` (``count: Long``) — the contract this
restores without global x64.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "make",
    "from_int",
    "lo",
    "hi",
    "add_u32",
    "add_f32",
    "add64",
    "sub_u32",
    "sub64",
    "le",
    "lt",
    "is_zero",
    "mod64",
    "diff_small",
    "to_f32",
    "to_int",
]

_TWO32 = float(2.0**32)


def make(lo_w: jax.Array, hi_w: jax.Array) -> jax.Array:
    """Pack ``(lo, hi)`` uint32 words into the trailing-axis-2 layout."""
    return jnp.stack(
        [jnp.asarray(lo_w, jnp.uint32), jnp.asarray(hi_w, jnp.uint32)], axis=-1
    )


def from_int(value: int, shape=()) -> jax.Array:
    """A constant logical uint64 broadcast to ``shape + (2,)``."""
    value = int(value)
    lo_w = jnp.full(shape, value & 0xFFFFFFFF, jnp.uint32)
    hi_w = jnp.full(shape, (value >> 32) & 0xFFFFFFFF, jnp.uint32)
    return make(lo_w, hi_w)


def lo(a: jax.Array) -> jax.Array:
    return a[..., 0]


def hi(a: jax.Array) -> jax.Array:
    return a[..., 1]


def add_u32(a: jax.Array, d) -> jax.Array:
    """``a + d`` for ``d`` a uint32 (carry-propagating)."""
    d = jnp.asarray(d, jnp.uint32)
    lo_n = a[..., 0] + d
    carry = (lo_n < a[..., 0]).astype(jnp.uint32)  # wrapped iff smaller
    return make(lo_n, a[..., 1] + carry)


def add_f32(a: jax.Array, f: jax.Array) -> jax.Array:
    """``a + floor(f)`` for non-negative f32 ``f`` (< 2^63).

    The hi/lo split of ``f`` is exact in f32 (exponent-shift multiply,
    exact floor, and a remainder on the same mantissa grid), so this is
    bit-identical to ``a + f.astype(int64)`` under x64.
    """
    f = jnp.maximum(f, 0.0)
    hi_f = jnp.floor(f * (1.0 / _TWO32))
    rem = f - hi_f * _TWO32  # exact: multiple of the grid at f's exponent
    lo_n = a[..., 0] + rem.astype(jnp.uint32)
    carry = (lo_n < a[..., 0]).astype(jnp.uint32)
    return make(lo_n, a[..., 1] + hi_f.astype(jnp.uint32) + carry)


def add64(a: jax.Array, b: jax.Array) -> jax.Array:
    """``a + b`` for two logical uint64s (wrapping mod 2^64)."""
    lo_n = a[..., 0] + b[..., 0]
    carry = (lo_n < a[..., 0]).astype(jnp.uint32)
    return make(lo_n, a[..., 1] + b[..., 1] + carry)


def sub_u32(a: jax.Array, d) -> jax.Array:
    """``a - d`` for ``d`` a uint32 (borrow-propagating, wrapping)."""
    d = jnp.asarray(d, jnp.uint32)
    borrow = (a[..., 0] < d).astype(jnp.uint32)
    return make(a[..., 0] - d, a[..., 1] - borrow)


def sub64(a: jax.Array, b: jax.Array) -> jax.Array:
    """``a - b`` for two logical uint64s (wrapping mod 2^64)."""
    borrow = (a[..., 0] < b[..., 0]).astype(jnp.uint32)
    return make(a[..., 0] - b[..., 0], a[..., 1] - b[..., 1] - borrow)


def le(a: jax.Array, b: jax.Array) -> jax.Array:
    """``a <= b`` as 64-bit unsigned lexicographic compare."""
    return (a[..., 1] < b[..., 1]) | (
        (a[..., 1] == b[..., 1]) & (a[..., 0] <= b[..., 0])
    )


def lt(a: jax.Array, b: jax.Array) -> jax.Array:
    """``a < b`` as 64-bit unsigned lexicographic compare."""
    return ~le(b, a)


def is_zero(a: jax.Array) -> jax.Array:
    return (a[..., 0] == 0) & (a[..., 1] == 0)


def mod64(a: jax.Array, d: jax.Array) -> jax.Array:
    """``a mod d`` for logical uint64s, ``d >= 1`` — restoring long
    division, 64 shift-subtract steps on the planes.

    Correct for any ``d`` including ``d > 2^63``: the bit shifted out of
    the 64-bit remainder window forces a subtraction, and the wrapping
    :func:`sub64` then yields the true (in-range) remainder because the
    pre-subtraction value is always < 2·d.  O(64) vectorized iterations —
    intended for cold paths (result-level merges), not per-element loops.
    """
    a_lo, a_hi = a[..., 0], a[..., 1]

    def body(i, rem):
        idx = (jnp.uint32(63) - jnp.asarray(i, jnp.uint32))
        use_hi = idx >= jnp.uint32(32)
        word = jnp.where(use_hi, a_hi, a_lo)
        sh = jnp.where(use_hi, idx - jnp.uint32(32), idx)
        bit = (word >> sh) & jnp.uint32(1)
        shifted_out = rem[..., 1] >> 31
        rem2 = make(
            (rem[..., 0] << 1) | bit,
            (rem[..., 1] << 1) | (rem[..., 0] >> 31),
        )
        need = (shifted_out == 1) | ~lt(rem2, d)
        return jnp.where(need[..., None], sub64(rem2, d), rem2)

    return jax.lax.fori_loop(0, 64, body, jnp.zeros_like(a))


def diff_small(a: jax.Array, b: jax.Array) -> jax.Array:
    """``a - b`` as int32 for differences known to fit int32 (e.g. a tile-
    local position): wrap-around low-word subtraction, two's complement."""
    return (a[..., 0] - b[..., 0]).astype(jnp.int32)


def to_f32(a: jax.Array) -> jax.Array:
    """Approximate float32 value (for stats/telemetry, not sampling state —
    the single owner of the plane layout, so callers never index planes)."""
    return a[..., 0].astype(jnp.float32) + _TWO32 * a[..., 1].astype(
        jnp.float32
    )


def to_int(a) -> int:
    """Host-side readback of a scalar logical value as a Python int."""
    import numpy as np

    arr = np.asarray(a)
    return int(arr[..., 1]) * (1 << 32) + int(arr[..., 0])
