"""Device ops: vmapped Algorithm-L, bottom-k distinct, weighted A-ExpJ, hashing."""
