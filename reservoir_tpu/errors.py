"""Exception types for reservoir-tpu.

The reference maps failure modes onto JVM exception types
(``core/src/main/scala/lgbt/princess/reservoir/Sampler.scala:79-95, 185-186``;
``akka-stream/.../SampleImpl.scala:56-57``).  We mirror the *semantics* with
idiomatic Python exception types:

- ``IllegalArgumentException``  -> ``ValueError``   (invalid ``max_sample_size``)
- ``NullPointerException``      -> ``TypeError``    (missing/non-callable ``map``/``hash``)
- ``IllegalStateException``     -> ``SamplerClosedError``
- ``AbruptStageTerminationException`` -> ``AbruptStreamTermination``

Beyond the reference's surface, the module carries the **failure taxonomy**
of the robustness plane (SURVEY §5 failure-detection row): a device/transfer
failure is either *transient* (:class:`TransientDeviceError` — worth
retrying under a :class:`RetryPolicy`) or *fatal* (everything else — fails
the stream through the tri-state completion protocol).  :class:`FlushTimeout`
is the watchdog's verdict on a hung device and is deliberately fatal: the
flush worker may be wedged inside the runtime, so a retry could never run.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Tuple, Type


class SamplerClosedError(RuntimeError):
    """Raised when a single-use sampler is used after ``result()``.

    Mirrors the reference's ``IllegalStateException`` thrown by
    ``SingleUse.checkOpen()`` (``Sampler.scala:185-186``).
    """


class AbruptStreamTermination(RuntimeError):
    """The stream operator terminated without completing, failing or cancelling.

    Mirrors ``AbruptStageTerminationException`` delivered by the reference's
    ``postStop`` backstop (``SampleImpl.scala:56-57``): if the materialized
    future was never completed by the normal protocol, it is failed with this.
    """


class StreamCancelled(RuntimeError):
    """Downstream cancelled with a real failure (non-graceful).

    Mirrors the non-``NonFailureCancellation`` branch of
    ``onDownstreamFinish`` (``SampleImpl.scala:48-54``).
    """


class TransientDeviceError(RuntimeError):
    """A device/transfer failure worth retrying (the *transient* half of the
    failure taxonomy).  The bridge's flush worker retries these under its
    :class:`RetryPolicy` before surfacing them; every other exception type is
    fatal on first occurrence."""


class FlushTimeout(RuntimeError):
    """A device flush exceeded the bridge's watchdog budget.

    Deliberately **fatal** (not a :class:`TransientDeviceError`): the flush
    worker is presumed wedged inside the runtime call, so the watchdog fails
    the materialized future through the tri-state completion protocol
    instead of letting callers block forever on ``join``/``result``."""


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file is truncated or corrupt (bad zip container, missing
    or unparseable manifest) — typed so recovery tooling can distinguish
    "re-take the checkpoint" from programming errors, instead of catching
    raw numpy/zipfile internals."""


class CheckpointMismatch(CheckpointCorrupt):
    """A checkpoint is internally consistent but cannot be restored *here*:
    its state arrays disagree with its recorded config, or its config needs
    a backend this process does not have (e.g. a meshed engine whose
    reservoir count does not divide the live device count).  Raised by the
    recovery pre-flight (``load_engine`` / ``recover``) with the mismatch
    named, instead of an opaque shape error deep inside XLA."""


class FencedError(RuntimeError):
    """A write was refused because a newer primary epoch is persisted in the
    checkpoint directory: this process was fenced by a failover promotion
    (``StandbyReplica.promote``) and must not touch the durable state again
    — split-brain protection for the HA plane.  ``observed_epoch`` is the
    persisted epoch, ``own_epoch`` the one this writer was admitted at."""

    def __init__(self, message: str, observed_epoch: int = 0,
                 own_epoch: int = 0) -> None:
        super().__init__(message)
        self.observed_epoch = observed_epoch
        self.own_epoch = own_epoch


class UnknownSessionError(KeyError):
    """A session key is not (or no longer) leased in the serving plane's
    :class:`~reservoir_tpu.serve.sessions.SessionTable` — never opened,
    closed, or evicted (TTL/LRU).  ``KeyError`` subclass: the table is a
    mapping and callers may already handle lookup misses that way."""


class StaleSessionError(RuntimeError):
    """A session handle references a recycled reservoir row: the row's
    generation counter moved past the handle's lease.  Raised instead of
    serving another tenant's data — the serve plane's equivalent of a
    use-after-free guard."""


class SessionIngestError(RuntimeError):
    """An ingest for one session failed (device dispatch error, injected
    ``serve.ingest`` fault, bad payload).  Scoped to the failing call: the
    service and every other session stay live.  ``session`` names the key."""

    def __init__(self, session, message: str) -> None:
        super().__init__(f"session {session!r}: {message}")
        self.session = session


class ServiceSaturated(RuntimeError):
    """Admission control verdict: the serving plane's in-flight byte bound
    is exceeded and the flush pipeline cannot absorb more right now.
    Retry after ``retry_after_s`` — the request was REJECTED, not queued
    (bounded memory is the contract; queuing unboundedly would trade an
    explicit 429 for an OOM)."""

    def __init__(self, message: str, retry_after_s: float) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ShardUnavailable(ServiceSaturated):
    """The shard a session routes to cannot serve right now — its primary
    is fenced (a standby promotion is in flight), killed, or not yet
    recovered.  A :class:`ServiceSaturated` subclass: to a client this is
    the same verdict (back off ``retry_after_s`` and retry the SAME key —
    routing is deterministic, the session never moves), and crucially it
    is scoped to ONE shard: every session routed elsewhere keeps serving.
    ``shard`` names the failure domain, ``reason`` why it rejected."""

    def __init__(
        self, message: str, retry_after_s: float, shard: int,
        reason: str = "unavailable",
    ) -> None:
        super().__init__(message, retry_after_s)
        self.shard = int(shard)
        self.reason = reason


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded, jittered exponential backoff for *transient* flush failures.

    Deterministic by construction: the jitter for attempt ``i`` is drawn
    from ``random.Random((seed, i))``, so two runs with the same policy see
    the same backoff schedule (the bit-exactness story extends to timing
    decisions).

    Attributes:
      max_retries: retry attempts after the first failure (0 disables).
      base_backoff_s: backoff before retry 1; doubles per attempt.
      max_backoff_s: hard cap on any single backoff.
      jitter: fraction of the backoff randomized (0 = fully deterministic
        delay, 0.5 = uniform in ``[0.75, 1.25] * backoff``).
      seed: jitter seed.
      retryable_types: exception types considered transient.
    """

    max_retries: int = 3
    base_backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    jitter: float = 0.5
    seed: int = 0
    retryable_types: Tuple[Type[BaseException], ...] = (TransientDeviceError,)

    def retryable(self, exc: BaseException) -> bool:
        return isinstance(exc, self.retryable_types)

    def backoff_s(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1-based), jittered and capped."""
        base = min(
            self.base_backoff_s * (2.0 ** (attempt - 1)), self.max_backoff_s
        )
        if not self.jitter:
            return base
        u = random.Random(f"{self.seed}:{attempt}").random()  # deterministic
        return min(
            base * (1.0 + self.jitter * (u - 0.5)), self.max_backoff_s
        )
