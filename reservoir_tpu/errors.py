"""Exception types for reservoir-tpu.

The reference maps failure modes onto JVM exception types
(``core/src/main/scala/lgbt/princess/reservoir/Sampler.scala:79-95, 185-186``;
``akka-stream/.../SampleImpl.scala:56-57``).  We mirror the *semantics* with
idiomatic Python exception types:

- ``IllegalArgumentException``  -> ``ValueError``   (invalid ``max_sample_size``)
- ``NullPointerException``      -> ``TypeError``    (missing/non-callable ``map``/``hash``)
- ``IllegalStateException``     -> ``SamplerClosedError``
- ``AbruptStageTerminationException`` -> ``AbruptStreamTermination``
"""

from __future__ import annotations


class SamplerClosedError(RuntimeError):
    """Raised when a single-use sampler is used after ``result()``.

    Mirrors the reference's ``IllegalStateException`` thrown by
    ``SingleUse.checkOpen()`` (``Sampler.scala:185-186``).
    """


class AbruptStreamTermination(RuntimeError):
    """The stream operator terminated without completing, failing or cancelling.

    Mirrors ``AbruptStageTerminationException`` delivered by the reference's
    ``postStop`` backstop (``SampleImpl.scala:56-57``): if the materialized
    future was never completed by the normal protocol, it is failed with this.
    """


class StreamCancelled(RuntimeError):
    """Downstream cancelled with a real failure (non-graceful).

    Mirrors the non-``NonFailureCancellation`` branch of
    ``onDownstreamFinish`` (``SampleImpl.scala:48-54``).
    """
