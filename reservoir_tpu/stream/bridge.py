"""Host->device stream bridge: per-stream buffers, tile-granular flushes.

The reference's stream stage handles one element per actor callback
(``SampleImpl.scala:27-31``); a TPU cannot be fed that way.  The bridge
replaces per-element ``onPush`` with **batch flushes**: S logical streams
buffer on the host into an ``[R=S, B]`` tile, which is dispatched to a
:class:`~reservoir_tpu.engine.ReservoirEngine` whenever any stream's row
fills (ragged ``valid`` counts keep partially-filled rows exact).  This is
the SURVEY §2.4 "host->device stream bridge" component and the scale path
for BASELINE.md config 5 (65,536 concurrent streams).

The completion protocol survives the batching (SURVEY §5 "failure
detection" row): the bridge exposes the same tri-state outcome as the
operator — :meth:`complete` (future succeeds with the per-stream samples),
:meth:`fail` (future fails with the cause), and a drop-without-completion
backstop failing it with :class:`AbruptStreamTermination`
(``SampleImpl.scala:35-57``).

Thread-safety contract matches the reference (``Sampler.scala:19``): one
writer.  Wrap pushes in your own queue for multi-producer feeds.
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import Any, List, Optional, Union

import numpy as np

from ..config import SamplerConfig
from ..engine import ReservoirEngine
from ..errors import AbruptStreamTermination, SamplerClosedError
from ..native import NativeStaging
from ..utils.metrics import BridgeMetrics
from ..utils.tracing import trace_span

__all__ = ["DeviceStreamBridge", "DeviceSampler"]


class DeviceStreamBridge:
    """S independent logical streams feeding S device reservoirs in lockstep.

    Stream ``s`` owns reservoir row ``s``; elements pushed for it buffer into
    row ``s`` of a host-side ``[S, B]`` staging tile.  When any row reaches
    the tile width, the whole tile flushes to the device with per-row
    ``valid`` counts (padding rows are never sampled — the engine's ragged
    contract).  State between flushes lives only on the device.

    Args:
      config: engine config; ``num_reservoirs`` is the stream count.
      key: PRNG key or seed for the engine.
      map_fn / hash_fn: traceable hooks forwarded to the engine.
      reusable: lifecycle switch — reusable bridges allow :meth:`complete`
        followed by more pushes (snapshot semantics).
    """

    def __init__(
        self,
        config: SamplerConfig,
        key: Union[int, Any, None] = None,
        map_fn: Optional[Any] = None,
        hash_fn: Optional[Any] = None,
        reusable: bool = False,
        mesh: Optional[Any] = None,
    ) -> None:
        self._config = config
        self._engine = ReservoirEngine(
            config,
            key=key,
            map_fn=map_fn,
            hash_fn=hash_fn,
            reusable=reusable,
            mesh=mesh,
        )
        self._reusable = reusable
        S, B = config.num_reservoirs, config.tile_size
        # staging is native (C++ demux, _native/staging_buffer.cc) when the
        # helper library is available, numpy otherwise — same semantics
        self._staging = NativeStaging(
            S, B, np.dtype(config.element_dtype), weighted=config.weighted
        )
        self._tile = np.zeros((S, B), dtype=np.dtype(config.element_dtype))
        self._wtile = np.ones((S, B), np.float32) if config.weighted else None
        self._valid = np.zeros(S, np.int32)
        self._future: Future = Future()
        self._metrics = BridgeMetrics()

    # ------------------------------------------------------------ properties

    @property
    def num_streams(self) -> int:
        return self._config.num_reservoirs

    @property
    def sample(self) -> Future:
        """The bridge's materialized value: future of the per-stream samples
        (list of ``S`` arrays), completed by the tri-state protocol."""
        return self._future

    @property
    def metrics(self) -> BridgeMetrics:
        return self._metrics

    @property
    def is_open(self) -> bool:
        return self._engine.is_open and not self._future.done()

    def _check_open(self) -> None:
        if self._future.done():
            raise SamplerClosedError("this bridge has completed or failed")
        self._engine._check_open()

    # --------------------------------------------------------------- pushing

    def push(
        self,
        stream: int,
        elements: Any,
        weights: Optional[Any] = None,
    ) -> None:
        """Buffer one element or a 1-D chunk for logical stream ``stream``;
        flushes automatically whenever the stream's row fills."""
        self._check_open()
        self._metrics.start()
        arr = np.atleast_1d(np.asarray(elements, self._tile.dtype))
        warr = self._check_weights(arr, weights)
        off = 0
        n = arr.shape[0]
        while off < n:
            took = self._staging.push_chunk(
                stream,
                arr[off:],
                warr[off:] if warr is not None else None,
            )
            off += took
            if off < n or self._staging.row_full(stream):
                self.flush()
        self._metrics.elements += n

    def push_interleaved(self, streams: Any, elements: Any,
                         weights: Optional[Any] = None) -> None:
        """Demux an interleaved feed of ``(stream_id, element)`` pairs — the
        multi-producer wire format.  The scatter runs in the native staging
        helper when available (C-speed pointer walk; numpy fallback
        otherwise), flushing whenever a row fills mid-batch."""
        self._check_open()
        self._metrics.start()
        # conversions up front so the resume-loop slices stay no-copy; shape
        # and range validation belongs to NativeStaging (single owner)
        streams = np.ascontiguousarray(streams, np.int32)
        arr = np.ascontiguousarray(elements, self._tile.dtype)
        warr = self._check_weights(arr, weights)
        off = 0
        n = arr.shape[0]
        while off < n:
            took = self._staging.push_interleaved(
                streams[off:],
                arr[off:],
                warr[off:] if warr is not None else None,
            )
            off += took
            if off < n:
                self.flush()
        self._metrics.elements += n

    def _check_weights(self, arr, weights):
        if self._wtile is not None:
            if weights is None:
                raise ValueError("weighted bridge requires weights")
            warr = np.atleast_1d(np.ascontiguousarray(weights, np.float32))
            if warr.shape != arr.shape:
                raise ValueError("weights must match elements shape")
            if not np.all(warr >= 0):
                raise ValueError("weights must be nonnegative")
            return warr
        if weights is not None:
            raise ValueError("weights are only meaningful with weighted=True")
        return None

    def push_tile(self, tile: Any, valid: Optional[Any] = None,
                  weights: Optional[Any] = None) -> None:
        """Bypass buffering: dispatch a pre-assembled ``[S, B]`` tile straight
        to the device (the zero-copy fast path for array-shaped sources)."""
        self._check_open()
        self._metrics.start()
        tile = np.asarray(tile)
        with trace_span("reservoir_bridge_flush"):
            self._engine.sample(tile, valid=valid, weights=weights)
        n = int(tile.shape[1]) * tile.shape[0] if valid is None else int(
            np.sum(np.asarray(valid))
        )
        self._metrics.elements += n
        self._metrics.flushed_elements += n
        self._metrics.flushes += 1

    def flush(self) -> None:
        """Dispatch buffered elements (ragged tile) to the device."""
        total = self._staging.drain(
            self._tile,
            self._valid,
            self._wtile if self._wtile is not None else None,
        )
        if total == 0:
            return
        with trace_span("reservoir_bridge_flush"):
            if self._wtile is not None:
                # stale weight-slots past each row's valid count hold old
                # (nonnegative) weights; the valid mask keeps them out of
                # sampling and user weights are never rewritten (the r1
                # 1e-30 clamp silently mutated legitimate denormal weights)
                self._engine.sample(
                    self._tile, valid=self._valid, weights=self._wtile
                )
            else:
                self._engine.sample(self._tile, valid=self._valid)
        self._metrics.flushes += 1
        self._metrics.flushed_elements += total

    # ------------------------------------------------------------ completion

    def complete(self) -> List[np.ndarray]:
        """Upstream completion: flush remainders, fulfill the future with the
        per-stream samples, and return them (``onUpstreamFinish``,
        ``SampleImpl.scala:38-41``).  Reusable bridges may continue pushing
        afterwards (a fresh future is armed)."""
        self._check_open()
        self.flush()
        with trace_span("reservoir_bridge_result"):
            res = self._engine.result()
        self._metrics.completions += 1
        self._future.set_result(res)
        if self._reusable:
            self._future = Future()
        return res

    def fail(self, cause: BaseException) -> None:
        """Upstream failure: fail the future with ``cause``
        (``onUpstreamFailure``, ``SampleImpl.scala:43-46``)."""
        if not self._future.done():
            self._metrics.failures += 1
            self._future.set_exception(cause)

    def cancel(self, cause: Optional[BaseException] = None) -> None:
        """Downstream cancellation (``SampleImpl.scala:48-54``): graceful
        delivers the partial sample, a cause fails the future."""
        if self._future.done():
            return
        if cause is None:
            self.complete()
        else:
            self.fail(cause)

    def __del__(self) -> None:
        # postStop backstop (SampleImpl.scala:56-57)
        fut = getattr(self, "_future", None)
        if fut is not None and not fut.done():
            fut.set_exception(
                AbruptStreamTermination(
                    "stream bridge dropped without completing"
                )
            )


class DeviceSampler:
    """Single-stream :class:`~reservoir_tpu.api.Sampler`-shaped adapter over
    the device engine — lets the pass-through operator
    (:class:`~reservoir_tpu.stream.operator.Sample`) sample on TPU.

    Per-element ``sample`` buffers on the host; the device sees fixed-width
    tiles (static shapes, one compile).  ``result`` flushes the remainder and
    applies the reference truncation/lifecycle contract.
    """

    def __init__(
        self,
        config: SamplerConfig,
        key: Union[int, Any, None] = None,
        reusable: bool = False,
    ) -> None:
        if config.num_reservoirs != 1:
            raise ValueError(
                "DeviceSampler is single-stream (num_reservoirs=1); use "
                "DeviceStreamBridge for many streams"
            )
        self._engine = ReservoirEngine(config, key=key, reusable=reusable)
        self._reusable = reusable
        self._open = True
        self._buf = np.zeros(config.tile_size, dtype=np.dtype(config.element_dtype))
        self._fill = 0

    @property
    def is_open(self) -> bool:
        return True if self._reusable else self._open

    def _check_open(self) -> None:
        if not self.is_open:
            raise SamplerClosedError("this sampler is single-use, and no longer open")

    def _flush(self) -> None:
        if self._fill:
            self._engine.sample(
                self._buf[None, :], valid=np.asarray([self._fill], np.int32)
            )
            self._fill = 0

    def sample(self, element: Any) -> None:
        self._check_open()
        self._buf[self._fill] = element
        self._fill += 1
        if self._fill >= self._buf.shape[0]:
            self._flush()

    def sample_all(self, elements: Any) -> None:
        """Bulk path: array-shaped input flushes in whole tiles without the
        per-element loop (the ``sampleAll`` fast-path analog,
        ``Sampler.scala:261-287``)."""
        self._check_open()
        if not isinstance(elements, np.ndarray) and not hasattr(elements, "__len__"):
            # generator/iterator source (the Sampler ABC accepts any iterable)
            for e in elements:
                self.sample(e)
            return
        arr = np.asarray(elements) if not isinstance(elements, np.ndarray) else elements
        if arr.dtype == object or arr.ndim != 1:
            for e in np.ravel(arr):
                self.sample(e)
            return
        B = self._buf.shape[0]
        off = 0
        n = arr.shape[0]
        while off < n:
            take = min(B - self._fill, n - off)
            self._buf[self._fill : self._fill + take] = arr[off : off + take]
            self._fill += take
            off += take
            if self._fill >= B:
                self._flush()

    def result(self) -> np.ndarray:
        self._check_open()
        self._flush()
        res = self._engine.result()[0]
        if not self._reusable:
            self._open = False
            self._buf = None  # free (Sampler.scala:345-350)
        return res
