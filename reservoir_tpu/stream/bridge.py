"""Host->device stream bridge: per-stream buffers, tile-granular flushes.

The reference's stream stage handles one element per actor callback
(``SampleImpl.scala:27-31``); a TPU cannot be fed that way.  The bridge
replaces per-element ``onPush`` with **batch flushes**: S logical streams
buffer on the host into an ``[R=S, B]`` tile, which is dispatched to a
:class:`~reservoir_tpu.engine.ReservoirEngine` whenever any stream's row
fills (ragged ``valid`` counts keep partially-filled rows exact).  This is
the SURVEY §2.4 "host->device stream bridge" component and the scale path
for BASELINE.md config 5 (65,536 concurrent streams).

The completion protocol survives the batching (SURVEY §5 "failure
detection" row): the bridge exposes the same tri-state outcome as the
operator — :meth:`complete` (future succeeds with the per-stream samples),
:meth:`fail` (future fails with the cause), and a drop-without-completion
backstop failing it with :class:`AbruptStreamTermination`
(``SampleImpl.scala:35-57``).

Thread-safety contract matches the reference (``Sampler.scala:19``): one
writer.  Wrap pushes in your own queue for multi-producer feeds.
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import Any, List, Optional, Union

import numpy as np

from ..config import SamplerConfig
from ..engine import ReservoirEngine
from ..errors import AbruptStreamTermination, SamplerClosedError
from ..utils.metrics import BridgeMetrics
from ..utils.tracing import trace_span

__all__ = ["DeviceStreamBridge", "DeviceSampler"]


class DeviceStreamBridge:
    """S independent logical streams feeding S device reservoirs in lockstep.

    Stream ``s`` owns reservoir row ``s``; elements pushed for it buffer into
    row ``s`` of a host-side ``[S, B]`` staging tile.  When any row reaches
    the tile width, the whole tile flushes to the device with per-row
    ``valid`` counts (padding rows are never sampled — the engine's ragged
    contract).  State between flushes lives only on the device.

    Args:
      config: engine config; ``num_reservoirs`` is the stream count.
      key: PRNG key or seed for the engine.
      map_fn / hash_fn: traceable hooks forwarded to the engine.
      reusable: lifecycle switch — reusable bridges allow :meth:`complete`
        followed by more pushes (snapshot semantics).
    """

    def __init__(
        self,
        config: SamplerConfig,
        key: Union[int, Any, None] = None,
        map_fn: Optional[Any] = None,
        hash_fn: Optional[Any] = None,
        reusable: bool = False,
    ) -> None:
        self._config = config
        self._engine = ReservoirEngine(
            config, key=key, map_fn=map_fn, hash_fn=hash_fn, reusable=reusable
        )
        self._reusable = reusable
        S, B = config.num_reservoirs, config.tile_size
        self._buf = np.zeros((S, B), dtype=np.dtype(config.element_dtype))
        self._wbuf = np.ones((S, B), np.float32) if config.weighted else None
        self._fill = np.zeros(S, np.int64)
        self._future: Future = Future()
        self._metrics = BridgeMetrics()

    # ------------------------------------------------------------ properties

    @property
    def num_streams(self) -> int:
        return self._config.num_reservoirs

    @property
    def sample(self) -> Future:
        """The bridge's materialized value: future of the per-stream samples
        (list of ``S`` arrays), completed by the tri-state protocol."""
        return self._future

    @property
    def metrics(self) -> BridgeMetrics:
        return self._metrics

    @property
    def is_open(self) -> bool:
        return self._engine.is_open and not self._future.done()

    def _check_open(self) -> None:
        if self._future.done():
            raise SamplerClosedError("this bridge has completed or failed")
        self._engine._check_open()

    # --------------------------------------------------------------- pushing

    def push(
        self,
        stream: int,
        elements: Any,
        weights: Optional[Any] = None,
    ) -> None:
        """Buffer one element or a 1-D chunk for logical stream ``stream``;
        flushes automatically whenever the stream's row fills."""
        self._check_open()
        self._metrics.start()
        arr = np.atleast_1d(np.asarray(elements, self._buf.dtype))
        if self._wbuf is not None:
            if weights is None:
                raise ValueError("weighted bridge requires weights")
            warr = np.atleast_1d(np.asarray(weights, np.float32))
            if warr.shape != arr.shape:
                raise ValueError("weights must match elements shape")
            if not np.all(warr > 0):
                raise ValueError("weights must be strictly positive")
        elif weights is not None:
            raise ValueError("weights are only meaningful with weighted=True")
        B = self._buf.shape[1]
        off = 0
        n = arr.shape[0]
        while off < n:
            fill = int(self._fill[stream])
            take = min(B - fill, n - off)
            self._buf[stream, fill : fill + take] = arr[off : off + take]
            if self._wbuf is not None:
                self._wbuf[stream, fill : fill + take] = warr[off : off + take]
            self._fill[stream] += take
            off += take
            if self._fill[stream] >= B:
                self.flush()
        self._metrics.elements += n

    def push_tile(self, tile: Any, valid: Optional[Any] = None,
                  weights: Optional[Any] = None) -> None:
        """Bypass buffering: dispatch a pre-assembled ``[S, B]`` tile straight
        to the device (the zero-copy fast path for array-shaped sources)."""
        self._check_open()
        self._metrics.start()
        tile = np.asarray(tile)
        with trace_span("reservoir_bridge_flush"):
            self._engine.sample(tile, valid=valid, weights=weights)
        n = int(tile.shape[1]) * tile.shape[0] if valid is None else int(
            np.sum(np.asarray(valid))
        )
        self._metrics.elements += n
        self._metrics.flushed_elements += n
        self._metrics.flushes += 1

    def flush(self) -> None:
        """Dispatch buffered elements (ragged tile) to the device."""
        if not np.any(self._fill):
            return
        valid = self._fill.astype(np.int32)
        with trace_span("reservoir_bridge_flush"):
            if self._wbuf is not None:
                self._engine.sample(self._buf, valid=valid, weights=self._wbuf)
            else:
                self._engine.sample(self._buf, valid=valid)
        self._metrics.flushes += 1
        self._metrics.flushed_elements += int(valid.sum())
        self._fill[:] = 0

    # ------------------------------------------------------------ completion

    def complete(self) -> List[np.ndarray]:
        """Upstream completion: flush remainders, fulfill the future with the
        per-stream samples, and return them (``onUpstreamFinish``,
        ``SampleImpl.scala:38-41``).  Reusable bridges may continue pushing
        afterwards (a fresh future is armed)."""
        self._check_open()
        self.flush()
        with trace_span("reservoir_bridge_result"):
            res = self._engine.result()
        self._metrics.completions += 1
        self._future.set_result(res)
        if self._reusable:
            self._future = Future()
        return res

    def fail(self, cause: BaseException) -> None:
        """Upstream failure: fail the future with ``cause``
        (``onUpstreamFailure``, ``SampleImpl.scala:43-46``)."""
        if not self._future.done():
            self._metrics.failures += 1
            self._future.set_exception(cause)

    def cancel(self, cause: Optional[BaseException] = None) -> None:
        """Downstream cancellation (``SampleImpl.scala:48-54``): graceful
        delivers the partial sample, a cause fails the future."""
        if self._future.done():
            return
        if cause is None:
            self.complete()
        else:
            self.fail(cause)

    def __del__(self) -> None:
        # postStop backstop (SampleImpl.scala:56-57)
        fut = getattr(self, "_future", None)
        if fut is not None and not fut.done():
            fut.set_exception(
                AbruptStreamTermination(
                    "stream bridge dropped without completing"
                )
            )


class DeviceSampler:
    """Single-stream :class:`~reservoir_tpu.api.Sampler`-shaped adapter over
    the device engine — lets the pass-through operator
    (:class:`~reservoir_tpu.stream.operator.Sample`) sample on TPU.

    Per-element ``sample`` buffers on the host; the device sees fixed-width
    tiles (static shapes, one compile).  ``result`` flushes the remainder and
    applies the reference truncation/lifecycle contract.
    """

    def __init__(
        self,
        config: SamplerConfig,
        key: Union[int, Any, None] = None,
        reusable: bool = False,
    ) -> None:
        if config.num_reservoirs != 1:
            raise ValueError(
                "DeviceSampler is single-stream (num_reservoirs=1); use "
                "DeviceStreamBridge for many streams"
            )
        self._engine = ReservoirEngine(config, key=key, reusable=reusable)
        self._reusable = reusable
        self._open = True
        self._buf = np.zeros(config.tile_size, dtype=np.dtype(config.element_dtype))
        self._fill = 0

    @property
    def is_open(self) -> bool:
        return True if self._reusable else self._open

    def _check_open(self) -> None:
        if not self.is_open:
            raise SamplerClosedError("this sampler is single-use, and no longer open")

    def _flush(self) -> None:
        if self._fill:
            self._engine.sample(
                self._buf[None, :], valid=np.asarray([self._fill], np.int32)
            )
            self._fill = 0

    def sample(self, element: Any) -> None:
        self._check_open()
        self._buf[self._fill] = element
        self._fill += 1
        if self._fill >= self._buf.shape[0]:
            self._flush()

    def sample_all(self, elements: Any) -> None:
        """Bulk path: array-shaped input flushes in whole tiles without the
        per-element loop (the ``sampleAll`` fast-path analog,
        ``Sampler.scala:261-287``)."""
        self._check_open()
        if not isinstance(elements, np.ndarray) and not hasattr(elements, "__len__"):
            # generator/iterator source (the Sampler ABC accepts any iterable)
            for e in elements:
                self.sample(e)
            return
        arr = np.asarray(elements) if not isinstance(elements, np.ndarray) else elements
        if arr.dtype == object or arr.ndim != 1:
            for e in np.ravel(arr):
                self.sample(e)
            return
        B = self._buf.shape[0]
        off = 0
        n = arr.shape[0]
        while off < n:
            take = min(B - self._fill, n - off)
            self._buf[self._fill : self._fill + take] = arr[off : off + take]
            self._fill += take
            off += take
            if self._fill >= B:
                self._flush()

    def result(self) -> np.ndarray:
        self._check_open()
        self._flush()
        res = self._engine.result()[0]
        if not self._reusable:
            self._open = False
            self._buf = None  # free (Sampler.scala:345-350)
        return res
