"""Host->device stream bridge: per-stream buffers, tile-granular flushes.

The reference's stream stage handles one element per actor callback
(``SampleImpl.scala:27-31``); a TPU cannot be fed that way.  The bridge
replaces per-element ``onPush`` with **batch flushes**: S logical streams
buffer on the host into an ``[R=S, B]`` tile, which is dispatched to a
:class:`~reservoir_tpu.engine.ReservoirEngine` whenever any stream's row
fills (ragged ``valid`` counts keep partially-filled rows exact).  This is
the SURVEY §2.4 "host->device stream bridge" component and the scale path
for BASELINE.md config 5 (65,536 concurrent streams).

The completion protocol survives the batching (SURVEY §5 "failure
detection" row): the bridge exposes the same tri-state outcome as the
operator — :meth:`complete` (future succeeds with the per-stream samples),
:meth:`fail` (future fails with the cause), and a drop-without-completion
backstop failing it with :class:`AbruptStreamTermination`
(``SampleImpl.scala:35-57``).

Beyond the reference's protocol, the bridge carries the executable half of
the SURVEY §5 failure-*recovery* story (ISSUE 3):

- **retry**: the flush worker retries :class:`TransientDeviceError` under a
  bounded, jittered :class:`~reservoir_tpu.errors.RetryPolicy` before
  surfacing — an injected transient fault completes the stream with results
  bit-identical to a clean run (state advances only on success);
- **watchdog**: ``flush_timeout_s`` arms a per-flush (per-attempt) timer; a
  hung device fails the materialized future with
  :class:`~reservoir_tpu.errors.FlushTimeout` through the tri-state
  protocol instead of wedging every caller;
- **auto-checkpoint + journal replay**: ``checkpoint_dir`` snapshots engine
  state atomically every ``checkpoint_every`` flushes and journals each
  flushed tile to a spill file; :meth:`recover` rebuilds the bridge after a
  crash and replays the journaled tail — reservoirs come back bit-identical
  to an uninterrupted run (counter-keyed draws make replay exact);
- **fault plane**: the ``bridge.demux`` / ``bridge.dispatch`` injection
  sites (:mod:`reservoir_tpu.utils.faults`) make all of the above testable
  deterministically, per-bridge (``faults=``) or globally
  (``RESERVOIR_FAULTS``), at zero cost when disabled.

Thread-safety contract matches the reference (``Sampler.scala:19``): one
writer.  Wrap pushes in your own queue for multi-producer feeds.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import queue
import struct
import threading
import time
import zlib
from concurrent.futures import Future
from typing import Any, Iterator, List, Optional, Tuple, Union

import numpy as np

from ..config import SamplerConfig
from ..engine import ReservoirEngine
from ..errors import (
    AbruptStreamTermination,
    FencedError,
    FlushTimeout,
    RetryPolicy,
    SamplerClosedError,
)
from ..native import NativeStaging
from ..obs import flight as _flight
from ..obs import registry as _obs
from ..obs import trace as _ctrace
from ..utils import faults as _faults
from .gate import SkipGate, gate_ineligible_reason
from ..utils.checkpoint import read_epoch
from ..utils.log import warn_once
from ..utils.metrics import BridgeMetrics
from ..utils.tracing import trace_span

__all__ = ["DeviceStreamBridge", "DeviceSampler"]


class _FlushPipeline:
    """Depth-1 dispatch pipeline: a single worker thread runs the device
    flushes while the caller demuxes the NEXT tile (VERDICT r2 item 3 —
    the r2 bridge drained and dispatched serially on one staging tile).

    ``reserve`` blocks while both host tiles are busy (bounded
    reservations = natural backpressure, two host tiles of memory total);
    ``join`` waits for the in-flight flushes and re-raises any worker
    exception on the caller's thread.  One producer, one worker: the
    engine keeps its single-writer contract because only the worker
    touches it between ``join`` barriers.

    The tile-reuse hazard the semaphore closes: ``Queue.put`` alone
    returns as soon as the worker has *taken* the previous tile, not
    finished it — the caller could then demux into a tile the worker is
    still reading.  ``reserve()`` (sized to the tile count) blocks until
    a host tile is genuinely free: the worker releases a reservation only
    AFTER its flush completes.

    Robustness plane (ISSUE 3): the worker retries *transient* flush
    failures under ``retry_policy`` (bounded jittered backoff) before
    surfacing them; ``watchdog_s`` arms a per-attempt timer that fails the
    owner's future with :class:`FlushTimeout` when a flush hangs (the
    worker is presumed wedged inside the runtime — the pipeline marks
    itself wedged and every later ``reserve``/``join``/``close`` raises
    instead of blocking forever); any terminal worker error is ALSO routed
    to ``fail_cb`` immediately, so the stream fails with its cause even if
    the producer never calls again.
    """

    def __init__(
        self,
        fn,
        n_tiles: int = 2,
        retry_policy: Optional[RetryPolicy] = None,
        watchdog_s: Optional[float] = None,
        fail_cb=None,
        metrics: Optional[BridgeMetrics] = None,
    ) -> None:
        import weakref

        # weak method: the worker must not keep the bridge alive, or the
        # abrupt-termination __del__ backstop (SampleImpl.scala:56-57)
        # could never fire — a dead owner simply ends the pipeline
        self._fn = weakref.WeakMethod(fn)
        self._fail_cb = (
            weakref.WeakMethod(fail_cb) if fail_cb is not None else None
        )
        self._retry = retry_policy
        self._watchdog_s = watchdog_s
        self._metrics = metrics
        self._q: "queue.Queue" = queue.Queue()
        self._free = threading.Semaphore(n_tiles)
        self._error: Optional[BaseException] = None
        self._wedged = False
        self._inflight = False
        # completion counters replace Queue.join so the watchdog can wake
        # joiners a hung worker would otherwise block forever
        self._cv = threading.Condition()
        self._submitted = 0
        self._done = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._mark_done()
                return
            try:
                fn = self._fn()
                if fn is None:  # owner collected: discard remaining work
                    return
                with self._cv:
                    healthy = self._error is None and not self._wedged
                if healthy:
                    self._run_one(fn, item)
            except BaseException as e:  # surfaced at next reserve/join...
                with self._cv:
                    if self._error is None:
                        self._error = e
                self._fatal(e)  # ...AND on the future, right now
            finally:
                self._free.release()  # the tile is safe to demux into
                self._mark_done()

    def _run_one(self, fn, item) -> None:
        """One flush: watchdog-armed, transient failures retried."""
        attempt = 0
        while True:
            timer: Optional[threading.Timer] = None
            if self._watchdog_s is not None:
                timer = threading.Timer(self._watchdog_s, self._trip_watchdog)
                timer.daemon = True
            with self._cv:
                self._inflight = True
            if timer is not None:
                timer.start()
            try:
                fn(*item)
                return
            except BaseException as e:
                policy = self._retry
                with self._cv:
                    wedged = self._wedged
                if (
                    policy is not None
                    and not wedged
                    and policy.retryable(e)
                    and attempt < policy.max_retries
                ):
                    attempt += 1
                    if self._metrics is not None:
                        self._metrics.retries += 1
                    time.sleep(policy.backoff_s(attempt))
                    continue
                raise
            finally:
                with self._cv:
                    self._inflight = False
                if timer is not None:
                    timer.cancel()

    def _trip_watchdog(self) -> None:
        """Timer thread: the in-flight flush blew its budget.  Fail fast on
        behalf of the (presumed wedged) worker."""
        with self._cv:
            if not self._inflight:
                return  # the flush completed in the arm/cancel gap: benign
            exc = FlushTimeout(
                f"device flush exceeded the watchdog budget "
                f"({self._watchdog_s:g}s); worker presumed wedged"
            )
            self._wedged = True
            if self._error is None:
                self._error = exc
            if self._metrics is not None:
                self._metrics.watchdog_trips += 1
            self._cv.notify_all()
        self._fatal(exc)
        tr = _ctrace.get()
        if tr is not None:
            tr.point("bridge.watchdog_trip", budget_s=self._watchdog_s)
        fl = _flight.get()
        if fl is not None:
            fl.trigger("watchdog", budget_s=self._watchdog_s)

    def _fatal(self, exc: BaseException) -> None:
        """Terminal failure: fail the owner's future with the cause (the
        tri-state protocol must resolve even if the producer is gone)."""
        cb = self._fail_cb() if self._fail_cb is not None else None
        if cb is not None:
            cb(exc)

    def _mark_done(self) -> None:
        with self._cv:
            self._done += 1
            self._cv.notify_all()

    def _check(self) -> None:
        with self._cv:
            err, self._error = self._error, None
            wedged = self._wedged
        if err is not None:
            raise err
        if wedged:
            # the first caller got the original FlushTimeout above; the
            # pipeline stays unusable (its worker is stuck in the runtime)
            raise FlushTimeout("flush pipeline wedged past its watchdog")

    def reserve(self) -> None:
        """Block until a host tile is free to demux into (call BEFORE
        draining into the tile that will be submitted).  Polls so a
        watchdog trip unblocks a producer waiting on a permit the wedged
        worker will never release."""
        self._check()
        while not self._free.acquire(timeout=0.1):
            self._check()

    def would_block(self) -> bool:
        """True when a ``reserve()`` right now would block (no free host
        tile — every permit is held by in-flight flushes).  The serving
        plane's admission-control probe; never blocks itself."""
        if self._free.acquire(blocking=False):
            self._free.release()
            return False
        return True

    def release(self) -> None:
        """Return an unused reservation (the drain produced nothing)."""
        self._free.release()

    def submit(self, *args) -> None:
        with self._cv:
            self._submitted += 1
        self._q.put(args)

    def join(self) -> None:
        with self._cv:
            while (
                self._done < self._submitted
                and self._error is None
                and not self._wedged
            ):
                self._cv.wait()
        self._check()

    def close(self) -> None:
        if self._thread.is_alive():
            self._q.put(None)
            with self._cv:
                self._submitted += 1  # the sentinel is counted when drained
                wedged = self._wedged
            # a wedged worker is stuck inside a runtime call and may never
            # reach the sentinel — don't block teardown on it
            self._thread.join(timeout=1.0 if wedged else 30)
        # An exception raised on the FINAL flush used to be silently lost
        # here when the owner closed without another reserve()/join();
        # close() is a completion barrier and must re-raise it (the
        # bridge's __del__ routes it through fail() instead of raising
        # mid-teardown).
        self._check()


class _FlushJournal:
    """Append-only spill of flushed tiles since the last checkpoint.

    Each record frames one flush: ``MAGIC | seq:u64 | payload_len:u32 |
    payload | crc32(payload):u32`` where the payload is the ``valid``
    int32[S] counts, the ``[S, B]`` tile bytes, and (weighted bridges) the
    float32 weight tile.  Appends are flushed to the OS per record, so a
    *process* crash loses nothing already journaled; an OS/power crash may
    cost the tail record, which :meth:`replay` detects (short read or CRC
    mismatch, necessarily the last record) and cleanly ignores — the
    producer re-pushes from the durable watermark.

    The journal is rotated (truncated) after every successful checkpoint;
    records also carry ``seq`` so a crash *between* checkpoint write and
    rotation is safe: recovery filters out records the checkpoint already
    covers instead of double-applying them.

    Gated bridges (ISSUE 8) additionally journal **gated frames**
    (``MAGIC = RTJG``): ``valid`` is the per-row candidate count, the tile
    is the compacted ``[S, Bg]`` candidate tile, and an extra int32[S]
    ``advance`` array carries each row's total logical consumption — the
    journal then stores only the bytes that can win, and replay re-applies
    them through :meth:`ReservoirEngine.sample_gated` bit-exactly.  The
    gate-tile width ``Bg`` is recovered from the frame length, so readers
    need no extra metadata and mixed gated/ungated journals replay in
    order.

    Durability (ISSUE 5 satellite): ``fsync=True`` additionally fsyncs
    every appended frame (and the file+directory on rotation), closing the
    OS/power-crash window the buffered default concedes above — at the
    cost of one fsync per flush, counted through ``sync_cb``.

    Live migration (ISSUE 12) adds **adopt frames** (``MAGIC = RTJA``):
    the payload is a self-describing npz blob carrying the adopted row
    indices plus the packed row state (the destination half of
    :meth:`DeviceStreamBridge.adopt_rows`).  Readers surface it with the
    :data:`ADOPT` sentinel in the ``advance`` slot and the raw payload in
    the ``tile`` slot; replay re-applies it through
    :meth:`ReservoirEngine.adopt_rows` at its original position between
    flushes — the bit-exactness contract extends across migrations.
    """

    _MAGIC = b"RTJL"
    _MAGIC_GATED = b"RTJG"
    _MAGIC_ADOPT = b"RTJA"
    _HEADER = struct.Struct("<4sQI")

    #: Sentinel yielded in the ``advance`` slot of :meth:`read_records` /
    #: :meth:`replay` for adopt frames (the ``tile`` slot then holds the
    #: raw payload bytes) — check it BEFORE the ``advance is not None``
    #: gated-frame test.
    ADOPT = "adopt"

    def __init__(
        self,
        path: str,
        num_streams: int,
        tile_width: int,
        dtype,
        weighted: bool,
        fsync: bool = False,
        sync_cb=None,
    ) -> None:
        self._path = path
        self._S = int(num_streams)
        self._B = int(tile_width)
        self._dtype = np.dtype(dtype)
        self._weighted = weighted
        self._fsync = bool(fsync)
        self._sync_cb = sync_cb
        self._fh = open(path, "ab")

    def _sync(self) -> None:
        reg = _obs.get()  # telemetry (ISSUE 6): the durability tax, alone
        t0 = time.perf_counter() if reg is not None else 0.0
        os.fsync(self._fh.fileno())
        if reg is not None:
            reg.histogram("bridge.journal_fsync_s").observe(
                time.perf_counter() - t0
            )
        if self._sync_cb is not None:
            self._sync_cb()

    def append(
        self,
        seq: int,
        tile: np.ndarray,
        valid: np.ndarray,
        wtile: Optional[np.ndarray],
    ) -> None:
        payload = valid.tobytes() + tile.tobytes()
        if wtile is not None:
            payload += wtile.tobytes()
        self._append_frame(self._MAGIC, seq, payload)

    def append_gated(
        self,
        seq: int,
        tile: np.ndarray,
        nvalid: np.ndarray,
        advance: np.ndarray,
    ) -> None:
        """One gated frame (ISSUE 8): candidate counts + per-row logical
        advance + the compacted ``[S, Bg]`` candidate tile — the journal's
        share of the bytes-elided win."""
        payload = nvalid.tobytes() + advance.tobytes() + tile.tobytes()
        self._append_frame(self._MAGIC_GATED, seq, payload)

    def append_adopt(self, seq: int, payload: bytes) -> None:
        """One adopt frame (ISSUE 12): the packed row-adoption blob from
        :func:`_pack_adopt_payload` — a migration's durable record."""
        self._append_frame(self._MAGIC_ADOPT, seq, payload)

    def _append_frame(self, magic: bytes, seq: int, payload: bytes) -> None:
        self._fh.write(self._HEADER.pack(magic, seq, len(payload)))
        self._fh.write(payload)
        self._fh.write(struct.pack("<I", zlib.crc32(payload)))
        self._fh.flush()
        if self._fsync:
            self._sync()

    def rotate(self) -> None:
        """Drop every record (a fresh checkpoint now covers them)."""
        self._fh.seek(0)
        self._fh.truncate()
        self._fh.flush()
        if self._fsync:
            self._sync()
            # the directory too: the truncation must not resurrect stale
            # records after a power crash once the checkpoint replaced them
            dir_fd = os.open(os.path.dirname(self._path) or ".", os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)
            if self._sync_cb is not None:
                self._sync_cb()

    def close(self) -> None:
        self._fh.close()

    @classmethod
    def read_records(
        cls,
        path: str,
        num_streams: int,
        tile_width: int,
        dtype,
        weighted: bool,
        offset: int = 0,
    ) -> Iterator[
        Tuple[
            int, int, np.ndarray, np.ndarray, Optional[np.ndarray],
            Optional[np.ndarray],
        ]
    ]:
        """Yield ``(end_offset, seq, tile, valid, wtile, advance)`` for
        every intact record starting at byte ``offset``, stopping cleanly
        at the first truncated/corrupt frame.  ``advance`` is None for
        plain frames; for gated frames (ISSUE 8) it is the per-row int32
        logical advance, ``valid`` is the candidate count and ``tile`` the
        compacted ``[S, Bg]`` candidate tile (``Bg`` recovered from the
        frame length).  ``end_offset`` is the byte cursor AFTER the
        yielded record — the resumable-tail API the HA plane's
        :class:`~reservoir_tpu.serve.replica.JournalFollower` polls (a torn
        tail is retried from its start offset on the next poll, never
        treated as permanent corruption: the primary may be mid-append)."""
        dtype = np.dtype(dtype)
        S, B = int(num_streams), int(tile_width)
        n_valid = S * 4
        n_tile = S * B * dtype.itemsize
        expect = n_valid + n_tile + (S * B * 4 if weighted else 0)
        try:
            fh = open(path, "rb")
        except FileNotFoundError:
            return
        with fh:
            fh.seek(offset)
            while True:
                head = fh.read(cls._HEADER.size)
                if len(head) < cls._HEADER.size:
                    return
                magic, seq, plen = cls._HEADER.unpack(head)
                if magic == cls._MAGIC:
                    if plen != expect:
                        return
                elif magic == cls._MAGIC_GATED:
                    # gated frames carry their own width: Bg from plen
                    rem = plen - 2 * n_valid
                    if rem < 0 or rem % (S * dtype.itemsize):
                        return
                elif magic == cls._MAGIC_ADOPT:
                    pass  # self-describing payload; CRC is the only check
                else:
                    return
                payload = fh.read(plen)
                crc = fh.read(4)
                if len(payload) < plen or len(crc) < 4:
                    return
                if zlib.crc32(payload) != struct.unpack("<I", crc)[0]:
                    return
                if magic == cls._MAGIC_ADOPT:
                    # adopt frame (ISSUE 12): raw payload in the tile slot,
                    # the ADOPT sentinel in the advance slot
                    yield fh.tell(), int(seq), payload, None, None, cls.ADOPT
                    continue
                if magic == cls._MAGIC_GATED:
                    bg = (plen - 2 * n_valid) // (S * dtype.itemsize)
                    nvalid = np.frombuffer(payload, np.int32, S).copy()
                    advance = np.frombuffer(
                        payload, np.int32, S, n_valid
                    ).copy()
                    gtile = (
                        np.frombuffer(payload, dtype, S * bg, 2 * n_valid)
                        .reshape(S, bg)
                        .copy()
                    )
                    yield fh.tell(), int(seq), gtile, nvalid, None, advance
                    continue
                valid = np.frombuffer(payload, np.int32, S).copy()
                tile = (
                    np.frombuffer(payload, dtype, S * B, n_valid)
                    .reshape(S, B)
                    .copy()
                )
                wtile = (
                    np.frombuffer(payload, np.float32, S * B, n_valid + n_tile)
                    .reshape(S, B)
                    .copy()
                    if weighted
                    else None
                )
                yield fh.tell(), int(seq), tile, valid, wtile, None

    @classmethod
    def replay(
        cls, path: str, num_streams: int, tile_width: int, dtype, weighted: bool
    ) -> Iterator[
        Tuple[
            int, np.ndarray, np.ndarray, Optional[np.ndarray],
            Optional[np.ndarray],
        ]
    ]:
        """Yield ``(seq, tile, valid, wtile, advance)`` for every intact
        record (``advance`` non-None marks a gated frame), stopping
        cleanly at the first truncated/corrupt one."""
        for _, seq, tile, valid, wtile, advance in cls.read_records(
            path, num_streams, tile_width, dtype, weighted
        ):
            yield seq, tile, valid, wtile, advance


def _pack_adopt_payload(rows: np.ndarray, sub_state: Any) -> bytes:
    """Serialize one row adoption (indices + packed row state) into a
    self-describing npz blob for the RTJA journal frame.  Reuses the
    checkpoint packer, so typed PRNG keys round-trip as key-data words and
    replay reconstructs the exact state pytree the live adopt applied."""
    from ..utils.checkpoint import _pack_state

    arrays, manifest = _pack_state(sub_state)
    bio = io.BytesIO()
    np.savez(
        bio,
        __rows__=np.ascontiguousarray(rows, np.int32),
        __manifest__=np.frombuffer(json.dumps(manifest).encode(), np.uint8),
        **arrays,
    )
    return bio.getvalue()


def _unpack_adopt_payload(payload: bytes) -> Tuple[np.ndarray, Any]:
    """Inverse of :func:`_pack_adopt_payload`: ``(rows, sub_state)``."""
    from ..utils.checkpoint import _unpack_state

    with np.load(io.BytesIO(payload)) as data:
        manifest = json.loads(bytes(data["__manifest__"]).decode())
        rows = np.ascontiguousarray(data["__rows__"], np.int32)
        arrays = {
            k: data[k]
            for k in data.files
            if k not in ("__rows__", "__manifest__")
        }
    return rows, _unpack_state(arrays, manifest)


class DeviceStreamBridge:
    """S independent logical streams feeding S device reservoirs in lockstep.

    Stream ``s`` owns reservoir row ``s``; elements pushed for it buffer into
    row ``s`` of a host-side ``[S, B]`` staging tile.  When any row reaches
    the tile width, the whole tile flushes to the device with per-row
    ``valid`` counts (padding rows are never sampled — the engine's ragged
    contract).  State between flushes lives only on the device.

    Args:
      config: engine config; ``num_reservoirs`` is the stream count.
      key: PRNG key or seed for the engine.
      map_fn / hash_fn: traceable hooks forwarded to the engine.
      reusable: lifecycle switch — reusable bridges allow :meth:`complete`
        followed by more pushes (snapshot semantics).
      pipelined: overlap the host demux with the device flush — the C++
        demux fills tile B while tile A's transfer+dispatch is in flight
        on a worker thread (double buffering; default on).  ``False``
        restores the fully synchronous single-tile path.
      retry_policy: bounded jittered backoff for *transient* flush
        failures (:class:`~reservoir_tpu.errors.TransientDeviceError`) on
        the pipelined worker; defaults to ``RetryPolicy()``.  Fatal errors
        (everything else) surface on first occurrence.
      flush_timeout_s: per-flush watchdog budget (pipelined bridges).  A
        flush exceeding it fails the future with
        :class:`~reservoir_tpu.errors.FlushTimeout` instead of wedging
        callers on a hung device.  ``None`` (default) disables the
        watchdog.
      checkpoint_dir: directory for crash recovery.  When set, the bridge
        snapshots engine state there atomically every ``checkpoint_every``
        flushes (``engine.npz``) and journals each flushed tile to
        ``journal.bin``; :meth:`recover` rebuilds the bridge bit-exactly
        after a crash.  ``None`` (default) disables — the journal copy per
        flush is the durability cost, paid only when asked for.
      checkpoint_every: auto-checkpoint cadence in flushes (default 64).
      durability: journal write discipline when ``checkpoint_dir`` is set.
        ``"buffered"`` (the default) flushes each frame to the OS — a
        process crash loses nothing, an OS/power crash may cost the tail
        record (tolerated by replay).  ``"fsync"`` additionally fsyncs
        every frame (and the directory on rotation), closing that window;
        syncs are counted in ``metrics.journal_syncs`` (zero in buffered
        mode, pinned by ``tests/test_ha.py``).
      faults: per-bridge :class:`~reservoir_tpu.utils.faults.FaultPlane`
        for the ``bridge.*``/``engine.*`` injection sites; ``None`` defers
        to the globally installed plane (``RESERVOIR_FAULTS``) — and when
        neither exists every site is a zero-overhead no-op.
      gated: ingest-side skip-ahead gating (ISSUE 8, default off).  A
        host-side replica of the Algorithm-L skip recursion
        (:mod:`reservoir_tpu.stream.gate`) decides per staged chunk which
        elements can still win; only those candidates (plus fill-phase
        prefixes) are compacted into a small ``[S, gate_tile]`` tile,
        journaled, and dispatched — bit-identical reservoirs to the
        ungated path, a fraction of the bytes.  Eligible in duplicates
        mode with int32 counters on an unmeshed engine; elsewhere
        (weighted/distinct/WIDE/mesh) the flag is INERT — same results,
        no elision (``gate_active`` says which).  Chunks whose candidates
        overflow ``gate_tile`` (the fill phase, mostly) fall back to the
        ungated dispatch for that flush, still bit-exact.
      gate_tile: candidate-tile width ``Bg`` of the gated dispatch path
        (default 64): per gated dispatch, each row ships at most this many
        candidates; acceptance-free flushes coalesce until some row's
        buffer fills or a visibility barrier (:meth:`flush`,
        :meth:`complete`, a serve-plane ``sync``) forces the dispatch.
        ``0`` resolves the width from the persistent autotune cache
        (``kernel="gate"``, populated by ``tools/tpu_block_sweep.py
        --kernel gate``), falling back to 64 when untuned — same for
        ``gate_push_chunk=0`` (fallback 1 Mi).
      gate_push_chunk: slice width of the PRE-staging push fast path
        (default 1 Mi elements): a row-contiguous :meth:`push` chunk is
        gated in slices of this many elements — one vectorized recursion
        eval per slice, candidates gathered straight from the producer's
        array, elided elements never even demuxed.  A slice whose
        candidates exceed ``gate_tile`` (fill phase, early stream)
        automatically reroutes through the staged path; wide slices
        amortize the per-eval call cost, which dominates the gated hot
        path once everything else is elided.
      device: pin the engine (state + every staged flush) to one device
        (ISSUE 12, per-shard placement).  Mutually exclusive with
        ``mesh``; ``None`` keeps jax's default placement.
    """

    def __init__(
        self,
        config: SamplerConfig,
        key: Union[int, Any, None] = None,
        map_fn: Optional[Any] = None,
        hash_fn: Optional[Any] = None,
        reusable: bool = False,
        mesh: Optional[Any] = None,
        pipelined: bool = True,
        *,
        retry_policy: Optional[RetryPolicy] = None,
        flush_timeout_s: Optional[float] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 64,
        durability: str = "buffered",
        faults: Optional[Any] = None,
        gated: bool = False,
        gate_tile: int = 64,
        gate_push_chunk: int = 1 << 20,
        device: Optional[Any] = None,
        _engine: Optional[ReservoirEngine] = None,
    ) -> None:
        if durability not in ("buffered", "fsync"):
            raise ValueError(
                f"durability must be 'buffered' or 'fsync', got {durability!r}"
            )
        self._config = config
        self._faults = faults
        # _engine is the recovery path (recover() restores it from the
        # checkpoint); normal construction builds a fresh one.  device=
        # (ISSUE 12) pins the engine to one chip — the per-shard placement
        # that gives the collective merge real interconnect to cross; a
        # recovered engine is pinned after the fact (placement is
        # process-local, never persisted in the checkpoint).
        self._engine = _engine if _engine is not None else ReservoirEngine(
            config,
            key=key,
            map_fn=map_fn,
            hash_fn=hash_fn,
            reusable=reusable,
            mesh=mesh,
            faults=faults,
            device=device,
        )
        if _engine is not None and device is not None:
            self._engine._pin_device(device)
        self._reusable = reusable
        S, B = config.num_reservoirs, config.tile_size
        # staging is native (C++ demux, _native/staging_buffer.cc) when the
        # helper library is available, numpy otherwise — same semantics
        self._staging = NativeStaging(
            S, B, np.dtype(config.element_dtype), weighted=config.weighted
        )
        n_bufs = 2 if pipelined else 1
        dtype = np.dtype(config.element_dtype)
        self._tiles = [np.zeros((S, B), dtype) for _ in range(n_bufs)]
        self._wtiles = (
            [np.ones((S, B), np.float32) for _ in range(n_bufs)]
            if config.weighted
            else None
        )
        # Pre-fault the host tiles: numpy's large zeros are lazily mapped,
        # so without this the first flush cycle's demux page-faults on
        # every 4 KiB page of a ~100 MB tile (measured ~2x demux slowdown
        # at config-5 scale).  One write per page at construction moves
        # that cost out of the hot path.
        # (_wtiles need no pre-fault: np.ones writes every element, which
        # already faults every page at allocation)
        page = 4096
        for t in self._tiles:
            t.reshape(-1).view(np.uint8)[::page] = 0
        self._valids = [np.zeros(S, np.int32) for _ in range(n_bufs)]
        self._buf = 0
        # Zero-copy flush mode (r4 config-5 host-path work): the demux
        # scatters straight into the active flush tile, so a flush is a
        # fill-count read + buffer swap instead of an [S, B] drain copy
        # (134 MB per flush at config-5 scale).  Pipeline depth drops to 1
        # permit: reserve() then guarantees the tile being attached next is
        # no longer read by the worker — same steady-state overlap (demux
        # of tile B rides tile A's transfer+dispatch), no copy.
        self._zero_copy = self._staging.supports_attach()
        if self._zero_copy:
            self._staging.attach(
                self._tiles[0],
                self._wtiles[0] if self._wtiles is not None else None,
            )
        # ingest-side skip-ahead gate (ISSUE 8): constructed only when
        # requested AND eligible — an inert gate costs nothing, an active
        # one evaluates the host replica per flush and coalesces candidates.
        # 0 = resolve from the persistent autotune cache (kernel="gate",
        # ISSUE 12 satellite) with the historical defaults as fallback, so
        # a sweep winner becomes the live geometry without a code change.
        if gate_tile == 0 or gate_push_chunk == 0:
            geo = self._gate_geometry(B, dtype)
            if gate_tile == 0:
                gate_tile = (
                    geo.gate_tile if geo is not None and geo.gate_tile else 64
                )
            if gate_push_chunk == 0:
                gate_push_chunk = (
                    geo.gate_push_chunk
                    if geo is not None and geo.gate_push_chunk
                    else 1 << 20
                )
        self._gate: Optional[SkipGate] = None
        self._gate_reason: Optional[str] = None
        if gated:
            self._gate_reason = gate_ineligible_reason(config)
            if self._gate_reason is None:
                self._gate = SkipGate(
                    S, config.max_sample_size, B, dtype, cap=gate_tile
                )
        self._gate_tile = int(gate_tile)
        self._gate_push_chunk = max(1, int(gate_push_chunk))
        self._gated_requested = bool(gated)
        self._future: Future = Future()
        self._metrics = BridgeMetrics()
        self._metrics.demux_threads = self._staging.threads()
        self._retry_policy = (
            retry_policy if retry_policy is not None else RetryPolicy()
        )
        self._pipeline = (
            _FlushPipeline(
                self._dispatch_flush,
                n_tiles=1 if self._zero_copy else 2,
                retry_policy=self._retry_policy,
                watchdog_s=flush_timeout_s,
                fail_cb=self.fail,
                metrics=self._metrics,
            )
            if pipelined
            else None
        )
        # ------------------------------------------- crash recovery plane
        self._ckpt_dir = checkpoint_dir
        self._ckpt_every = max(1, int(checkpoint_every))
        self._flush_seq = 0  # flushes journaled/checkpointed so far
        self._journal: Optional[_FlushJournal] = None
        self._ckpt_failed_logged = False
        self._durability = durability
        # HA fencing (ISSUE 5): the bridge is admitted at the epoch
        # persisted in the checkpoint dir at construction; a later epoch
        # bump (StandbyReplica.promote on another process/object) fences
        # every subsequent flush/checkpoint with FencedError
        self._epoch = 0
        self._fence_cache: Tuple[Optional[Tuple[int, int]], int] = (None, 0)
        if checkpoint_dir is not None:
            os.makedirs(checkpoint_dir, exist_ok=True)
            self._epoch = read_epoch(checkpoint_dir)
            self._journal = _FlushJournal(
                os.path.join(checkpoint_dir, "journal.bin"),
                S,
                B,
                dtype,
                config.weighted,
                fsync=durability == "fsync",
                sync_cb=self._count_journal_sync,
            )
            if _engine is None:
                # seq-0 anchor: recovery must be possible from flush one
                # (it carries the config and the key-derived initial
                # state), and it keeps recovery possible even if every
                # later periodic checkpoint write fails — the journal
                # then simply grows from here
                self._save_snapshot()

    def _gate_geometry(self, width: int, dtype):
        """Tuned gate geometry for this shape from the persistent autotune
        cache (``kernel="gate"`` — ``tools/tpu_block_sweep.py --kernel
        gate`` populates it), or None: callers then keep the historical
        defaults, so untuned devices behave exactly as before."""
        import jax

        from ..ops import autotune

        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:  # backend init failure surfaces elsewhere
            return None
        return autotune.lookup(
            device_kind,
            self._config.num_reservoirs,
            self._config.max_sample_size,
            width,
            dtype,
            kernel="gate",
        )

    # ------------------------------------------------------------ properties

    @property
    def num_streams(self) -> int:
        return self._config.num_reservoirs

    @property
    def engine(self) -> ReservoirEngine:
        """The bridge's engine.  Read-side consumers (the serving plane's
        snapshot path, recovery hooks) share the bridge's single-writer
        contract: call :meth:`drain_barrier` before touching engine state
        while a pipelined flush may be in flight."""
        return self._engine

    @property
    def device(self) -> Optional[Any]:
        """The device this bridge's engine is pinned to (``None`` when
        unpinned — jax's default placement)."""
        return self._engine.device

    @property
    def sample(self) -> Future:
        """The bridge's materialized value: future of the per-stream samples
        (list of ``S`` arrays), completed by the tri-state protocol."""
        return self._future

    @property
    def metrics(self) -> BridgeMetrics:
        return self._metrics

    @property
    def gate_active(self) -> bool:
        """Whether the ingest-side skip gate is live (``gated=True`` AND
        the config is eligible — see :attr:`gate_inert_reason`)."""
        return self._gate is not None

    @property
    def gate_inert_reason(self) -> Optional[str]:
        """Why a requested gate is inert (None when active or never
        requested) — ``weighted``/``distinct``/WIDE/meshed configs take
        the ungated path with identical results."""
        return self._gate_reason

    @property
    def checkpoint_every(self) -> int:
        """Live auto-checkpoint cadence in flushes (see
        :meth:`set_checkpoint_every`)."""
        return self._ckpt_every

    @property
    def gate_push_chunk(self) -> int:
        """Live slice width of the gated push fast path (see
        :meth:`set_gate_push_chunk`)."""
        return self._gate_push_chunk

    def set_checkpoint_every(self, n: int) -> None:
        """Retune the auto-checkpoint cadence on a LIVE bridge (the serve
        autotuner's write path, ISSUE 14).  Takes effect from the next
        flush; durability is unaffected — every flush is journaled
        regardless, the cadence only sets how far recovery replays."""
        self._ckpt_every = max(1, int(n))

    def set_gate_push_chunk(self, n: int) -> None:
        """Retune the gated push slice width on a LIVE bridge (ISSUE 14).
        Takes effect from the next push; a no-op path on ungated bridges
        (the field exists either way so live setters work on any bridge)."""
        self._gate_push_chunk = max(1, int(n))

    @property
    def is_open(self) -> bool:
        return self._engine.is_open and not self._future.done()

    def _check_open(self) -> None:
        if self._future.done():
            raise SamplerClosedError("this bridge has completed or failed")
        self._engine._check_open()

    # --------------------------------------------------------------- pushing

    def push(
        self,
        stream: int,
        elements: Any,
        weights: Optional[Any] = None,
    ) -> None:
        """Buffer one element or a 1-D chunk for logical stream ``stream``;
        flushes automatically whenever the stream's row fills.  Shape/dtype
        errors name the offending stream — at 65k streams a bare
        "weights must match" is undebuggable."""
        self._check_open()
        _faults.fire("bridge.demux", self._faults)
        self._metrics.start()
        if not 0 <= int(stream) < self.num_streams:
            raise ValueError(
                f"stream {int(stream)} out of range [0, {self.num_streams})"
            )
        try:
            arr = np.atleast_1d(np.asarray(elements, self._tiles[0].dtype))
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"stream {int(stream)}: elements not convertible to "
                f"{self._tiles[0].dtype}: {e}"
            ) from None
        warr = self._check_weights(arr, weights, stream=int(stream))
        n = arr.shape[0]
        if self._gate is not None and warr is None:
            # pre-staging fast path (ISSUE 8): a row-contiguous chunk is
            # gated BEFORE any staging copy — elided elements never cost
            # a demux byte, let alone a DMA one
            self._gate_push(int(stream), arr)
            self._metrics.elements += n
            return
        off = 0
        while off < n:
            t0 = time.perf_counter()
            took = self._staging.push_chunk(
                stream,
                arr[off:],
                warr[off:] if warr is not None else None,
            )
            self._metrics.demux_s += time.perf_counter() - t0
            off += took
            if off < n or self._staging.row_full(stream):
                # internal row-full flush: gated bridges may coalesce it
                # into the candidate buffer (no dispatch) — the public
                # flush()/complete() barriers force the dispatch
                self._flush_staging()
        self._metrics.elements += n

    def push_interleaved(self, streams: Any, elements: Any,
                         weights: Optional[Any] = None) -> None:
        """Demux an interleaved feed of ``(stream_id, element)`` pairs — the
        multi-producer wire format.  The scatter runs in the native staging
        helper when available (C-speed pointer walk; numpy fallback
        otherwise), flushing whenever a row fills mid-batch."""
        self._check_open()
        _faults.fire("bridge.demux", self._faults)
        self._metrics.start()
        # conversions up front so the resume-loop slices stay no-copy; shape
        # and range validation belongs to NativeStaging (single owner)
        streams = np.ascontiguousarray(streams, np.int32)
        arr = np.ascontiguousarray(elements, self._tiles[0].dtype)
        warr = self._check_weights(arr, weights)
        off = 0
        n = arr.shape[0]
        while off < n:
            t0 = time.perf_counter()
            took = self._staging.push_interleaved(
                streams[off:],
                arr[off:],
                warr[off:] if warr is not None else None,
            )
            self._metrics.demux_s += time.perf_counter() - t0
            off += took
            if off < n:
                self._flush_staging()
        self._metrics.elements += n

    def _check_weights(self, arr, weights, stream: Optional[int] = None):
        # ``stream`` (the single-stream push path) prefixes every error so
        # the failing row is identifiable in a many-stream feed
        where = "" if stream is None else f"stream {stream}: "
        if self._wtiles is not None:
            if weights is None:
                raise ValueError(f"{where}weighted bridge requires weights")
            warr = np.atleast_1d(np.ascontiguousarray(weights, np.float32))
            if warr.shape != arr.shape:
                raise ValueError(
                    f"{where}weights must match elements shape "
                    f"{arr.shape}, got {warr.shape}"
                )
            if not np.all(warr >= 0):
                bad = int(np.argmax(warr < 0))
                raise ValueError(
                    f"{where}weights must be nonnegative "
                    f"(weights[{bad}] = {warr[bad]})"
                )
            return warr
        if weights is not None:
            raise ValueError(
                f"{where}weights are only meaningful with weighted=True"
            )
        return None

    def push_tile(self, tile: Any, valid: Optional[Any] = None,
                  weights: Optional[Any] = None) -> None:
        """Bypass buffering: dispatch a pre-assembled ``[S, B]`` tile straight
        to the device (the zero-copy fast path for array-shaped sources)."""
        self._check_open()
        self._check_fence()
        self._metrics.start()
        if self._gate is not None:
            # pre-assembled tiles bypass the gate: ship the coalesced
            # candidate buffer first (stream order), then mark the host
            # replica stale — it re-pulls before the next gated eval
            self._dispatch_gated_pending()
            self._gate.mark_dirty()
        self.drain_barrier()  # engine is single-writer: wait out the worker
        tile = np.asarray(tile)
        if self._journal is not None:
            # journal replay re-applies the exact bytes; a dtype the
            # staging tiles don't carry could not round-trip bit-exactly
            if tile.dtype != self._tiles[0].dtype:
                raise ValueError(
                    f"an auto-checkpointing bridge requires push_tile tiles "
                    f"of the configured element dtype "
                    f"{self._tiles[0].dtype}, got {tile.dtype}"
                )
            valid_arr = (
                np.full(tile.shape[0], tile.shape[1], np.int32)
                if valid is None
                else np.ascontiguousarray(valid, np.int32)
            )
            wtile_arr = (
                np.ascontiguousarray(weights, np.float32)
                if self._wtiles is not None
                else None
            )
            self._flush_seq += 1
            self._journal_append(
                self._flush_seq,
                np.ascontiguousarray(tile),
                valid_arr,
                wtile_arr,
            )
            # normalize the live call to the journaled form (explicit
            # valid counts) so replay re-executes the exact same engine
            # code path — the bit-exactness contract of recover()
            valid = valid_arr
        with trace_span("reservoir_bridge_flush"):
            self._engine.sample(tile, valid=valid, weights=weights)
        n = int(tile.shape[1]) * tile.shape[0] if valid is None else int(
            np.sum(np.asarray(valid))
        )
        self._metrics.elements += n
        self._metrics.flushed_elements += n
        self._metrics.flushes += 1
        self._metrics.demotions = self._engine.demotions
        self._maybe_checkpoint()

    def adopt_rows(self, rows: Any, sub_state: Any) -> None:
        """Adopt exported reservoir rows into this bridge's engine — the
        destination half of a live migration (ISSUE 12).

        ``sub_state`` is the pytree returned by the source engine's
        :meth:`~reservoir_tpu.engine.ReservoirEngine.export_rows`; leaves
        may still live on the source device (the engine re-commits them
        onto this bridge's device).  The adopt is fence-checked, runs
        under the single-writer slot (gated candidate buffers dispatch
        first, in-flight flushes drain), consumes one flush sequence
        number, and — on journaling bridges — is durably recorded as one
        RTJA frame BEFORE it mutates the engine, so :meth:`recover` and a
        :class:`~reservoir_tpu.serve.replica.StandbyReplica` replay it
        bit-exactly at its original position between flushes.
        """
        self._check_open()
        self._check_fence()
        if self._gate is not None:
            # stream order: everything the gate buffered precedes the
            # adopt; the replica re-pulls before the next gated eval
            self._dispatch_gated_pending()
            self._gate.mark_dirty()
        self.drain_barrier()  # engine is single-writer
        self._flush_seq += 1
        if self._journal is not None:
            reg = _obs.get()
            t0 = time.perf_counter() if reg is not None else 0.0
            with trace_span("reservoir_journal_append"):
                self._journal.append_adopt(
                    self._flush_seq, _pack_adopt_payload(rows, sub_state)
                )
            if reg is not None:
                reg.histogram("bridge.journal_append_s").observe(
                    time.perf_counter() - t0
                )
        with trace_span("reservoir_bridge_adopt"):
            self._engine.adopt_rows(rows, sub_state)
        self._metrics.flushes += 1
        self._maybe_checkpoint()

    def _dispatch_flush(self, tile, valid, wtile, advance=None) -> None:
        """The device half of a flush (worker thread when pipelined).

        The ``bridge.dispatch`` fault site fires BEFORE the engine update:
        an injected transient failure is retried by the pipeline worker
        and, because engine state only advances on a successful update,
        the retried stream completes bit-identical to a clean run.

        ``advance`` non-None marks a GATED flush (ISSUE 8): ``tile`` is
        the compacted candidate tile, ``valid`` the per-row candidate
        counts, and each row additionally advances by ``advance[r]``
        logical elements — :meth:`ReservoirEngine.sample_gated`.
        """
        _faults.fire("bridge.dispatch", self._faults)
        t0 = time.perf_counter()
        tr = _ctrace.get()
        cm = (
            tr.span(
                "bridge.dispatch",
                key=self._flush_seq,
                flush_seq=self._flush_seq,
                gated=advance is not None,
            )
            if tr is not None
            else contextlib.nullcontext()
        )
        with cm, trace_span("reservoir_bridge_flush"):
            if advance is not None:
                self._engine.sample_gated(tile, valid, advance)
            elif wtile is not None:
                # stale weight-slots past each row's valid count hold old
                # (nonnegative) weights; the valid mask keeps them out of
                # sampling and user weights are never rewritten (the r1
                # 1e-30 clamp silently mutated legitimate denormal weights)
                self._engine.sample(tile, valid=valid, weights=wtile)
            else:
                self._engine.sample(tile, valid=valid)
        dt = time.perf_counter() - t0
        self._metrics.dispatch_s += dt
        reg = _obs.get()  # telemetry (ISSUE 6): one load + None test when off
        if reg is not None:
            reg.histogram("bridge.flush_s").observe(dt)
            reg.histogram("bridge.flush_bytes", lo=1.0, hi=1e12).observe(
                tile.nbytes + (wtile.nbytes if wtile is not None else 0)
            )
        # surface graceful degradation: a mid-stream Pallas->XLA demotion
        # happens inside the engine; mirror it onto the bridge counters
        self._metrics.demotions = self._engine.demotions

    def flush(self) -> None:
        """Dispatch buffered elements (ragged tile) to the device — the
        public visibility barrier: after it returns (plus
        :meth:`drain_barrier`), every pushed element has been dispatched,
        including a gated bridge's coalesced candidate buffer.

        Zero-copy mode (the default): the demux already scattered into the
        active host tile, so the flush reads the fill counts, hands the
        tile to the worker, and re-points the demux at the other tile —
        blocking only while that tile's previous flush is still in flight.
        Copy mode (stale native lib): drain-copies staging into the idle
        tile first.  Either way the next demux overlaps this flush's
        transfer+dispatch when pipelined.
        """
        self._flush_staging()
        if self._gate is not None:
            self._dispatch_gated_pending()

    def _flush_staging(self) -> None:
        """One staging flush (the internal row-full path): gated bridges
        may absorb it into the candidate buffer without any dispatch."""
        # fence BEFORE any staging drain or journal append: a fenced
        # primary must fail fast with nothing mutated (ISSUE 5)
        self._check_fence()
        if self._zero_copy:
            i = self._buf
            tile, valid = self._tiles[i], self._valids[i]
            wtile = self._wtiles[i] if self._wtiles is not None else None
            t0 = time.perf_counter()
            total = self._staging.take(valid)
            self._metrics.drain_s += time.perf_counter() - t0
            if total == 0:
                return
            if self._gate is not None and self._gate_flush(tile, valid):
                # candidates buffered (possibly dispatched); the staging
                # tile was fully consumed by the gather — keep demuxing
                # into it, no swap needed
                return
            # journal BEFORE handing the tile to the worker: the producer
            # still owns it here (the worker reads the other tile), and a
            # dispatch that later fails fatally was still journaled — so
            # recover() replays it and no flushed element is ever lost
            self._flush_seq += 1
            if self._journal is not None:
                self._journal_append(self._flush_seq, tile, valid, wtile)
            if self._pipeline is not None:
                # wait until the OTHER tile's previous flight is done,
                # then swap the demux onto it
                tr = _ctrace.get()
                qcm = (
                    tr.span(
                        "bridge.queue",
                        key=self._flush_seq,
                        flush_seq=self._flush_seq,
                    )
                    if tr is not None
                    else contextlib.nullcontext()
                )
                with qcm:
                    self._pipeline.reserve()
                self._pipeline.submit(tile, valid, wtile)
                self._buf = 1 - i
                self._staging.attach(
                    self._tiles[self._buf],
                    self._wtiles[self._buf]
                    if self._wtiles is not None
                    else None,
                )
            else:
                self._dispatch_flush(tile, valid, wtile)
            self._metrics.flushes += 1
            self._metrics.flushed_elements += total
            self._maybe_checkpoint()
            return
        if self._pipeline is not None:
            # block until the tile we are about to drain into is truly
            # free (the worker may still be reading it)
            tr = _ctrace.get()
            qcm = (
                tr.span(
                    "bridge.queue",
                    key=self._flush_seq,
                    flush_seq=self._flush_seq,
                )
                if tr is not None
                else contextlib.nullcontext()
            )
            with qcm:
                self._pipeline.reserve()
        i = self._buf
        tile, valid = self._tiles[i], self._valids[i]
        wtile = self._wtiles[i] if self._wtiles is not None else None
        t0 = time.perf_counter()
        total = self._staging.drain(tile, valid, wtile)
        self._metrics.drain_s += time.perf_counter() - t0
        if total == 0:
            if self._pipeline is not None:
                self._pipeline.release()
            return
        if self._gate is not None:
            if self._pipeline is not None:
                # the gate path manages its own reservations (a gated
                # dispatch reserves inside _dispatch_gated_pending)
                self._pipeline.release()
            if self._gate_flush(tile, valid):
                return
            if self._pipeline is not None:
                self._pipeline.reserve()  # re-acquire for the fallback
        self._flush_seq += 1
        if self._journal is not None:
            self._journal_append(self._flush_seq, tile, valid, wtile)
        if self._pipeline is not None:
            self._pipeline.submit(tile, valid, wtile)
            self._buf = 1 - i  # demux continues into the other tile
        else:
            self._dispatch_flush(tile, valid, wtile)
        self._metrics.flushes += 1
        self._metrics.flushed_elements += total
        self._maybe_checkpoint()

    # ------------------------------------------------------- skip-ahead gate

    def _gate_push(self, stream: int, arr: np.ndarray) -> None:
        """Gate a row-contiguous pushed chunk BEFORE staging (ISSUE 8).

        The chunk is evaluated in ``gate_push_chunk``-element slices: one
        vectorized recursion eval decides each slice's candidates, which
        are gathered straight from the producer's array into the
        coalescing buffer — elided elements are never demuxed, staged,
        journaled or DMA'd.  Candidate-dense slices (the fill phase,
        early stream) are routed to the ordinary staged path, whose
        flushes re-evaluate tile-by-tile; row order is preserved because
        the fast path only runs while the row's staging is empty."""
        gate = self._gate
        if gate.stale(self._engine):
            self.drain_barrier()
            gate.resync(self._engine)
        m = self._metrics
        n = int(arr.shape[0])
        off = 0
        while off < n:
            if self._staging.fill(stream):
                # staged residue (a fallback slice's partial row): keep
                # this slice on the staged path so the row stays ordered
                off += self._push_staged(stream, arr[off:])
                continue
            self._check_fence()
            take = min(n - off, self._gate_push_chunk)
            chunk = arr[off : off + take]
            reg = _obs.get()
            tr = _ctrace.get()
            gcm = (
                tr.span("gate.eval", stream=stream)
                if tr is not None
                else contextlib.nullcontext()
            )
            t0 = time.perf_counter()
            with gcm, trace_span("reservoir_gate_eval"):
                ev = gate.evaluate_row(stream, take)
            dt = time.perf_counter() - t0
            m.gate_eval_s += dt
            if reg is not None:
                reg.histogram("gate.eval_s").observe(dt)
            if int(ev.n_cand[stream]) > gate.cap:
                # candidate-dense slice: NOT committed — the staged
                # flushes re-run the recursion in tile pieces and commit
                off += self._push_staged(stream, chunk)
                continue
            if not gate.fits_row(stream, ev):
                self._dispatch_gated_pending()
            gate.commit(ev)
            elided = gate.append_row(stream, chunk, ev)
            m.gate_buffered_flushes += 1
            m.gate_bytes_elided += elided * arr.itemsize
            if reg is not None:
                reg.counter("gate.bytes_elided").inc(elided * arr.itemsize)
            if gate.advance_high():
                self._dispatch_gated_pending()
            off += take

    def _push_staged(self, stream: int, arr: np.ndarray) -> int:
        """One staged-path step of a single-row push: stage what fits,
        flush on row-full (the pre-gate push loop's body); returns the
        element count consumed."""
        t0 = time.perf_counter()
        took = self._staging.push_chunk(stream, arr, None)
        self._metrics.demux_s += time.perf_counter() - t0
        if took < arr.shape[0] or self._staging.row_full(stream):
            self._flush_staging()
        return took

    def _gate_flush(self, tile: np.ndarray, valid: np.ndarray) -> bool:
        """Gate one staged chunk (ISSUE 8).  Returns True when the chunk
        was fully absorbed by the gate (candidates buffered, possibly a
        gated dispatch); False when the caller must take the ungated
        fallback path for THIS tile (candidate overflow — fill phase,
        mostly).  Either way the host replica has already advanced over
        the chunk, so fallback tiles stay bit-consistent."""
        gate = self._gate
        if gate.stale(self._engine):
            # the engine was mutated outside the gated path (recovery
            # replay, push_tile, serve-plane row resets): re-pull the
            # replica under the single-writer slot.  Every sanctioned
            # mutation path dispatches the pending buffer BEFORE mutating
            # (push_tile does, serve syncs before reset_rows), so a
            # pending buffer here is a single-writer contract violation —
            # resync() refuses it rather than reorder the stream.
            self.drain_barrier()
            gate.resync(self._engine)
        m = self._metrics
        reg = _obs.get()
        tr = _ctrace.get()
        gcm = (
            tr.span("gate.eval")
            if tr is not None
            else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        with gcm, trace_span("reservoir_gate_eval"):
            ev = gate.evaluate(valid)
        dt = time.perf_counter() - t0
        m.gate_eval_s += dt
        if reg is not None:
            reg.histogram("gate.eval_s").observe(dt)
        # both branches consume the chunk at THIS granularity (buffered
        # gated or shipped whole), so the replica advances either way
        gate.commit(ev)
        if ev.fallback:
            # this chunk's candidates exceed the gate tile: ship it whole,
            # but dispatch the buffered advance FIRST (stream order)
            self._dispatch_gated_pending()
            shipped = int(np.asarray(valid).sum()) * tile.itemsize
            m.gate_bytes_shipped += shipped
            if reg is not None:
                reg.counter("gate.bytes_shipped").inc(shipped)
                self._observe_skip_frac(reg)
            return False
        if not gate.fits(ev):
            self._dispatch_gated_pending()
        elided = gate.append(tile, valid, ev)
        m.gate_buffered_flushes += 1
        m.gate_bytes_elided += elided * tile.itemsize
        if reg is not None:
            reg.counter("gate.bytes_elided").inc(elided * tile.itemsize)
        if gate.advance_high():
            self._dispatch_gated_pending()
        return True

    def _dispatch_gated_pending(self) -> None:
        """Dispatch the gate's coalesced candidate buffer as one gated
        flush (journaled like any other flush; replay uses
        :meth:`ReservoirEngine.sample_gated`).  No-op when empty."""
        gate = self._gate
        if gate is None or not gate.pending():
            return
        self._check_fence()
        gtile, nvalid, advance, total_adv = gate.take()
        self._flush_seq += 1
        tr = _ctrace.get()
        if self._journal is not None:
            reg = _obs.get()
            t0 = time.perf_counter() if reg is not None else 0.0
            jcm = (
                tr.span(
                    "bridge.journal",
                    key=self._flush_seq,
                    flush_seq=self._flush_seq,
                )
                if tr is not None
                else contextlib.nullcontext()
            )
            with jcm, trace_span("reservoir_journal_append"):
                self._journal.append_gated(
                    self._flush_seq, gtile, nvalid, advance
                )
            if reg is not None:
                reg.histogram("bridge.journal_append_s").observe(
                    time.perf_counter() - t0
                )
        if self._pipeline is not None:
            qcm = (
                tr.span(
                    "bridge.queue",
                    key=self._flush_seq,
                    flush_seq=self._flush_seq,
                )
                if tr is not None
                else contextlib.nullcontext()
            )
            with qcm:
                self._pipeline.reserve()
            self._pipeline.submit(gtile, nvalid, None, advance)
        else:
            self._dispatch_flush(gtile, nvalid, None, advance)
        m = self._metrics
        m.flushes += 1
        m.gated_dispatches += 1
        # the folded advance becomes durable here: journal (when enabled)
        # now covers these elements, so they count as flushed
        m.flushed_elements += total_adv
        shipped = gtile.nbytes + nvalid.nbytes + advance.nbytes
        m.gate_bytes_shipped += shipped
        reg = _obs.get()
        if reg is not None:
            reg.counter("gate.bytes_shipped").inc(shipped)
            self._observe_skip_frac(reg)
        self._maybe_checkpoint()

    def _observe_skip_frac(self, reg) -> None:
        m = self._metrics
        denom = m.gate_bytes_shipped + m.gate_bytes_elided
        if denom:
            reg.gauge("gate.skip_frac").set(m.gate_bytes_elided / denom)

    def _journal_append(self, seq, tile, valid, wtile) -> None:
        """Journal one flushed tile — traced (``reservoir_journal_append``
        shows up in Perfetto next to the flush span) and, when telemetry
        is enabled, timed into the ``bridge.journal_append_s`` histogram."""
        reg = _obs.get()
        tr = _ctrace.get()
        t0 = time.perf_counter() if reg is not None else 0.0
        jcm = (
            tr.span("bridge.journal", key=seq, flush_seq=seq)
            if tr is not None
            else contextlib.nullcontext()
        )
        with jcm, trace_span("reservoir_journal_append"):
            self._journal.append(seq, tile, valid, wtile)
        if reg is not None:
            reg.histogram("bridge.journal_append_s").observe(
                time.perf_counter() - t0
            )

    def drain_barrier(self) -> None:
        """Wait for any in-flight pipelined flush (re-raising its error)."""
        if self._pipeline is not None:
            self._pipeline.join()

    def flush_would_block(self) -> bool:
        """True when a :meth:`flush` right now would block waiting for the
        in-flight pipeline (no free host tile).  Non-blocking — the serving
        plane's admission-control probe (reject-with-retry-after instead of
        queuing unboundedly).  Always False on unpipelined bridges."""
        return self._pipeline is not None and self._pipeline.would_block()

    # -------------------------------------------------------- crash recovery

    @property
    def flushed_seq(self) -> int:
        """Durable flush watermark: every flush with sequence number
        ``<= flushed_seq`` is covered by the checkpoint+journal pair and
        survives a crash.  Producers resume pushing from here after
        :meth:`recover` (elements staged but never flushed are not
        recoverable — they never left the producer's custody)."""
        return self._flush_seq

    @property
    def epoch(self) -> int:
        """The primary epoch this bridge was admitted at (0 when it does
        not checkpoint).  A newer epoch persisted in the checkpoint dir —
        a failover promotion — fences this bridge: its next flush or
        checkpoint raises :class:`~reservoir_tpu.errors.FencedError`
        without touching the journal."""
        return self._epoch

    def _count_journal_sync(self) -> None:
        self._metrics.journal_syncs += 1

    def _current_epoch(self) -> int:
        """The persisted epoch, stat-cached so the per-flush fence check
        costs one stat when nothing changed (no read, no parse)."""
        path = os.path.join(self._ckpt_dir, "epoch.json")
        try:
            st = os.stat(path)
        except FileNotFoundError:
            return 0
        key = (st.st_mtime_ns, st.st_size)
        if self._fence_cache[0] != key:
            self._fence_cache = (key, read_epoch(self._ckpt_dir))
        return self._fence_cache[1]

    def _check_fence(self) -> None:
        """Refuse durable writes once a newer primary epoch is persisted
        (split-brain protection): raises BEFORE any journal/staging
        mutation, so a fenced primary can never double-write a flush the
        promoted primary also owns."""
        if self._journal is None:
            return
        current = self._current_epoch()
        if current > self._epoch:
            self._metrics.fenced_writes += 1
            _obs.emit(
                "bridge.fenced",
                site="bridge.flush",
                epoch=current,
                own_epoch=self._epoch,
                flush_seq=self._flush_seq,
            )
            tr = _ctrace.get()
            if tr is not None:
                tr.point(
                    "bridge.fenced",
                    epoch=current,
                    own_epoch=self._epoch,
                    flush_seq=self._flush_seq,
                )
            fl = _flight.get()
            if fl is not None:
                fl.trigger(
                    "fenced",
                    epoch=current,
                    own_epoch=self._epoch,
                    flush_seq=self._flush_seq,
                    checkpoint_dir=self._ckpt_dir,
                )
            raise FencedError(
                f"bridge fenced: checkpoint dir {self._ckpt_dir!r} is at "
                f"primary epoch {current}, this bridge was admitted at "
                f"{self._epoch} — a standby was promoted; stop writing",
                observed_epoch=current,
                own_epoch=self._epoch,
            )

    def _attach_journal(
        self,
        checkpoint_dir: str,
        *,
        checkpoint_every: int = 64,
        durability: str = "buffered",
        epoch: Optional[int] = None,
    ) -> None:
        """Adopt ``checkpoint_dir`` as this bridge's durability plane — the
        standby-promotion path (:meth:`StandbyReplica.promote`): opens the
        journal for append WITHOUT the fresh-bridge seq-0 anchor (the
        existing checkpoint+journal already cover ``flushed_seq``) and
        admits the bridge at ``epoch`` (default: the persisted one)."""
        if self._journal is not None:
            raise ValueError("this bridge already journals")
        os.makedirs(checkpoint_dir, exist_ok=True)
        self._ckpt_dir = checkpoint_dir
        self._ckpt_every = max(1, int(checkpoint_every))
        self._durability = durability
        self._epoch = read_epoch(checkpoint_dir) if epoch is None else epoch
        self._fence_cache = (None, 0)
        self._journal = _FlushJournal(
            os.path.join(checkpoint_dir, "journal.bin"),
            self._config.num_reservoirs,
            self._config.tile_size,
            np.dtype(self._config.element_dtype),
            self._config.weighted,
            fsync=durability == "fsync",
            sync_cb=self._count_journal_sync,
        )

    def _save_snapshot(self) -> None:
        """Checkpoint engine state covering every flush ``<= _flush_seq``
        (atomic: temp file + rename inside ``utils.checkpoint``), then drop
        the journal records the snapshot covers.  Both crash windows are
        safe: a crash mid-write leaves the previous checkpoint intact, a
        crash between write and rotation leaves only records recovery
        filters out by sequence number."""
        from ..utils.checkpoint import save_engine

        self._check_fence()
        save_engine(
            os.path.join(self._ckpt_dir, "engine.npz"),
            self._engine,
            metadata={
                "bridge": {
                    "seq": self._flush_seq,
                    "epoch": self._epoch,
                    "reusable": self._reusable,
                    "pipelined": self._pipeline is not None,
                    "checkpoint_every": self._ckpt_every,
                    "durability": self._durability,
                    "elements": self._metrics.elements,
                    "flushed_elements": self._metrics.flushed_elements,
                    "gated": self._gated_requested,
                    "gate_tile": self._gate_tile,
                }
            },
        )
        self._journal.rotate()
        self._metrics.checkpoints += 1
        _obs.emit(
            "bridge.checkpoint",
            site="checkpoint.write",
            flush_seq=self._flush_seq,
            epoch=self._epoch,
        )

    def _maybe_checkpoint(self) -> None:
        if self._journal is None or self._flush_seq % self._ckpt_every:
            return
        # the barrier runs OUTSIDE the degradation guard: a worker error it
        # re-raises is a stream failure, not a checkpoint failure
        self.drain_barrier()
        try:
            self._save_snapshot()
        except FencedError:
            raise  # not a durability degradation: this primary must STOP
        except Exception as e:
            # degraded durability, not lost availability: the previous
            # checkpoint is intact (atomic write) and the journal keeps
            # growing from it, so recover() still reconstructs everything —
            # sampling continues
            warn_once(
                self,
                "_ckpt_failed_logged",
                "auto-checkpoint failed (%s: %s); sampling continues, "
                "recovery will replay the longer journal (logged once "
                "per bridge)",
                type(e).__name__,
                e,
                logger=__name__,
                site="checkpoint.write",
            )

    @classmethod
    def recover(
        cls,
        checkpoint_dir: str,
        map_fn: Optional[Any] = None,
        hash_fn: Optional[Any] = None,
        pipelined: Optional[bool] = None,
        retry_policy: Optional[RetryPolicy] = None,
        flush_timeout_s: Optional[float] = None,
        checkpoint_every: Optional[int] = None,
        faults: Optional[Any] = None,
        *,
        durability: Optional[str] = None,
        gated: Optional[bool] = None,
        gate_tile: Optional[int] = None,
        replay_hook: Optional[Any] = None,
        device: Optional[Any] = None,
    ) -> "DeviceStreamBridge":
        """Reconstruct a crashed auto-checkpointing bridge from its
        ``checkpoint_dir`` and replay the journaled post-checkpoint tail.

        The returned bridge's reservoirs are bit-identical to those of an
        uninterrupted run over the same flushes (counter-keyed draws make
        replay exact; pinned by ``tests/test_faults.py`` in all three
        sampling modes).  Resume pushing from :attr:`flushed_seq` /
        ``metrics.flushed_elements`` — the durable watermark.  ``map_fn``/
        ``hash_fn`` are code, not data, and must be re-supplied when the
        bridge was built with them; ``pipelined``/``checkpoint_every``
        default to the crashed bridge's settings.

        ``replay_hook(bridge, watermark)`` interleaves external engine
        mutations into the replay at their original positions: it is called
        once when state reaches the checkpoint's watermark (before any tile
        replays) and again after each replayed tile with that tile's
        sequence number.  The serving plane uses this to re-apply journaled
        session row resets exactly between the flushes they originally fell
        between — required for bit-exact recovery under session recycling.
        """
        from ..utils.checkpoint import load_engine

        engine_path = os.path.join(checkpoint_dir, "engine.npz")
        engine, metadata = load_engine(
            engine_path, map_fn=map_fn, hash_fn=hash_fn, with_metadata=True
        )
        info = (metadata or {}).get("bridge")
        if info is None:
            raise ValueError(
                f"{engine_path!r} was not written by an auto-checkpointing "
                "bridge (no bridge metadata); use ReservoirEngine.restore()"
            )
        # Recovery pre-flight (ISSUE-9 satellite): cross-check the epoch
        # this checkpoint lineage was admitted at against the persisted
        # fence BEFORE any replay.  A newer persisted epoch means a
        # standby was promoted past this lineage — recovering it would
        # put a second journaling writer on rows the promoted primary now
        # owns.  Fail typed and immediately, not via a FencedError on the
        # first post-recovery flush (or worse, silently adopting the new
        # epoch).  Old checkpoints without the recorded epoch pre-date
        # fencing promotions on their dir and pass vacuously.
        from ..errors import CheckpointMismatch
        persisted = read_epoch(checkpoint_dir)
        recorded = int(info.get("epoch", persisted))
        if persisted > recorded:
            raise CheckpointMismatch(
                f"{checkpoint_dir!r}: checkpoint lineage was admitted at "
                f"primary epoch {recorded}, but the persisted fence is at "
                f"epoch {persisted} — a standby was promoted past this "
                "lineage; recover from the promoted primary's checkpoint "
                "(its post-promotion handoff checkpoint) instead"
            )
        engine._faults = faults
        if device is not None:
            # placement is process-local: re-pin before any replay so the
            # replayed flushes land on the same chip the live path uses
            engine._pin_device(device)
        bridge = cls(
            engine.config,
            map_fn=map_fn,
            hash_fn=hash_fn,
            reusable=bool(info["reusable"]),
            pipelined=bool(info["pipelined"]) if pipelined is None else pipelined,
            retry_policy=retry_policy,
            flush_timeout_s=flush_timeout_s,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=(
                int(info["checkpoint_every"])
                if checkpoint_every is None
                else checkpoint_every
            ),
            durability=(
                info.get("durability", "buffered")
                if durability is None
                else durability
            ),
            faults=faults,
            gated=(
                bool(info.get("gated", False)) if gated is None else gated
            ),
            gate_tile=(
                int(info.get("gate_tile", 64))
                if gate_tile is None
                else gate_tile
            ),
            _engine=engine,
        )
        covered = int(info["seq"])
        bridge._flush_seq = covered
        m = bridge._metrics
        m.elements = int(info.get("elements", 0))
        m.flushed_elements = int(info.get("flushed_elements", 0))
        m.flushes = covered
        # replay the journaled tail on THIS thread (the pipeline is idle, so
        # the engine's single-writer contract holds), skipping records the
        # checkpoint already covers — a crash between checkpoint write and
        # journal rotation leaves such records behind by design
        config = engine.config
        if replay_hook is not None:
            replay_hook(bridge, covered)
        for seq, tile, valid, wtile, advance in _FlushJournal.replay(
            os.path.join(checkpoint_dir, "journal.bin"),
            config.num_reservoirs,
            config.tile_size,
            np.dtype(config.element_dtype),
            config.weighted,
        ):
            if seq <= covered:
                continue
            if advance is _FlushJournal.ADOPT:
                # adopt frame (ISSUE 12): re-apply the migrated rows at
                # their original position between flushes — ``tile`` is
                # the raw payload
                rows, sub = _unpack_adopt_payload(tile)
                engine.adopt_rows(rows, sub)
                total = 0
            elif advance is not None:
                # gated frame (ISSUE 8): candidates + per-row advance
                # replay through the same gated apply the live path used
                engine.sample_gated(tile, valid, advance)
                total = int(advance.sum())
            else:
                engine.sample(tile, valid=valid, weights=wtile)
                total = int(valid.sum())
            bridge._flush_seq = seq
            m.flushes += 1
            m.elements += total
            m.flushed_elements += total
            if replay_hook is not None:
                replay_hook(bridge, seq)
        m.recoveries += 1
        _obs.emit(
            "bridge.recovered",
            site="bridge.recover",
            flush_seq=bridge._flush_seq,
            replayed=bridge._flush_seq - covered,
            epoch=bridge._epoch,
        )
        return bridge

    # ------------------------------------------------------------ completion

    def complete(self) -> List[np.ndarray]:
        """Upstream completion: flush remainders, fulfill the future with the
        per-stream samples, and return them (``onUpstreamFinish``,
        ``SampleImpl.scala:38-41``).  Reusable bridges may continue pushing
        afterwards (a fresh future is armed)."""
        self._check_open()
        self.flush()
        self.drain_barrier()  # result() must see every dispatched tile
        self._metrics.demotions = self._engine.demotions
        with trace_span("reservoir_bridge_result"):
            res = self._engine.result()
        self._metrics.completions += 1
        self._future.set_result(res)
        if self._reusable:
            self._future = Future()
        return res

    def fail(self, cause: BaseException) -> None:
        """Upstream failure: fail the future with ``cause``
        (``onUpstreamFailure``, ``SampleImpl.scala:43-46``)."""
        if not self._future.done():
            self._metrics.failures += 1
            self._future.set_exception(cause)

    def cancel(self, cause: Optional[BaseException] = None) -> None:
        """Downstream cancellation (``SampleImpl.scala:48-54``): graceful
        delivers the partial sample, a cause fails the future."""
        if self._future.done():
            return
        if cause is None:
            self.complete()
        else:
            self.fail(cause)

    def __del__(self) -> None:
        # postStop backstop (SampleImpl.scala:56-57)
        pipe = getattr(self, "_pipeline", None)
        fut = getattr(self, "_future", None)
        if pipe is not None:
            try:
                pipe.close()
            except BaseException as e:
                # close() re-raises an error from the FINAL flush (the one
                # a bare owner-drop used to lose); teardown must not
                # swallow it — route it through the tri-state protocol
                if fut is not None and not fut.done():
                    fut.set_exception(e)
        journal = getattr(self, "_journal", None)
        if journal is not None:
            journal.close()
        if fut is not None and not fut.done():
            fut.set_exception(
                AbruptStreamTermination(
                    "stream bridge dropped without completing"
                )
            )


class DeviceSampler:
    """Single-stream :class:`~reservoir_tpu.api.Sampler`-shaped adapter over
    the device engine — lets the pass-through operator
    (:class:`~reservoir_tpu.stream.operator.Sample`) sample on TPU.

    Per-element ``sample`` buffers on the host; the device sees fixed-width
    tiles (static shapes, one compile).  ``result`` flushes the remainder and
    applies the reference truncation/lifecycle contract.
    """

    def __init__(
        self,
        config: SamplerConfig,
        key: Union[int, Any, None] = None,
        reusable: bool = False,
    ) -> None:
        if config.num_reservoirs != 1:
            raise ValueError(
                "DeviceSampler is single-stream (num_reservoirs=1); use "
                "DeviceStreamBridge for many streams"
            )
        self._engine = ReservoirEngine(config, key=key, reusable=reusable)
        self._reusable = reusable
        self._open = True
        self._buf = np.zeros(config.tile_size, dtype=np.dtype(config.element_dtype))
        self._fill = 0

    @property
    def is_open(self) -> bool:
        return True if self._reusable else self._open

    def _check_open(self) -> None:
        if not self.is_open:
            raise SamplerClosedError("this sampler is single-use, and no longer open")

    def _flush(self) -> None:
        if self._fill:
            self._engine.sample(
                self._buf[None, :], valid=np.asarray([self._fill], np.int32)
            )
            self._fill = 0

    def sample(self, element: Any) -> None:
        self._check_open()
        self._buf[self._fill] = element
        self._fill += 1
        if self._fill >= self._buf.shape[0]:
            self._flush()

    def sample_all(self, elements: Any) -> None:
        """Bulk path: array-shaped input flushes in whole tiles without the
        per-element loop (the ``sampleAll`` fast-path analog,
        ``Sampler.scala:261-287``).  A dtype/shape error names the element
        range that failed to convert, not just the target dtype."""
        self._check_open()
        if not isinstance(elements, np.ndarray) and not hasattr(elements, "__len__"):
            # generator/iterator source (the Sampler ABC accepts any iterable)
            for i, e in enumerate(elements):
                try:
                    self.sample(e)
                except (TypeError, ValueError) as e_:
                    raise ValueError(
                        f"elements[{i}] not storable as "
                        f"{self._buf.dtype}: {e_}"
                    ) from None
            return
        arr = np.asarray(elements) if not isinstance(elements, np.ndarray) else elements
        if arr.dtype == object or arr.ndim != 1:
            for i, e in enumerate(np.ravel(arr)):
                try:
                    self.sample(e)
                except (TypeError, ValueError) as e_:
                    raise ValueError(
                        f"elements[{i}] not storable as "
                        f"{self._buf.dtype}: {e_}"
                    ) from None
            return
        B = self._buf.shape[0]
        off = 0
        n = arr.shape[0]
        while off < n:
            take = min(B - self._fill, n - off)
            try:
                self._buf[self._fill : self._fill + take] = arr[off : off + take]
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"elements[{off}:{off + take}] (dtype {arr.dtype}) not "
                    f"storable as {self._buf.dtype}: {e}"
                ) from None
            self._fill += take
            off += take
            if self._fill >= B:
                self._flush()

    def result(self) -> np.ndarray:
        self._check_open()
        self._flush()
        res = self._engine.result()[0]
        if not self._reusable:
            self._open = False
            self._buf = None  # free (Sampler.scala:345-350)
        return res
