"""Host->device stream bridge: per-stream buffers, tile-granular flushes.

The reference's stream stage handles one element per actor callback
(``SampleImpl.scala:27-31``); a TPU cannot be fed that way.  The bridge
replaces per-element ``onPush`` with **batch flushes**: S logical streams
buffer on the host into an ``[R=S, B]`` tile, which is dispatched to a
:class:`~reservoir_tpu.engine.ReservoirEngine` whenever any stream's row
fills (ragged ``valid`` counts keep partially-filled rows exact).  This is
the SURVEY §2.4 "host->device stream bridge" component and the scale path
for BASELINE.md config 5 (65,536 concurrent streams).

The completion protocol survives the batching (SURVEY §5 "failure
detection" row): the bridge exposes the same tri-state outcome as the
operator — :meth:`complete` (future succeeds with the per-stream samples),
:meth:`fail` (future fails with the cause), and a drop-without-completion
backstop failing it with :class:`AbruptStreamTermination`
(``SampleImpl.scala:35-57``).

Thread-safety contract matches the reference (``Sampler.scala:19``): one
writer.  Wrap pushes in your own queue for multi-producer feeds.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, List, Optional, Union

import numpy as np

from ..config import SamplerConfig
from ..engine import ReservoirEngine
from ..errors import AbruptStreamTermination, SamplerClosedError
from ..native import NativeStaging
from ..utils.metrics import BridgeMetrics
from ..utils.tracing import trace_span

__all__ = ["DeviceStreamBridge", "DeviceSampler"]


class _FlushPipeline:
    """Depth-1 dispatch pipeline: a single worker thread runs the device
    flushes while the caller demuxes the NEXT tile (VERDICT r2 item 3 —
    the r2 bridge drained and dispatched serially on one staging tile).

    ``reserve`` blocks while both host tiles are busy (bounded
    reservations = natural backpressure, two host tiles of memory total);
    ``join`` waits for the in-flight flushes and re-raises any worker
    exception on the caller's thread.  One producer, one worker: the
    engine keeps its single-writer contract because only the worker
    touches it between ``join`` barriers.

    The tile-reuse hazard the semaphore closes: ``Queue.put`` alone
    returns as soon as the worker has *taken* the previous tile, not
    finished it — the caller could then demux into a tile the worker is
    still reading.  ``reserve()`` (sized to the tile count) blocks until
    a host tile is genuinely free: the worker releases a reservation only
    AFTER its flush completes.
    """

    def __init__(self, fn, n_tiles: int = 2) -> None:
        import weakref

        # weak method: the worker must not keep the bridge alive, or the
        # abrupt-termination __del__ backstop (SampleImpl.scala:56-57)
        # could never fire — a dead owner simply ends the pipeline
        self._fn = weakref.WeakMethod(fn)
        self._q: "queue.Queue" = queue.Queue()
        self._free = threading.Semaphore(n_tiles)
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            try:
                fn = self._fn()
                if fn is None:  # owner collected: discard remaining work
                    return
                if self._error is None:
                    fn(*item)
            except BaseException as e:  # surfaced at next reserve/join
                self._error = e
            finally:
                self._free.release()  # the tile is safe to demux into
                self._q.task_done()

    def _check(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def reserve(self) -> None:
        """Block until a host tile is free to demux into (call BEFORE
        draining into the tile that will be submitted)."""
        self._check()
        self._free.acquire()

    def release(self) -> None:
        """Return an unused reservation (the drain produced nothing)."""
        self._free.release()

    def submit(self, *args) -> None:
        self._q.put(args)

    def join(self) -> None:
        self._q.join()
        self._check()

    def close(self) -> None:
        if self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout=30)


class DeviceStreamBridge:
    """S independent logical streams feeding S device reservoirs in lockstep.

    Stream ``s`` owns reservoir row ``s``; elements pushed for it buffer into
    row ``s`` of a host-side ``[S, B]`` staging tile.  When any row reaches
    the tile width, the whole tile flushes to the device with per-row
    ``valid`` counts (padding rows are never sampled — the engine's ragged
    contract).  State between flushes lives only on the device.

    Args:
      config: engine config; ``num_reservoirs`` is the stream count.
      key: PRNG key or seed for the engine.
      map_fn / hash_fn: traceable hooks forwarded to the engine.
      reusable: lifecycle switch — reusable bridges allow :meth:`complete`
        followed by more pushes (snapshot semantics).
      pipelined: overlap the host demux with the device flush — the C++
        demux fills tile B while tile A's transfer+dispatch is in flight
        on a worker thread (double buffering; default on).  ``False``
        restores the fully synchronous single-tile path.
    """

    def __init__(
        self,
        config: SamplerConfig,
        key: Union[int, Any, None] = None,
        map_fn: Optional[Any] = None,
        hash_fn: Optional[Any] = None,
        reusable: bool = False,
        mesh: Optional[Any] = None,
        pipelined: bool = True,
    ) -> None:
        self._config = config
        self._engine = ReservoirEngine(
            config,
            key=key,
            map_fn=map_fn,
            hash_fn=hash_fn,
            reusable=reusable,
            mesh=mesh,
        )
        self._reusable = reusable
        S, B = config.num_reservoirs, config.tile_size
        # staging is native (C++ demux, _native/staging_buffer.cc) when the
        # helper library is available, numpy otherwise — same semantics
        self._staging = NativeStaging(
            S, B, np.dtype(config.element_dtype), weighted=config.weighted
        )
        n_bufs = 2 if pipelined else 1
        dtype = np.dtype(config.element_dtype)
        self._tiles = [np.zeros((S, B), dtype) for _ in range(n_bufs)]
        self._wtiles = (
            [np.ones((S, B), np.float32) for _ in range(n_bufs)]
            if config.weighted
            else None
        )
        # Pre-fault the host tiles: numpy's large zeros are lazily mapped,
        # so without this the first flush cycle's demux page-faults on
        # every 4 KiB page of a ~100 MB tile (measured ~2x demux slowdown
        # at config-5 scale).  One write per page at construction moves
        # that cost out of the hot path.
        # (_wtiles need no pre-fault: np.ones writes every element, which
        # already faults every page at allocation)
        page = 4096
        for t in self._tiles:
            t.reshape(-1).view(np.uint8)[::page] = 0
        self._valids = [np.zeros(S, np.int32) for _ in range(n_bufs)]
        self._buf = 0
        # Zero-copy flush mode (r4 config-5 host-path work): the demux
        # scatters straight into the active flush tile, so a flush is a
        # fill-count read + buffer swap instead of an [S, B] drain copy
        # (134 MB per flush at config-5 scale).  Pipeline depth drops to 1
        # permit: reserve() then guarantees the tile being attached next is
        # no longer read by the worker — same steady-state overlap (demux
        # of tile B rides tile A's transfer+dispatch), no copy.
        self._zero_copy = self._staging.supports_attach()
        if self._zero_copy:
            self._staging.attach(
                self._tiles[0],
                self._wtiles[0] if self._wtiles is not None else None,
            )
        self._pipeline = (
            _FlushPipeline(
                self._dispatch_flush, n_tiles=1 if self._zero_copy else 2
            )
            if pipelined
            else None
        )
        self._future: Future = Future()
        self._metrics = BridgeMetrics()
        self._metrics.demux_threads = self._staging.threads()

    # ------------------------------------------------------------ properties

    @property
    def num_streams(self) -> int:
        return self._config.num_reservoirs

    @property
    def sample(self) -> Future:
        """The bridge's materialized value: future of the per-stream samples
        (list of ``S`` arrays), completed by the tri-state protocol."""
        return self._future

    @property
    def metrics(self) -> BridgeMetrics:
        return self._metrics

    @property
    def is_open(self) -> bool:
        return self._engine.is_open and not self._future.done()

    def _check_open(self) -> None:
        if self._future.done():
            raise SamplerClosedError("this bridge has completed or failed")
        self._engine._check_open()

    # --------------------------------------------------------------- pushing

    def push(
        self,
        stream: int,
        elements: Any,
        weights: Optional[Any] = None,
    ) -> None:
        """Buffer one element or a 1-D chunk for logical stream ``stream``;
        flushes automatically whenever the stream's row fills."""
        self._check_open()
        self._metrics.start()
        arr = np.atleast_1d(np.asarray(elements, self._tiles[0].dtype))
        warr = self._check_weights(arr, weights)
        off = 0
        n = arr.shape[0]
        while off < n:
            t0 = time.perf_counter()
            took = self._staging.push_chunk(
                stream,
                arr[off:],
                warr[off:] if warr is not None else None,
            )
            self._metrics.demux_s += time.perf_counter() - t0
            off += took
            if off < n or self._staging.row_full(stream):
                self.flush()
        self._metrics.elements += n

    def push_interleaved(self, streams: Any, elements: Any,
                         weights: Optional[Any] = None) -> None:
        """Demux an interleaved feed of ``(stream_id, element)`` pairs — the
        multi-producer wire format.  The scatter runs in the native staging
        helper when available (C-speed pointer walk; numpy fallback
        otherwise), flushing whenever a row fills mid-batch."""
        self._check_open()
        self._metrics.start()
        # conversions up front so the resume-loop slices stay no-copy; shape
        # and range validation belongs to NativeStaging (single owner)
        streams = np.ascontiguousarray(streams, np.int32)
        arr = np.ascontiguousarray(elements, self._tiles[0].dtype)
        warr = self._check_weights(arr, weights)
        off = 0
        n = arr.shape[0]
        while off < n:
            t0 = time.perf_counter()
            took = self._staging.push_interleaved(
                streams[off:],
                arr[off:],
                warr[off:] if warr is not None else None,
            )
            self._metrics.demux_s += time.perf_counter() - t0
            off += took
            if off < n:
                self.flush()
        self._metrics.elements += n

    def _check_weights(self, arr, weights):
        if self._wtiles is not None:
            if weights is None:
                raise ValueError("weighted bridge requires weights")
            warr = np.atleast_1d(np.ascontiguousarray(weights, np.float32))
            if warr.shape != arr.shape:
                raise ValueError("weights must match elements shape")
            if not np.all(warr >= 0):
                raise ValueError("weights must be nonnegative")
            return warr
        if weights is not None:
            raise ValueError("weights are only meaningful with weighted=True")
        return None

    def push_tile(self, tile: Any, valid: Optional[Any] = None,
                  weights: Optional[Any] = None) -> None:
        """Bypass buffering: dispatch a pre-assembled ``[S, B]`` tile straight
        to the device (the zero-copy fast path for array-shaped sources)."""
        self._check_open()
        self._metrics.start()
        self.drain_barrier()  # engine is single-writer: wait out the worker
        tile = np.asarray(tile)
        with trace_span("reservoir_bridge_flush"):
            self._engine.sample(tile, valid=valid, weights=weights)
        n = int(tile.shape[1]) * tile.shape[0] if valid is None else int(
            np.sum(np.asarray(valid))
        )
        self._metrics.elements += n
        self._metrics.flushed_elements += n
        self._metrics.flushes += 1

    def _dispatch_flush(self, tile, valid, wtile) -> None:
        """The device half of a flush (worker thread when pipelined)."""
        t0 = time.perf_counter()
        with trace_span("reservoir_bridge_flush"):
            if wtile is not None:
                # stale weight-slots past each row's valid count hold old
                # (nonnegative) weights; the valid mask keeps them out of
                # sampling and user weights are never rewritten (the r1
                # 1e-30 clamp silently mutated legitimate denormal weights)
                self._engine.sample(tile, valid=valid, weights=wtile)
            else:
                self._engine.sample(tile, valid=valid)
        self._metrics.dispatch_s += time.perf_counter() - t0

    def flush(self) -> None:
        """Dispatch buffered elements (ragged tile) to the device.

        Zero-copy mode (the default): the demux already scattered into the
        active host tile, so the flush reads the fill counts, hands the
        tile to the worker, and re-points the demux at the other tile —
        blocking only while that tile's previous flush is still in flight.
        Copy mode (stale native lib): drain-copies staging into the idle
        tile first.  Either way the next demux overlaps this flush's
        transfer+dispatch when pipelined.
        """
        if self._zero_copy:
            i = self._buf
            tile, valid = self._tiles[i], self._valids[i]
            wtile = self._wtiles[i] if self._wtiles is not None else None
            t0 = time.perf_counter()
            total = self._staging.take(valid)
            self._metrics.drain_s += time.perf_counter() - t0
            if total == 0:
                return
            if self._pipeline is not None:
                # wait until the OTHER tile's previous flight is done,
                # then swap the demux onto it
                self._pipeline.reserve()
                self._pipeline.submit(tile, valid, wtile)
                self._buf = 1 - i
                self._staging.attach(
                    self._tiles[self._buf],
                    self._wtiles[self._buf]
                    if self._wtiles is not None
                    else None,
                )
            else:
                self._dispatch_flush(tile, valid, wtile)
            self._metrics.flushes += 1
            self._metrics.flushed_elements += total
            return
        if self._pipeline is not None:
            # block until the tile we are about to drain into is truly
            # free (the worker may still be reading it)
            self._pipeline.reserve()
        i = self._buf
        tile, valid = self._tiles[i], self._valids[i]
        wtile = self._wtiles[i] if self._wtiles is not None else None
        t0 = time.perf_counter()
        total = self._staging.drain(tile, valid, wtile)
        self._metrics.drain_s += time.perf_counter() - t0
        if total == 0:
            if self._pipeline is not None:
                self._pipeline.release()
            return
        if self._pipeline is not None:
            self._pipeline.submit(tile, valid, wtile)
            self._buf = 1 - i  # demux continues into the other tile
        else:
            self._dispatch_flush(tile, valid, wtile)
        self._metrics.flushes += 1
        self._metrics.flushed_elements += total

    def drain_barrier(self) -> None:
        """Wait for any in-flight pipelined flush (re-raising its error)."""
        if self._pipeline is not None:
            self._pipeline.join()

    # ------------------------------------------------------------ completion

    def complete(self) -> List[np.ndarray]:
        """Upstream completion: flush remainders, fulfill the future with the
        per-stream samples, and return them (``onUpstreamFinish``,
        ``SampleImpl.scala:38-41``).  Reusable bridges may continue pushing
        afterwards (a fresh future is armed)."""
        self._check_open()
        self.flush()
        self.drain_barrier()  # result() must see every dispatched tile
        with trace_span("reservoir_bridge_result"):
            res = self._engine.result()
        self._metrics.completions += 1
        self._future.set_result(res)
        if self._reusable:
            self._future = Future()
        return res

    def fail(self, cause: BaseException) -> None:
        """Upstream failure: fail the future with ``cause``
        (``onUpstreamFailure``, ``SampleImpl.scala:43-46``)."""
        if not self._future.done():
            self._metrics.failures += 1
            self._future.set_exception(cause)

    def cancel(self, cause: Optional[BaseException] = None) -> None:
        """Downstream cancellation (``SampleImpl.scala:48-54``): graceful
        delivers the partial sample, a cause fails the future."""
        if self._future.done():
            return
        if cause is None:
            self.complete()
        else:
            self.fail(cause)

    def __del__(self) -> None:
        # postStop backstop (SampleImpl.scala:56-57)
        pipe = getattr(self, "_pipeline", None)
        if pipe is not None:
            pipe.close()
        fut = getattr(self, "_future", None)
        if fut is not None and not fut.done():
            fut.set_exception(
                AbruptStreamTermination(
                    "stream bridge dropped without completing"
                )
            )


class DeviceSampler:
    """Single-stream :class:`~reservoir_tpu.api.Sampler`-shaped adapter over
    the device engine — lets the pass-through operator
    (:class:`~reservoir_tpu.stream.operator.Sample`) sample on TPU.

    Per-element ``sample`` buffers on the host; the device sees fixed-width
    tiles (static shapes, one compile).  ``result`` flushes the remainder and
    applies the reference truncation/lifecycle contract.
    """

    def __init__(
        self,
        config: SamplerConfig,
        key: Union[int, Any, None] = None,
        reusable: bool = False,
    ) -> None:
        if config.num_reservoirs != 1:
            raise ValueError(
                "DeviceSampler is single-stream (num_reservoirs=1); use "
                "DeviceStreamBridge for many streams"
            )
        self._engine = ReservoirEngine(config, key=key, reusable=reusable)
        self._reusable = reusable
        self._open = True
        self._buf = np.zeros(config.tile_size, dtype=np.dtype(config.element_dtype))
        self._fill = 0

    @property
    def is_open(self) -> bool:
        return True if self._reusable else self._open

    def _check_open(self) -> None:
        if not self.is_open:
            raise SamplerClosedError("this sampler is single-use, and no longer open")

    def _flush(self) -> None:
        if self._fill:
            self._engine.sample(
                self._buf[None, :], valid=np.asarray([self._fill], np.int32)
            )
            self._fill = 0

    def sample(self, element: Any) -> None:
        self._check_open()
        self._buf[self._fill] = element
        self._fill += 1
        if self._fill >= self._buf.shape[0]:
            self._flush()

    def sample_all(self, elements: Any) -> None:
        """Bulk path: array-shaped input flushes in whole tiles without the
        per-element loop (the ``sampleAll`` fast-path analog,
        ``Sampler.scala:261-287``)."""
        self._check_open()
        if not isinstance(elements, np.ndarray) and not hasattr(elements, "__len__"):
            # generator/iterator source (the Sampler ABC accepts any iterable)
            for e in elements:
                self.sample(e)
            return
        arr = np.asarray(elements) if not isinstance(elements, np.ndarray) else elements
        if arr.dtype == object or arr.ndim != 1:
            for e in np.ravel(arr):
                self.sample(e)
            return
        B = self._buf.shape[0]
        off = 0
        n = arr.shape[0]
        while off < n:
            take = min(B - self._fill, n - off)
            self._buf[self._fill : self._fill + take] = arr[off : off + take]
            self._fill += take
            off += take
            if self._fill >= B:
                self._flush()

    def result(self) -> np.ndarray:
        self._check_open()
        self._flush()
        res = self._engine.result()[0]
        if not self._reusable:
            self._open = False
            self._buf = None  # free (Sampler.scala:345-350)
        return res
