"""Stream-operator layer — the reference's akka-stream module, TPU-native.

The reference's L2 is ``object Sample`` (``Sample.scala:21-92``): a
*pass-through* flow re-emitting every upstream element, whose materialized
value is a ``Future`` of the final sample, with a precise completion protocol
(``SampleImpl.scala:27-57``).  Here:

- :class:`~reservoir_tpu.stream.operator.Sample` — flow blueprint with eager
  validation; each ``run()`` materializes a fresh sampler and a future.
- :class:`~reservoir_tpu.stream.operator.RunningSample` — the materialized
  pass-through iterator implementing the emit/backpressure/complete/cancel
  protocol (backpressure = pull-based iteration).
- :class:`~reservoir_tpu.stream.bridge.DeviceStreamBridge` — the host->device
  batching layer: S logical streams buffered into ``[R, B]`` tiles feeding a
  :class:`~reservoir_tpu.engine.ReservoirEngine` (the 65,536-stream scale
  path, BASELINE.md config 5).
- :class:`~reservoir_tpu.stream.gate.SkipGate` — the ingest-side skip-ahead
  gate (ISSUE 8): a host replica of the Algorithm-L skip recursion that
  lets a ``gated=True`` bridge elide, compact and coalesce everything that
  cannot be accepted, bit-reconcilably.
"""

from .bridge import DeviceSampler, DeviceStreamBridge
from .gate import SkipGate, gate_ineligible_reason
from .operator import RunningSample, Sample

__all__ = [
    "Sample",
    "RunningSample",
    "DeviceStreamBridge",
    "DeviceSampler",
    "SkipGate",
    "gate_ineligible_reason",
]
