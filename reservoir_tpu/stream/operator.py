"""Pass-through sampling operator (``object Sample`` + ``SampleImpl``).

Stream-semantics contract, mirrored from ``Sample.scala:13-19`` /
``SampleImpl.scala:27-57`` onto Python iterators:

- **emits** when upstream pushes — every upstream element is re-emitted
  downstream unchanged (``SampleImpl.scala:27-31``);
- **backpressures** when downstream backpressures — iteration is pull-based,
  nothing is consumed until the downstream asks (``SampleImpl.scala:33``);
- **completes** when upstream completes — the materialized future is
  fulfilled with the sample (``SampleImpl.scala:38-41``);
- **cancels**: graceful downstream cancellation delivers the partial sample;
  cancellation with a cause fails the future with it
  (``SampleImpl.scala:48-54``);
- **abrupt termination**: if the operator is dropped without any of the
  above, the future fails with :class:`AbruptStreamTermination`
  (the ``postStop`` backstop, ``SampleImpl.scala:56-57``).

The Akka ``Future[IndexedSeq[B]]`` materialized value becomes a
``concurrent.futures.Future`` — usable from sync and async code alike.
Validation happens **eagerly at flow construction** (``Sample.scala:52, 89``)
while sampler creation is deferred to materialization, so each ``run()``
gets a fresh, independent sampler (``Sample.scala:23-24``).
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import Any, AsyncIterable, Callable, Iterable, Optional, Union

import numpy as np

from ..config import validate_non_distinct_params
from ..errors import AbruptStreamTermination, SamplerClosedError

__all__ = ["Sample", "RunningSample", "AsyncRunningSample"]


class Sample:
    """Flow blueprint: pass-through sampling with a future materialized value.

    ``Sample(k)`` mirrors ``Sample.apply`` (``Sample.scala:49-54``);
    :meth:`distinct` mirrors ``Sample.distinct`` (``:86-91``);
    :meth:`device` routes sampling through a TPU
    :class:`~reservoir_tpu.engine.ReservoirEngine` via the stream bridge.

    Parameters are validated here, at graph-construction time; the sampler
    itself is created per :meth:`run` (fresh randomness and lifecycle per
    materialization, ``SampleImpl.scala:23-25``).
    """

    def __init__(
        self,
        max_sample_size: int,
        *,
        pre_allocate: bool = False,
        map_fn: Optional[Callable[[Any], Any]] = None,
        rng: Union[None, int, np.random.Generator] = None,
    ) -> None:
        from .. import api

        validate_non_distinct_params(
            max_sample_size, map_fn if map_fn is not None else (lambda x: x)
        )
        self._factory: Callable[[], Any] = lambda: api.sampler(
            max_sample_size,
            pre_allocate=pre_allocate,
            map_fn=map_fn,
            rng=rng,
        )

    @classmethod
    def distinct(
        cls,
        max_sample_size: int,
        *,
        map_fn: Optional[Callable[[Any], Any]] = None,
        hash_fn: Optional[Callable[[Any], int]] = None,
        rng: Union[None, int, np.random.Generator] = None,
    ) -> "Sample":
        """Distinct-value flow (``Sample.distinct``, ``Sample.scala:86-91``)."""
        from .. import api

        # eager validation identical to the core factory's (Sample.scala:89)
        validate_non_distinct_params(
            max_sample_size, map_fn if map_fn is not None else (lambda x: x)
        )
        if hash_fn is not None and not callable(hash_fn):
            raise TypeError("hash function must be callable (got %r)" % (hash_fn,))
        return cls.from_factory(
            lambda: api.distinct(
                max_sample_size, map_fn=map_fn, hash_fn=hash_fn, rng=rng
            )
        )

    @classmethod
    def device(
        cls,
        max_sample_size: int,
        *,
        key: Union[int, Any, None] = None,
        tile_size: int = 1024,
        element_dtype: Any = "int32",
        distinct: bool = False,
        reusable: bool = False,
    ) -> "Sample":
        """A flow whose sampling side runs on the TPU engine: elements pass
        through on the host while tiles flush to the device reservoir
        (single logical stream; the many-stream scale path is
        :class:`~reservoir_tpu.stream.bridge.DeviceStreamBridge`)."""
        from ..config import SamplerConfig, validate_max_sample_size
        from .bridge import DeviceSampler

        validate_max_sample_size(max_sample_size)
        config = SamplerConfig(
            max_sample_size=max_sample_size,
            num_reservoirs=1,
            tile_size=tile_size,
            element_dtype=element_dtype,
            distinct=distinct,
        )
        return cls.from_factory(
            lambda: DeviceSampler(config, key=key, reusable=reusable)
        )

    @classmethod
    def from_factory(cls, factory: Callable[[], Any]) -> "Sample":
        """Flow over any by-name sampler thunk (the ``Sample.flow`` helper,
        ``Sample.scala:23-24``) — one fresh sampler per materialization."""
        flow = cls.__new__(cls)
        flow._factory = factory
        return flow

    # ---------------------------------------------------------- materialize

    def run(self, source: Iterable[Any]) -> "RunningSample":
        """Materialize over ``source``: returns the pass-through iterator;
        its ``.sample`` future is the materialized value (``Keep.right``,
        ``Sample.scala:23-24``)."""
        return RunningSample(self._factory(), source)

    def run_async(self, source: AsyncIterable[Any]) -> "AsyncRunningSample":
        """Materialize over an async source (the Akka execution model's
        natural Python analog)."""
        return AsyncRunningSample(self._factory(), source)


class _RunningBase:
    """Completion protocol shared by the sync and async operators
    (``SampleImpl.scala:35-57``)."""

    def __init__(self, sampler: Any) -> None:
        self._sampler = sampler
        self._future: Future = Future()
        self._done = False

    @property
    def sample(self) -> Future:
        """The materialized value: a future of the final sample
        (``SampleImpl.scala:23, 62``)."""
        return self._future

    # -- tryCompleteSampler (SampleImpl.scala:35-36): fulfill with the
    # sampler's result iff it is still open and the promise untouched.
    def _try_complete(self) -> None:
        if self._future.done():
            return
        if getattr(self._sampler, "is_open", True):
            try:
                self._future.set_result(self._sampler.result())
            except BaseException as exc:  # result() itself failed
                self._future.set_exception(exc)
        else:
            # A closed sampler at completion means the factory violated the
            # fresh-sampler-per-run contract; fail loudly rather than leave
            # the future forever pending (drain() would deadlock).
            self._future.set_exception(
                SamplerClosedError(
                    "sampler was already closed at stream completion; "
                    "factories must produce a fresh sampler per run"
                )
            )

    def _fail(self, exc: BaseException) -> None:
        if not self._future.done():
            self._future.set_exception(exc)

    def cancel(self, cause: Optional[BaseException] = None) -> None:
        """Downstream cancellation (``onDownstreamFinish``,
        ``SampleImpl.scala:48-54``): graceful (no cause) delivers the partial
        sample; a cause fails the future with it.  Idempotent."""
        if self._done:
            return
        self._done = True
        if cause is None:
            self._try_complete()
        else:
            self._fail(cause)

    close = cancel  # context-manager / generator-protocol friendly alias

    def __del__(self) -> None:
        # postStop backstop (SampleImpl.scala:56-57): dropped without
        # completing -> abrupt termination.
        fut = getattr(self, "_future", None)
        if fut is not None and not fut.done():
            fut.set_exception(
                AbruptStreamTermination(
                    "stream operator terminated abruptly without completion"
                )
            )


class RunningSample(_RunningBase):
    """Materialized pass-through iterator over a sync source.

    Iterating pulls one upstream element, samples it, and re-emits it
    (``onPush``, ``SampleImpl.scala:27-31``).  Exhaustion completes the
    future with the sample; an upstream exception fails the future and
    propagates (``SampleImpl.scala:38-46``).
    """

    def __init__(self, sampler: Any, source: Iterable[Any]) -> None:
        super().__init__(sampler)
        self._it = iter(source)

    def __iter__(self) -> "RunningSample":
        return self

    def __next__(self) -> Any:
        if self._done:
            raise StopIteration
        try:
            elem = next(self._it)
        except StopIteration:
            self._done = True
            self._try_complete()  # onUpstreamFinish (SampleImpl.scala:38-41)
            raise
        except BaseException as exc:
            self._done = True
            self._fail(exc)  # onUpstreamFailure (SampleImpl.scala:43-46)
            raise
        try:
            self._sampler.sample(elem)
        except BaseException as exc:
            self._done = True
            self._fail(exc)
            raise
        return elem

    def drain(self) -> Any:
        """Run the stream to completion discarding emitted elements
        (``Sink.ignore``) and return the sample — the common test/benchmark
        harness shape (``SampleTest.scala:32-37``)."""
        for _ in self:
            pass
        return self._future.result()


class AsyncRunningSample(_RunningBase):
    """Materialized pass-through async iterator (same protocol as
    :class:`RunningSample` over an ``AsyncIterable``)."""

    def __init__(self, sampler: Any, source: AsyncIterable[Any]) -> None:
        super().__init__(sampler)
        self._it = source.__aiter__()

    def __aiter__(self) -> "AsyncRunningSample":
        return self

    async def __anext__(self) -> Any:
        if self._done:
            raise StopAsyncIteration
        try:
            elem = await self._it.__anext__()
        except StopAsyncIteration:
            self._done = True
            self._try_complete()
            raise
        except BaseException as exc:
            self._done = True
            self._fail(exc)
            raise
        try:
            self._sampler.sample(elem)
        except BaseException as exc:
            self._done = True
            self._fail(exc)
            raise
        return elem

    async def drain(self) -> Any:
        """Async ``Sink.ignore`` + materialized value."""
        async for _ in self:
            pass
        return self._future.result()
