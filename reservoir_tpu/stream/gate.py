"""Ingest-side skip-ahead gate: stop shipping bytes that can't win (ISSUE 8).

Past the fill phase, Algorithm L accepts a vanishing fraction of elements —
yet the bridge DMAs every staged byte to the device (ROADMAP item 3).
Sanders et al., "Efficient Random Sampling — Parallel, Vectorized,
Cache-Efficient, and Online" (arXiv:1610.05141) shows skip-count sampling
runs at memory bandwidth when the skip recursion is evaluated in bulk, and
BatchRNG (arXiv:1412.4825) that counter-based RNG batches cleanly for
exactly this shape.  This module is that idea applied to the stream bridge:

- :class:`SkipGate` keeps a host-side **replica** of the engine's per-row
  Algorithm-L recursion ``(count, nxt, log_w)`` and advances it per staged
  chunk with the *same* traced code the device runs
  (:func:`~reservoir_tpu.ops.algorithm_l._advance_words`, Threefry draws
  keyed on absolute indices) — jitted on the **host CPU backend**, never
  numpy: numpy's ``log``/``exp``/``log1p`` differ from XLA in final ulps,
  and one ulp flips a ``floor`` and diverges the whole counter chain.  On
  CPU backends the replica is bit-identical to the engine *by construction*
  (same compiled math); on TPU the host-CPU-vs-TPU transcendental parity is
  an empirical capture question — the ``gated_parity`` row of the
  ``parity_probe`` selftest pins it per hardware window.

- Per flush, :meth:`evaluate` runs the recursion over all S rows in one
  vmapped call and reports, per row, the **candidate set** of the staged
  chunk: the fill-phase prefix plus every acceptance position.  Everything
  else is provably irrelevant — those bytes are *elided*, never journaled,
  never DMA'd.

- Candidates coalesce into a small ``[S, gate_tile]`` tile across flushes
  (:meth:`append`/:meth:`take`); the bridge dispatches it through
  :meth:`ReservoirEngine.sample_gated` with a per-row ``advance`` count, so
  hundreds of acceptance-free flushes collapse into one tiny dispatch.

Bit-reconciliation contract (the discipline ``ops/prefix.py`` established
for weights): the gated and ungated paths consume the same Threefry blocks
per logical index and accept the same set, so reservoirs are bit-identical
— pinned across chunk geometries, modes, crash-recovery replay and the
serving plane by ``tests/test_gate.py``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

__all__ = ["SkipGate", "GateEval", "gate_ineligible_reason"]

#: module-level jit cache keyed by (k, cap): every bridge of the same
#: reservoir capacity shares one compiled eval (shape axes are jit's own
#: cache dimensions) — a fresh gate must not pay a re-trace per instance.
_EVAL_CACHE: dict = {}


def gate_ineligible_reason(config, staging=None) -> Optional[str]:
    """None when the skip gate can run for ``config``, else why not.

    The gate replicates the *duplicates-mode* Algorithm-L recursion with
    narrow int32 counters; weighted (A-ExpJ needs every weight to decide)
    and distinct (every element's hash competes) modes, WIDE/int64
    counters, and meshed engines stay on the ungated path.  A ``gated=True``
    bridge in those modes is simply inert — same results, no elision.
    """
    if config.weighted:
        return "weighted mode (A-ExpJ must see every weight)"
    if config.distinct:
        return "distinct mode (every element's hash competes)"
    if config.count_dtype == "wide":
        return "WIDE counters (gate replica is int32-narrow)"
    if np.dtype(config.count_dtype) != np.int32:
        return f"count_dtype {config.count_dtype!r} (gate replica is int32)"
    if config.mesh_axis is not None:
        return "meshed engine (gated dispatch is single-device)"
    return None


class GateEval(NamedTuple):
    """One chunk's gate verdict (host arrays, per reservoir row).  Carries
    the post-chunk replica state UNCOMMITTED — the caller commits when it
    takes a gated path, or discards when it routes the chunk to the
    staged path (whose flushes re-evaluate in tile-sized pieces)."""

    pos: np.ndarray    #: [S, cap] int32 accept positions (first n_acc valid)
    fill: np.ndarray   #: [S] int32 fill-phase prefix lengths
    n_acc: np.ndarray  #: [S] int32 acceptance counts in the chunk
    n_cand: np.ndarray  #: [S] int32 fill + n_acc
    fallback: bool     #: some evaluated row's candidates overflow the tile
    state: tuple       #: (count, nxt, log_w) jax CPU arrays post-chunk


def _build_eval(k: int, cap: int):
    """The jitted skip-recursion eval: vmapped over rows, one while_loop
    per row running the SAME `_advance_words` trace the engine's accept
    loop runs — that identity is the whole bit-reconciliation story."""
    import jax
    import jax.numpy as jnp

    from ..ops.algorithm_l import _advance_words

    def one(count, nxt, log_w, k1, k2, m):
        end = count + m

        def cond(carry):
            return carry[1] <= end

        def body(carry):
            pos, nxt_c, log_w_c, n = carry
            p = (nxt_c - count - 1).astype(jnp.int32)
            pos = pos.at[jnp.minimum(n, cap - 1)].set(p)
            _, log_w_n, nxt_n = _advance_words(
                log_w_c, nxt_c, k1, k2, nxt_c, k
            )
            return pos, nxt_n, log_w_n, n + 1

        pos, nxt_f, log_w_f, n_acc = jax.lax.while_loop(
            cond,
            body,
            (jnp.zeros((cap,), jnp.int32), nxt, log_w, jnp.int32(0)),
        )
        f = jnp.clip(k - count, 0, m).astype(jnp.int32)
        return pos, f, n_acc, count + m, nxt_f, log_w_f

    return jax.jit(jax.vmap(one))


class SkipGate:
    """Host-side skip-ahead replica + candidate coalescing buffer for one
    :class:`~reservoir_tpu.stream.bridge.DeviceStreamBridge`.

    Single-writer like the bridge that owns it.  The replica state is
    authoritative only as a *predictor*: the device runs the identical
    recursion over what ships, so a correct replica elides only bytes the
    device would never have touched.  ``resync`` re-pulls the replica from
    the live engine state; the bridge calls it lazily whenever the engine
    was mutated behind the gate's back (construction, ``recover()`` replay,
    ``push_tile``, serve-plane ``reset_rows`` — tracked through
    ``engine.reset_epochs``).
    """

    def __init__(self, num_streams: int, k: int, tile_width: int, dtype,
                 cap: int = 64) -> None:
        if cap <= 0:
            raise ValueError(f"gate_tile must be positive, got {cap}")
        self._S = int(num_streams)
        self._k = int(k)
        self._B = int(tile_width)
        self._cap = int(cap)
        self._dtype = np.dtype(dtype)
        self._dirty = True
        self._seen_resets = -1
        # candidate coalescing buffers: gtile rows fill left-to-right
        # across flushes; gadv counts TOTAL logical elements consumed per
        # row since the last gated dispatch (int64 internally; a dispatch
        # is forced long before the int32 wire format could wrap)
        self._gtile = np.zeros((self._S, self._cap), self._dtype)
        self._gcount = np.zeros(self._S, np.int64)
        self._gadv = np.zeros(self._S, np.int64)
        self._cols = np.arange(self._cap, dtype=np.int32)[None, :]
        self._rows = np.arange(self._S, dtype=np.int32)[:, None]
        key = (self._k, self._cap)
        fn = _EVAL_CACHE.get(key)
        if fn is None:
            fn = _EVAL_CACHE[key] = _build_eval(self._k, self._cap)
        self._eval_fn = fn
        self._count = self._nxt = self._logw = None
        self._k1 = self._k2 = None

    # ------------------------------------------------------------ properties

    @property
    def cap(self) -> int:
        """Gate-tile width: max candidates bufferable per row."""
        return self._cap

    def pending(self) -> bool:
        """Whether any consumed-but-undispatched advance is buffered."""
        return bool(self._gadv.any())

    def advance_high(self) -> bool:
        """Buffered advance nearing the int32 wire format — force a
        dispatch (unreachable in practice: 2^30 elements per row between
        dispatches)."""
        return bool(self._gadv.max(initial=0) >= (1 << 30))

    # --------------------------------------------------------------- replica

    def stale(self, engine) -> bool:
        """True when the replica no longer mirrors the engine (never
        synced, or rows were reset behind the gate's back)."""
        return self._dirty or engine.reset_epochs != self._seen_resets

    def mark_dirty(self) -> None:
        """The engine was mutated outside the gated flush path
        (``push_tile``, recovery replay): re-pull before the next eval."""
        self._dirty = True

    def resync(self, engine) -> None:
        """Re-pull ``(count, nxt, log_w, key)`` from the live engine state.

        The caller must hold the engine's single-writer slot (the bridge
        drains its pipeline first) and must have dispatched any pending
        gated buffer — buffered candidates predate the state being pulled.
        """
        import jax
        import jax.random as jr

        if self.pending():
            raise RuntimeError(
                "resync with a pending gated buffer would reorder the "
                "stream; dispatch it first"
            )
        state = engine._state
        cpu = jax.devices("cpu")[0]
        kd = np.asarray(jr.key_data(state.key))
        stage = {
            "count": np.asarray(state.count),
            "nxt": np.asarray(state.nxt),
            "logw": np.asarray(state.log_w),
            "k1": np.ascontiguousarray(kd[..., 0]),
            "k2": np.ascontiguousarray(kd[..., 1]),
        }
        placed = jax.device_put(stage, cpu)
        self._count, self._nxt, self._logw = (
            placed["count"], placed["nxt"], placed["logw"]
        )
        self._k1, self._k2 = placed["k1"], placed["k2"]
        self._seen_resets = engine.reset_epochs
        self._dirty = False

    def evaluate(self, valid: np.ndarray) -> GateEval:
        """Run the skip recursion over one chunk of ``valid[r]`` elements
        per row (one vmapped call); returns the candidate verdict WITHOUT
        committing — pair with :meth:`commit` on the path that actually
        consumes the chunk at this granularity.  Rows with ``valid[r] ==
        0`` are untouched."""
        import jax

        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            m = jax.device_put(np.ascontiguousarray(valid, np.int32), cpu)
            pos, f, n_acc, count, nxt, logw = self._eval_fn(
                self._count, self._nxt, self._logw, self._k1, self._k2, m
            )
        pos = np.asarray(pos)
        f = np.asarray(f)
        n_acc = np.asarray(n_acc)
        n_cand = f + n_acc
        return GateEval(
            pos, f, n_acc, n_cand, bool((n_cand > self._cap).any()),
            (count, nxt, logw),
        )

    def evaluate_row(self, row: int, m: int) -> GateEval:
        """:meth:`evaluate` for a single row's contiguous chunk of ``m``
        elements — the pre-staging push fast path: a row-major producer's
        chunk is gated BEFORE any demux/staging copy ever happens."""
        valid = np.zeros(self._S, np.int32)
        valid[row] = m
        return self.evaluate(valid)

    def commit(self, ev: GateEval) -> None:
        """Adopt the post-chunk replica state: the evaluated chunk is now
        CONSUMED (buffered gated, dispatched gated, or shipped whole as an
        ungated fallback — every path runs the same recursion device-side)."""
        self._count, self._nxt, self._logw = ev.state

    # --------------------------------------------------------------- buffers

    def fits(self, ev: GateEval) -> bool:
        """Whether this eval's candidates fit the remaining buffer room."""
        return bool(((self._gcount + ev.n_cand) <= self._cap).all())

    def fits_row(self, row: int, ev: GateEval) -> bool:
        return bool(self._gcount[row] + ev.n_cand[row] <= self._cap)

    def append_row(self, row: int, chunk: np.ndarray, ev: GateEval) -> int:
        """Gather one row-chunk's candidates straight from the producer's
        array (no staging copy); returns the elided element count.
        Caller guarantees ``fits_row`` and ``ev.n_cand[row] <= cap``."""
        f = int(ev.fill[row])
        na = int(ev.n_acc[row])
        nc = f + na
        if nc:
            idx = np.concatenate(
                [np.arange(f, dtype=np.int64), ev.pos[row, :na]]
            ) if f else ev.pos[row, :na]
            at = int(self._gcount[row])
            self._gtile[row, at:at + nc] = chunk[idx]
            self._gcount[row] += nc
        self._gadv[row] += chunk.size
        return int(chunk.size) - nc

    def append(self, tile: np.ndarray, valid: np.ndarray, ev: GateEval) -> int:
        """Gather the candidates of ``tile`` into the coalescing buffer;
        returns the number of ELIDED elements (staged minus candidates).
        Caller guarantees ``fits(ev)`` and ``not ev.fallback``."""
        n_cand = ev.n_cand
        total_cand = int(n_cand.sum())
        total = int(np.asarray(valid).sum())
        if total_cand:
            # gather index per (row, slot): fill prefix positions 0..f-1,
            # then the accept positions — one vectorized fancy-gather
            f = ev.fill[:, None]
            acc_j = np.minimum(
                np.maximum(self._cols - f, 0), self._cap - 1
            )
            gidx = np.where(self._cols < f, self._cols, ev.pos[self._rows, acc_j])
            mask = self._cols < n_cand[:, None]
            vals = np.take_along_axis(
                tile, np.clip(gidx, 0, self._B - 1), axis=1
            )
            rsel, csel = np.nonzero(mask)
            self._gtile[rsel, self._gcount[rsel] + csel] = vals[rsel, csel]
            self._gcount += n_cand
        self._gadv += np.asarray(valid, np.int64)
        return total - total_cand

    def take(self):
        """Snapshot-and-reset the coalescing buffer for dispatch: returns
        ``(gtile, nvalid, advance, total_advance)`` as fresh arrays (safe
        to hand to the flush pipeline and the journal)."""
        gtile = self._gtile.copy()
        nvalid = self._gcount.astype(np.int32)
        advance = self._gadv.astype(np.int32)
        total_adv = int(self._gadv.sum())
        self._gcount[:] = 0
        self._gadv[:] = 0
        return gtile, nvalid, advance, total_adv
