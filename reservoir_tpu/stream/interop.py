"""JVM/Akka interop: a socket server that backs the reference's ``Sample``
stage with this framework's samplers (north-star clause, BASELINE.json).

The reference's operator is an Akka ``GraphStage``
(``akka-stream/.../Sample.scala:21-92``); this framework's native operator
is :mod:`reservoir_tpu.stream.operator`.  For *existing Akka flows*, the
bridge is this server plus the JVM-side shim stage in
``examples/akka_interop/TpuSample.scala``: the stage keeps every Akka
semantic locally (pass-through emit, backpressure, completion protocol,
``SampleImpl.scala:27-57``) and delegates only the *sampling state* over a
socket — ``sampler.sample(elem)`` becomes a buffered frame write, and
``result()`` a final round-trip.  TCP flow control IS the backpressure
coupling: if this server stalls, the stage's writes block and the stage
backpressures its upstream, exactly like a slow in-process sampler.

Wire protocol (all integers big-endian):

  handshake  C->S:  magic ``RSV1`` | mode u8 (0 dup, 1 distinct) | k u32
  frames     C->S:  ``B`` | count u32 | count x i64     (sample_all batch)
             C->S:  ``C``                               (upstream complete)
             C->S:  ``F``                               (failure/cancel-with-
                                                         cause: discard)
  result     S->C:  ``R`` | size u32 | size x i64       (reply to ``C``)
             S->C:  ``A``                               (reply to ``F``)

The completion protocol maps 1:1 onto ``SampleImpl.scala``'s:
``onUpstreamFinish``/graceful ``onDownstreamFinish`` send ``C`` (deliver
the sample, ``:38-41, 48-52``); ``onUpstreamFailure``/cancel-with-cause
send ``F`` (``:43-46, 53-54``); dropping the connection without either is
the ``postStop`` abrupt-termination analog (``:56-57``) — the server
discards the partial sample.

Elements are i64 on the wire (the ``Sampler[Long, Long]`` shape of
BASELINE config 1).  ``map``/``hash`` hooks stay JVM-side: the shim
applies ``map`` to the *returned* elements, which yields identical
results for pure functions but calls ``map`` once per result element
instead of once per accept — the one observable deviation, documented in
ARCHITECTURE.md.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Callable, Optional

import numpy as np

__all__ = ["SampleServer"]

_MAGIC = b"RSV1"

# Largest batch a single ``B`` frame may carry (ADVICE r3 #3): the wire
# count is untrusted u32, and without a cap a corrupt/malicious header
# could demand an 8*2^32 ~= 32 GiB allocation.  2^24 elements (128 MiB)
# is far beyond any sane shim flush (the JVM stage flushes ~2^16).
MAX_FRAME_ELEMS = 1 << 24

# Largest ``k`` a handshake may request, for the same reason: samplers
# preallocate O(k) state, so an untrusted u32 k near MAX_SIZE (2^31-3
# passes eager validation) would OOM the server from a few wire bytes.
MAX_HANDSHAKE_K = 1 << 24


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    # preallocated buffer + recv_into: O(n) for large frames (``bytes``
    # concatenation re-copies the prefix per chunk, O(n^2))
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("peer closed mid-frame")
        got += r
    # hand the bytearray back as-is: every consumer (slice compare,
    # struct.unpack, np.frombuffer) takes the buffer protocol, and a
    # bytes() round-trip would re-copy each max-size frame
    return buf


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # one connection == one materialization
        sock = self.request
        head = _recv_exact(sock, len(_MAGIC) + 1 + 4)
        if head[: len(_MAGIC)] != _MAGIC:
            sock.close()
            return
        mode = head[len(_MAGIC)]
        (k,) = struct.unpack(">I", head[len(_MAGIC) + 1 :])
        if k > MAX_HANDSHAKE_K:
            sock.close()  # untrusted k: refuse before any O(k) allocation
            return
        sampler = self.server._make_sampler(mode, k)  # type: ignore[attr-defined]
        try:
            while True:
                tag = _recv_exact(sock, 1)
                if tag == b"B":
                    (count,) = struct.unpack(">I", _recv_exact(sock, 4))
                    if count > MAX_FRAME_ELEMS:
                        raise ConnectionError(
                            f"batch frame of {count} elements exceeds "
                            f"MAX_FRAME_ELEMS={MAX_FRAME_ELEMS}"
                        )
                    data = _recv_exact(sock, 8 * count)
                    elems = np.frombuffer(data, dtype=">i8").astype(np.int64)
                    sampler.sample_all(elems)
                elif tag == b"C":
                    res = np.asarray(sampler.result(), dtype=np.int64)
                    sock.sendall(
                        b"R"
                        + struct.pack(">I", res.shape[0])
                        + res.astype(">i8").tobytes()
                    )
                    return
                elif tag == b"F":
                    # failure/cancel-with-cause: discard the partial sample
                    # (the future fails JVM-side, SampleImpl.scala:43-46)
                    sock.sendall(b"A")
                    return
                else:
                    raise ConnectionError(f"unknown frame tag {tag!r}")
        except ConnectionError:
            # abrupt termination (postStop analog): nothing to deliver
            return


class SampleServer:
    """Serve reference-``Sample`` materializations over TCP.

    One connection per stream materialization; each gets a FRESH sampler
    from ``sampler_factory(mode, k)`` (the by-name-thunk semantics of
    ``Sample.scala:23-24``).  The default factory uses the host samplers
    (:mod:`reservoir_tpu.api`); pass a factory returning a
    :class:`~reservoir_tpu.stream.bridge.DeviceSampler` to put the
    sampling state on the TPU.

    Usage::

        with SampleServer() as srv:        # srv.address -> ("127.0.0.1", p)
            ...  # point the JVM shim at srv.address and run the Akka graph
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        sampler_factory: Optional[Callable[[int, int], object]] = None,
    ) -> None:
        self._factory = sampler_factory or self._default_factory
        self._server = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True
        )
        self._server.daemon_threads = True
        self._server._make_sampler = self._factory  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    @staticmethod
    def _default_factory(mode: int, k: int):
        from .. import api

        return api.distinct(k) if mode == 1 else api.sampler(k)

    @property
    def address(self):
        return self._server.server_address

    def start(self) -> "SampleServer":
        self._thread.start()
        return self

    def close(self) -> None:
        # shutdown() blocks on an event only serve_forever() sets — calling
        # it when start() never ran would deadlock (ADVICE r3 #4)
        if self._thread.is_alive():
            self._server.shutdown()
        self._server.server_close()

    def __enter__(self) -> "SampleServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
